"""Offline analysis of a tracer spill: Chrome/Perfetto ``trace_event``
export plus the terminal reports behind ``python -m ddp_tpu.obs``.

A spill file (``--trace_spill``; obs/tracer.py) is append-only JSON lines
``{"phase", "step", "start_s", "dur_s", "overlap", "host"}``.  Multi-host
runs write one spill per host (rank suffixes); :func:`read_spill` merges
any number of them into one timeline.

Perfetto export (:func:`to_trace_events`) renders the run as the
``trace_event`` JSON format both ``chrome://tracing`` and
``ui.perfetto.dev`` load: one *process* per host, one *track* (thread)
per phase, complete ``"X"`` duration events carrying the step number in
``args`` — the per-step phase timeline MPMD-pipeline papers lean on for
straggler/overlap forensics (PAPERS.md, arxiv 2412.14374).
:func:`validate_trace_events` checks the documented schema subset and is
what CI runs against every exported trace.

Report semantics: ``overlap=True`` spans ran on producer threads
(prefetch workers, the async checkpoint writer) concurrently with the
consumer loop, so the wall-time identity only holds over *non-overlap*
spans — :func:`phase_summary` keeps the two ledgers separate and
reports the non-overlap sum as a fraction of wall (the acceptance
check: within 10% on a default CPU-box run).
"""
from __future__ import annotations

import json
import statistics
from typing import Dict, Iterable, List, Optional, Tuple

# Canonical phase order: consumer-loop phases first in pipeline order,
# then the boundary/background phases, then the serving engine's batch
# pipeline (ddp_tpu/serve/ — queue_wait is per-request and overlap=True;
# batch_form..d2h are the engine thread's serial stages, sharing "h2d"
# with the training pipeline).  Unknown phases sort after these (the
# tracer accepts free-form names).
PHASE_ORDER = ("data_wait", "host_augment", "h2d", "dispatch",
               "loss_flush", "drift_audit", "ckpt_write", "ckpt_upload",
               "eval",
               "queue_wait", "batch_form", "pad", "forward", "d2h",
               # Fleet/router phases (serve/router.py, serve/fleet.py):
               # route/retry are per-request handler-thread spans
               # (overlap=True); eject/readmit mark rotation changes and
               # swap_warm/swap_commit bracket a checkpoint hot-swap —
               # none is per-step (a request is not a batch sequence).
               "route", "retry", "eject", "readmit",
               "swap_warm", "swap_commit")

# Phases attributable to ONE step each — the per-step wall decomposition
# the histogram and slowest-K tables are built from.  Boundary phases
# (loss_flush covers a whole epoch's steps, ckpt_write/eval a whole
# epoch) stay in the phase table but not in per-step grouping.  On serve
# spills a "step" is one formed batch (the engine's sequence number), so
# the serving stages join the set — the two workloads never mix phases
# in one spill, so neither pollutes the other's decomposition.
PER_STEP_PHASES = frozenset(("data_wait", "host_augment", "h2d",
                             "dispatch",
                             "batch_form", "pad", "forward", "d2h"))


def _phase_rank(phase: str) -> tuple:
    try:
        return (PHASE_ORDER.index(phase), phase)
    except ValueError:
        return (len(PHASE_ORDER), phase)


def read_spill(paths: Iterable[str]) -> List[dict]:
    """Merge one or more spill files into one start-sorted span list.
    Torn tails (a final partial line from a SIGKILL mid-write) are
    skipped, not fatal — a telemetry reader must not die on the exact
    runs it exists to explain."""
    spans: List[dict] = []
    for path in paths:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue  # torn tail
                if isinstance(rec, dict) and "phase" in rec \
                        and "start_s" in rec and "dur_s" in rec:
                    rec.setdefault("host", 0)
                    rec.setdefault("overlap", False)
                    rec.setdefault("step", None)
                    rec.setdefault("req", None)
                    spans.append(rec)
    spans.sort(key=lambda r: r["start_s"])
    return spans


# -- Perfetto / chrome://tracing export -----------------------------------

def to_trace_events(spans: List[dict]) -> dict:
    """``trace_event`` JSON: one process per host, one track per phase.

    Timestamps are microseconds on the tracer's monotonic clock (hosts'
    clocks are independent; cross-host alignment is by step number in
    ``args``, not by wall time — same caveat as any multi-machine trace).

    Request-scoped spans (a ``req`` id minted by the router at admission
    and threaded through route/retry → queue_wait → the joined batch's
    engine stages) additionally emit Perfetto *flow* events — one
    ``s``/``t``.../``f`` chain per request id, each bound to its slice —
    so one request renders as a single connected arrow path across
    replica tracks, including a crash→retry hand-off between replicas.
    """
    hosts = sorted({int(s["host"]) for s in spans})
    phases = sorted({s["phase"] for s in spans}, key=_phase_rank)
    tid_of = {p: i + 1 for i, p in enumerate(phases)}
    events: List[dict] = []
    for h in hosts:
        events.append({"name": "process_name", "ph": "M", "pid": h,
                       "tid": 0, "args": {"name": f"host {h}"}})
        for p in phases:
            events.append({"name": "thread_name", "ph": "M", "pid": h,
                           "tid": tid_of[p], "args": {"name": p}})
    slice_of: Dict[int, dict] = {}
    for s in spans:
        args = {"overlap": bool(s["overlap"])}
        if s.get("step") is not None:
            args["step"] = int(s["step"])
        if s.get("req") is not None:
            args["req"] = str(s["req"])
        ev = {
            "name": s["phase"], "cat": "train", "ph": "X",
            "ts": round(float(s["start_s"]) * 1e6, 3),
            "dur": round(max(float(s["dur_s"]), 0.0) * 1e6, 3),
            "pid": int(s["host"]), "tid": tid_of[s["phase"]],
            "args": args,
        }
        slice_of[id(s)] = ev
        events.append(ev)
    # One flow chain per request: parent/child links between the slices
    # the request passed through, in time order.  The flow event binds
    # to its slice via matching pid/tid and a ts inside the slice.
    for fid, (req, chain) in enumerate(
            sorted(request_chains(spans).items()), start=1):
        if len(chain) < 2:
            continue  # a single-span request has nothing to connect
        for j, s in enumerate(chain):
            ev = slice_of[id(s)]
            ph = "s" if j == 0 else ("f" if j == len(chain) - 1 else "t")
            fev = {"name": f"req {req}", "cat": "request", "ph": ph,
                   "id": fid, "pid": ev["pid"], "tid": ev["tid"],
                   "ts": round(ev["ts"] + ev["dur"] / 2.0, 3)}
            if ph == "f":
                fev["bp"] = "e"  # bind the finish to the enclosing slice
            events.append(fev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def validate_trace_events(trace: dict) -> int:
    """Schema check of the ``trace_event`` subset :func:`to_trace_events`
    emits — the CI gate that an exported file will load in
    ``ui.perfetto.dev``.  Returns the number of events; raises
    ``ValueError`` naming the first offending event otherwise."""
    if not isinstance(trace, dict) or "traceEvents" not in trace:
        raise ValueError("trace_event JSON must be an object with a "
                         "'traceEvents' array")
    events = trace["traceEvents"]
    if not isinstance(events, list) or not events:
        raise ValueError("'traceEvents' must be a non-empty array")
    for i, ev in enumerate(events):
        def bad(why: str):
            return ValueError(f"traceEvents[{i}] {why}: {ev!r}")
        if not isinstance(ev, dict):
            raise bad("is not an object")
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            raise bad("needs a non-empty string 'name'")
        ph = ev.get("ph")
        if ph not in ("X", "M", "s", "t", "f"):
            raise bad("has unsupported 'ph' (this exporter emits X/M "
                      "slices and s/t/f flow events only)")
        if not isinstance(ev.get("pid"), int) or ev["pid"] < 0:
            raise bad("needs a non-negative integer 'pid'")
        if not isinstance(ev.get("tid"), int) or ev["tid"] < 0:
            raise bad("needs a non-negative integer 'tid'")
        if ph == "X":
            for key in ("ts", "dur"):
                v = ev.get(key)
                if not isinstance(v, (int, float)) or v < 0:
                    raise bad(f"needs a non-negative numeric {key!r}")
        if ph in ("s", "t", "f"):
            if not isinstance(ev.get("id"), (int, str)):
                raise bad("flow events need an 'id' linking the chain")
            v = ev.get("ts")
            if not isinstance(v, (int, float)) or v < 0:
                raise bad("needs a non-negative numeric 'ts'")
        if "args" in ev and not isinstance(ev["args"], dict):
            raise bad("'args' must be an object")
    return len(events)


def write_perfetto(spans: List[dict], out_path: str) -> int:
    """Export + self-validate + write; returns the event count."""
    trace = to_trace_events(spans)
    n = validate_trace_events(trace)
    with open(out_path, "w") as f:
        json.dump(trace, f)
    return n


# -- request-scoped reconstruction ----------------------------------------

# Spans a request passes through directly (they carry its ``req`` id)
# versus the engine-thread stages it joins via the formed batch's
# sequence number (``step`` on a serve spill).
BATCH_PHASES = ("batch_form", "pad", "h2d", "forward", "d2h")


def request_chains(spans: List[dict]) -> Dict[str, List[dict]]:
    """``{req: [span, ...]}`` — every span a request passed through, in
    time order: its own route/retry/queue_wait spans plus the engine
    stages of each batch its ``queue_wait`` joined (matched on the
    global batch sequence number, which is unique across replicas and
    across checkpoint hot-swaps — serve/engine.py mints it from one
    process-wide counter exactly so this join is unambiguous)."""
    by_req: Dict[str, List[dict]] = {}
    for s in spans:
        if s.get("req") is not None:
            by_req.setdefault(str(s["req"]), []).append(s)
    if not by_req:
        return {}
    by_step: Dict[int, List[dict]] = {}
    for s in spans:
        if (s.get("req") is None and s.get("step") is not None
                and s["phase"] in BATCH_PHASES):
            by_step.setdefault(int(s["step"]), []).append(s)
    chains: Dict[str, List[dict]] = {}
    for req, own in by_req.items():
        steps = sorted({int(s["step"]) for s in own
                        if s.get("step") is not None
                        and s["phase"] == "queue_wait"})
        joined = list(own)
        for st in steps:
            joined.extend(by_step.get(st, []))
        joined.sort(key=lambda r: (r["start_s"], _phase_rank(r["phase"])))
        chains[req] = joined
    return chains


def request_flows(spans: List[dict]) -> Dict[str, dict]:
    """Per-request hop breakdown: total latency, retry count, and the
    batch step(s) it rode — the offline answer to "where did this p99
    request go"."""
    out: Dict[str, dict] = {}
    for req, chain in request_chains(spans).items():
        start = min(s["start_s"] for s in chain)
        end = max(s["start_s"] + s["dur_s"] for s in chain)
        out[req] = {
            "hops": [{"phase": s["phase"],
                      "start_s": round(float(s["start_s"]), 6),
                      "dur_ms": float(s["dur_s"]) * 1e3,
                      "step": s.get("step"),
                      "host": int(s.get("host", 0))} for s in chain],
            "total_ms": (end - start) * 1e3,
            "retries": sum(1 for s in chain if s["phase"] == "retry"),
            "batch_steps": sorted({
                int(s["step"]) for s in chain
                if s.get("step") is not None
                and s["phase"] in BATCH_PHASES + ("queue_wait",)}),
        }
    return out


def slowest_requests(spans: List[dict], k: int = 10
                     ) -> List[Tuple[str, dict]]:
    flows = request_flows(spans)
    return sorted(flows.items(), key=lambda kv: kv[1]["total_ms"],
                  reverse=True)[:max(k, 0)]


def format_requests_report(spans: List[dict], top: int = 10) -> str:
    """The ``python -m ddp_tpu.obs --requests`` table: slowest-K requests
    with their per-hop breakdown."""
    flows = request_flows(spans)
    if not flows:
        return ("no request-scoped spans in the spill (req ids are "
                "minted by the serve router; train spills have none)")
    lines = [f"{len(flows)} request(s); slowest {min(top, len(flows))}:"]
    for req, f in slowest_requests(spans, top):
        lines.append(
            f"  {req}: {f['total_ms']:9.3f} ms total, "
            f"{f['retries']} retries, batch step(s) "
            f"{','.join(map(str, f['batch_steps'])) or '-'}")
        lines.append("    " + " -> ".join(
            f"{h['phase']}"
            + (f"@{h['step']}" if h["step"] is not None else "")
            + f" {h['dur_ms']:.3f}ms" for h in f["hops"]))
    return "\n".join(lines)


# -- terminal reports ------------------------------------------------------

def phase_summary(spans: List[dict]) -> Tuple[List[dict], float, float]:
    """Per-phase ledger + the wall identity.

    Returns ``(rows, wall_s, critical_s)``: one row per phase (count,
    total/median/mean ms, overlap flag), the run's wall time (span of
    the whole timeline), and the *critical* sum — total time of
    non-overlap spans only, the quantity comparable to wall (producer
    threads run concurrently and would double-count)."""
    if not spans:
        return [], 0.0, 0.0
    by_phase: Dict[Tuple[str, bool], List[float]] = {}
    for s in spans:
        by_phase.setdefault((s["phase"], bool(s["overlap"])), []).append(
            float(s["dur_s"]))
    rows = []
    for (phase, overlap), durs in sorted(
            by_phase.items(), key=lambda kv: _phase_rank(kv[0][0])):
        rows.append({
            "phase": phase, "overlap": overlap, "count": len(durs),
            "total_ms": sum(durs) * 1e3,
            "median_ms": statistics.median(durs) * 1e3,
            "mean_ms": sum(durs) / len(durs) * 1e3,
        })
    wall_s = (max(s["start_s"] + s["dur_s"] for s in spans)
              - min(s["start_s"] for s in spans))
    critical_s = sum(s["dur_s"] for s in spans if not s["overlap"])
    return rows, wall_s, critical_s


def step_walls(spans: List[dict]) -> Dict[int, Dict[str, float]]:
    """Per-step phase decomposition: ``{step: {phase: ms, "total": ms}}``
    over non-overlap :data:`PER_STEP_PHASES` spans (the consumer loop's
    view of each step).

    Replay-aware: an ``--on_nan restore`` rewinds the step counter and
    the replayed trajectory re-emits spans under the SAME global step
    numbers — seeing a per-step phase repeat for a step starts a fresh
    row, so the report describes the latest trajectory (the same
    last-record-wins rule the metrics JSONL documents for the replay)
    instead of summing both into a fake 2x straggler."""
    out: Dict[int, Dict[str, float]] = {}
    seen: Dict[int, set] = {}
    for s in sorted(spans, key=lambda r: r["start_s"]):
        if (s.get("step") is None or s["overlap"]
                or s["phase"] not in PER_STEP_PHASES):
            continue
        step = int(s["step"])
        phases = seen.setdefault(step, set())
        if s["phase"] in phases:  # replayed trajectory: latest wins
            out[step] = {"total": 0.0}
            phases.clear()
        phases.add(s["phase"])
        row = out.setdefault(step, {"total": 0.0})
        row[s["phase"]] = row.get(s["phase"], 0.0) + s["dur_s"] * 1e3
        row["total"] += s["dur_s"] * 1e3
    return out


def slowest_steps(spans: List[dict], k: int = 10,
                  walls: Optional[Dict[int, Dict[str, float]]] = None
                  ) -> List[Tuple[int, Dict[str, float]]]:
    """Top-``k`` steps by per-step serial wall; pass a precomputed
    ``walls`` (from :func:`step_walls`) to avoid regrouping the spans."""
    if walls is None:
        walls = step_walls(spans)
    return sorted(walls.items(), key=lambda kv: kv[1]["total"],
                  reverse=True)[:max(k, 0)]


def histogram_lines(values: List[float], bins: int = 12,
                    width: int = 40) -> List[str]:
    """ASCII histogram of per-step ms — the one-look distribution check
    (a long tail here IS the straggler signature)."""
    if not values:
        return []
    lo, hi = min(values), max(values)
    if hi <= lo:
        return [f"  {lo:9.3f} ms  all {len(values)} steps identical"]
    bins = max(bins, 1)
    edges = [lo + (hi - lo) * i / bins for i in range(bins + 1)]
    counts = [0] * bins
    for v in values:
        i = min(int((v - lo) / (hi - lo) * bins), bins - 1)
        counts[i] += 1
    peak = max(counts)
    return [
        f"  {edges[i]:9.3f}..{edges[i + 1]:9.3f} ms "
        f"{'#' * max(int(c / peak * width), 1 if c else 0):<{width}} {c}"
        for i, c in enumerate(counts)]


def format_report(spans: List[dict], top: int = 10, bins: int = 12,
                  perfetto_out: Optional[str] = None) -> str:
    """The full terminal report ``python -m ddp_tpu.obs`` prints.

    Multi-host spills are reported PER HOST: each host's spans share one
    clock (its own tracer t0) and its serial lanes tile its own wall —
    pooling hosts would double-count every identity (two hosts' serial
    dispatch sums against one wall reads as ~200%) and merge unrelated
    per-step totals under colliding step numbers.  The Perfetto export
    is the one place the hosts land side by side (one process per host).
    """
    if not spans:
        return "no spans found in the spill file(s)"
    hosts = sorted({int(s["host"]) for s in spans})
    lines: List[str] = [f"{len(spans)} spans, {len(hosts)} host(s)"]
    for host in hosts:
        lines.extend(_format_host_report(
            [s for s in spans if int(s["host"]) == host],
            host=host, top=top, bins=bins, multi=len(hosts) > 1))
    if perfetto_out:
        n = write_perfetto(spans, perfetto_out)
        lines.append("")
        lines.append(f"wrote Perfetto trace_event JSON: {perfetto_out} "
                     f"({n} events) — open in ui.perfetto.dev")
    return "\n".join(lines)


def _format_host_report(spans: List[dict], *, host: int, top: int,
                        bins: int, multi: bool) -> List[str]:
    rows, wall_s, critical_s = phase_summary(spans)
    if not rows:
        return []
    lines: List[str] = [""]
    if multi:
        lines.append(f"=== host {host}: {len(spans)} spans, "
                     f"wall {wall_s:.3f} s ===")
    else:
        lines.append(f"wall {wall_s:.3f} s")
    lines.append(f"{'phase':<16} {'lane':<8} {'count':>7} {'total ms':>12} "
                 f"{'median ms':>11} {'mean ms':>11} {'% wall':>7}")
    for r in rows:
        share = r["total_ms"] / (wall_s * 1e3) * 100.0 if wall_s else 0.0
        lines.append(
            f"{r['phase']:<16} {'overlap' if r['overlap'] else 'serial':<8} "
            f"{r['count']:>7} {r['total_ms']:>12.2f} "
            f"{r['median_ms']:>11.3f} {r['mean_ms']:>11.3f} {share:>6.1f}%")
    pct = critical_s / wall_s * 100.0 if wall_s else 0.0
    lines.append("")
    lines.append(f"phase sum (serial lanes): {critical_s * 1e3:.1f} ms = "
                 f"{pct:.1f}% of wall {wall_s * 1e3:.1f} ms")
    walls = step_walls(spans)
    if walls:
        lines.append("")
        lines.append(f"step-time histogram ({len(walls)} steps, per-step "
                     f"serial phases {sorted(PER_STEP_PHASES)}):")
        lines.extend(histogram_lines([w["total"] for w in walls.values()],
                                     bins=bins))
        lines.append("")
        lines.append(f"slowest {min(top, len(walls))} steps:")
        for step, row in slowest_steps(spans, top, walls=walls):
            detail = " ".join(
                f"{p}={row[p]:.3f}" for p in sorted(
                    row, key=_phase_rank) if p != "total")
            lines.append(f"  step {step:>8}: {row['total']:9.3f} ms "
                         f"({detail})")
    return lines
