"""Memory ledger — measured per-program memory watermarks joined against
the static liveness predictions (the memory twin of obs/ledger.py).

``analysis/liveness.py`` prices every registry program's peak live bytes
per shard, and the auto-plan search prunes candidates on those numbers —
but until now they were never validated against a measured watermark,
the way the time cost model is validated by the efficiency ledger.  This
module closes that loop:

- **Predicted**: trace each program (abstract — no device memory) and
  scale the per-shard ``peak_live_bytes`` by the shard count; on the
  virtual CPU mesh every shard lives in ONE process, so the whole-process
  watermark is the per-shard peak summed over devices.  Replication is
  what makes the orderings measurable here: 1-D data parallelism holds
  R param copies in the process, TP holds ~R/m, ZeRO holds one optimizer
  slice instead of R — real host bytes, not annotations.
- **Measured**: run the REAL jitted program with concrete, properly
  placed arguments and read the runtime's own numbers — device
  ``memory_stats()['peak_bytes_in_use']`` where the backend keeps one
  (TPU/GPU); on this CPU box the exact per-device committed buffer
  bytes after the step (summed over every live array's addressable
  shards — a replicated param tree costs one full copy PER device,
  which is precisely what the sharding claims are about) plus the
  child-process ``ru_maxrss`` watermark (the same probe family as
  ``ckpt_shard.HostBytesProbe``).  One program per child process:
  ``ru_maxrss`` is a process-lifetime high-water mark, and a second
  in-process measurement would inherit the first one's peak.

The join basis matters.  The raw RSS watermark is dominated by XLA's
compile arena (measured here: a TP step's heavier compile swamps the
~100 MiB the sharding saves), so the gap percentages join the measured
committed bytes against the liveness report's BOUNDARY decomposition —
the post-step resident set ``inputs + max(0, outputs - donated)`` per
shard, scaled by the shard count.  ``peak_live_bytes`` (transients
included) and the RSS watermark are both recorded per row for the HBM
headroom question; the ORDERINGS are asserted on the measured committed
bytes, where they are decided by real replication, not by allocator
noise.

``bench.py --mem_ledger`` drives one child per program
(``--mem_ledger_child`` is the child entry), joins the two sides into
per-program gap percentages (BENCH_r14.json), and asserts the static
orderings — TP < 1-D, ZeRO < non-ZeRO — hold on the MEASURED numbers,
not just the predicted ones.
"""
from __future__ import annotations

import json
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# The update family plus one forward: the programs whose memory behavior
# the sharding claims are about.  Accum variants share their base
# program's state layout and double the child fleet for no new ordering
# information — excluded by default, selectable via --programs.
DEFAULT_PROGRAMS = (
    "train_step@dp8",
    "train_step_zero@dp8",
    "train_step@tp",
    "train_step_zero@tp",
    "serve_forward@dp8",
)

# (smaller, larger): the static orderings that must hold on measured
# watermarks.  TP shards params over the model axis (fewer replicated
# copies in the process); ZeRO shards the optimizer state.
ORDERINGS: Tuple[Tuple[str, str], ...] = (
    ("train_step@tp", "train_step@dp8"),          # TP < 1-D
    ("train_step_zero@dp8", "train_step@dp8"),    # ZeRO < non-ZeRO (1-D)
    ("train_step_zero@tp", "train_step@tp"),      # ZeRO < non-ZeRO (TP)
)


def predict(model_name: str, mesh_2d: Tuple[int, int],
            names: Optional[Sequence[str]] = None) -> Dict[str, dict]:
    """Static predictions per program: the per-shard liveness report plus
    the whole-process projection (``predicted_total_bytes`` = per-shard
    peak x shard count).  Abstract tracing only — safe in the parent."""
    import jax

    from ..analysis.liveness import liveness_of
    from ..analysis.programs import build_context, build_programs
    ctx = build_context(model_name, mesh_2d)
    out: Dict[str, dict] = {}
    n_shards = int(mesh_2d[0]) * int(mesh_2d[1])
    for p in build_programs(ctx, list(names) if names else None):
        closed = jax.make_jaxpr(p.fn)(*p.args)
        live = liveness_of(closed)
        # The post-step resident set per shard: non-donated inputs stay
        # owned by the caller, outputs survive, and donated inputs are
        # recycled INTO the outputs (an update's new state aliases the
        # old one's buffers) — so outputs only cost what donation didn't
        # already pay for.
        resident = (live["input_bytes"]
                    + max(0, live["output_bytes"]
                          - live["donated_input_bytes"]))
        out[p.name] = {
            **live,
            "n_shards": n_shards,
            "predicted_peak_total_bytes":
                int(live["peak_live_bytes"]) * n_shards,
            "predicted_resident_bytes": int(resident) * n_shards,
        }
    return out


def _concretize(args):
    """Materialise a program's abstract example args: zeros per
    ShapeDtypeStruct, a real PRNG key for key-dtype leaves (zeros cannot
    carry an extended dtype)."""
    import jax
    import jax.numpy as jnp

    def one(leaf):
        try:
            if jax.dtypes.issubdtype(leaf.dtype, jax.dtypes.prng_key):
                return jax.random.key(0)
        except (AttributeError, TypeError):
            pass
        return jnp.zeros(leaf.shape, leaf.dtype)

    return jax.tree_util.tree_map(one, args)


def _place(p, ctx, args):
    """Place concrete args the way the trainer would: under a TP plan the
    state/params must already sit on the plan's shardings — the jitted
    update aliases donated inputs to sharded outputs, so an unplaced
    replicated state fails at dispatch (exactly the placement
    trainer.py does via ``state_shardings`` before training)."""
    if p.plan is None:
        return args  # 1-D programs: jit places replicated/auto inputs
    import jax
    from jax.sharding import NamedSharding

    from ..parallel.tp.plan import state_shardings
    mesh = ctx.mesh2d
    sh = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    if p.kind == "update":
        state = jax.device_put(
            args[0], state_shardings(p.plan, mesh, zero=p.zero))
        return (state,) + tuple(args[1:])
    if p.kind in ("eval", "forward"):
        params = jax.device_put(
            args[0], jax.tree_util.tree_map(sh, p.plan.param_specs))
        stats = jax.device_put(
            args[1], jax.tree_util.tree_map(sh, p.plan.stats_specs))
        return (params, stats) + tuple(args[2:])
    return args


def _ru_maxrss_bytes() -> int:
    """Process high-water RSS in bytes (Linux reports KiB)."""
    import resource
    import sys
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    return int(rss) * (1 if sys.platform == "darwin" else 1024)


def live_shard_bytes() -> int:
    """Exact committed device-buffer bytes in this process right now:
    every live array's addressable shards summed — a replicated array on
    R virtual devices costs R full copies, a sharded one costs its
    slices.  The CPU-backend analogue of ``bytes_in_use``."""
    import jax
    total = 0
    for arr in jax.live_arrays():
        try:
            if arr.is_deleted():  # donated inputs: buffers recycled
                continue
            total += sum(s.data.nbytes for s in arr.addressable_shards)
        except Exception:
            continue
    return total


def device_watermark_bytes() -> Optional[int]:
    """Sum of per-device ``peak_bytes_in_use`` when the backend keeps
    memory stats (TPU/GPU); None on backends that don't (CPU)."""
    import jax
    total, seen = 0, False
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        if stats and "peak_bytes_in_use" in stats:
            total += int(stats["peak_bytes_in_use"])
            seen = True
    return total if seen else None


def measure_in_process(name: str, model_name: str,
                       mesh_2d: Tuple[int, int]) -> dict:
    """Measure ONE program's watermark in THIS process — the child-side
    body of ``bench.py --mem_ledger_child``.  Baseline is taken after
    imports + model init (shared fixed cost), so the delta attributes the
    program's own state materialisation, compile and execution."""
    import jax

    from ..analysis.programs import build_context, build_programs
    ctx = build_context(model_name, mesh_2d)
    progs = build_programs(ctx, [name])
    if not progs:
        raise SystemExit(f"program {name!r} not buildable in this "
                         f"context (no TP plan / no committed auto plan?)")
    p = progs[0]
    baseline = _ru_maxrss_bytes()
    args = _concretize(p.args)
    args = _place(p, ctx, args)
    out = p.fn(*args)
    jax.block_until_ready(out)
    measured_rss = _ru_maxrss_bytes() - baseline
    shard_bytes = live_shard_bytes()
    dev = device_watermark_bytes()
    return {
        "program": name,
        "source": ("device_memory_stats" if dev is not None
                   else "live_shard_bytes"),
        # The runtime's own committed device bytes: a true watermark on
        # backends with memory_stats, the post-step committed floor on
        # CPU (live per-device shard bytes; `out` and the non-donated
        # args are still referenced here, so the resident set is whole).
        "measured_bytes": int(dev if dev is not None else shard_bytes),
        "live_shard_bytes": int(shard_bytes),
        "host_watermark_bytes": int(measured_rss),
        "baseline_rss_bytes": int(baseline),
        "value": 1,  # sentinel key: bench._run_child picks this line
    }


def join(predicted: Dict[str, dict],
         measured: Iterable[dict]) -> List[dict]:
    """Per-program ledger rows: measured committed bytes vs the
    predicted resident set, gap percentage
    ((measured - predicted) / predicted x 100)."""
    rows: List[dict] = []
    for m in measured:
        name = m["program"]
        pred = predicted.get(name)
        if pred is None:
            continue
        basis = pred["predicted_resident_bytes"]
        gap = ((m["measured_bytes"] - basis) / basis * 100.0) \
            if basis else None
        rows.append({
            "program": name,
            "predicted_peak_shard_bytes": pred["peak_live_bytes"],
            "predicted_peak_total_bytes":
                pred["predicted_peak_total_bytes"],
            "predicted_resident_bytes": basis,
            "measured_bytes": m["measured_bytes"],
            "host_watermark_bytes": m.get("host_watermark_bytes"),
            "source": m["source"],
            "gap_pct": None if gap is None else round(gap, 1),
        })
    return rows


def check_orderings(measured_bytes: Dict[str, int]) -> List[dict]:
    """Evaluate the static orderings on measured numbers; pairs with a
    missing side are skipped (e.g. a model without a TP plan)."""
    out: List[dict] = []
    for small, large in ORDERINGS:
        if small not in measured_bytes or large not in measured_bytes:
            continue
        out.append({
            "smaller": small, "larger": large,
            "smaller_bytes": int(measured_bytes[small]),
            "larger_bytes": int(measured_bytes[large]),
            "ok": measured_bytes[small] < measured_bytes[large],
        })
    return out


def format_ledger(rows: List[dict], orderings: List[dict]) -> str:
    mib = 2.0 ** 20
    out = [f"{'program':<24} {'peak total':>11} {'resident':>11} "
           f"{'measured':>11} {'host peak':>11} {'gap':>8}  source"]
    for r in rows:
        gap = ("-" if r["gap_pct"] is None else f"{r['gap_pct']:+.1f}%")
        host = r.get("host_watermark_bytes")
        out.append(
            f"{r['program']:<24} "
            f"{r['predicted_peak_total_bytes'] / mib:>9.1f}Mi "
            f"{r['predicted_resident_bytes'] / mib:>9.1f}Mi "
            f"{r['measured_bytes'] / mib:>9.1f}Mi "
            + (f"{host / mib:>9.1f}Mi " if host is not None
               else f"{'-':>11} ")
            + f"{gap:>8}  {r['source']}")
    for o in orderings:
        verdict = "ok" if o["ok"] else "VIOLATED"
        out.append(
            f"ordering {o['smaller']} < {o['larger']}: "
            f"{o['smaller_bytes'] / mib:.1f}Mi < "
            f"{o['larger_bytes'] / mib:.1f}Mi  [{verdict}]")
    return "\n".join(out)


def main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m ddp_tpu.obs.memledger --predict`` — the abstract side
    only (no devices needed); the measured join lives in ``bench.py
    --mem_ledger`` where the child-process harness is."""
    import argparse
    ap = argparse.ArgumentParser(prog="ddp_tpu.obs.memledger")
    ap.add_argument("--model", default="deepnn")
    ap.add_argument("--mesh", default="2,4")
    ap.add_argument("--programs", nargs="*", default=None)
    args = ap.parse_args(argv)
    d, m = (int(x) for x in args.mesh.split(","))
    pred = predict(args.model, (d, m), args.programs)
    print(json.dumps(pred, indent=1, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
