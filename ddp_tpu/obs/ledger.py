"""The efficiency ledger: measured spans joined against cost-model
predictions, per phase.

PR 8's calibrated cost model (``bench.py --calibrate_cost``) predicts
ms/step per audited program from counted FLOPs/bytes and four fitted
machine coefficients; the tracer measures what actually ran.  Until now
nothing compared the two — this module is that join: each traced phase
that corresponds to an audited program (``dispatch`` → the train step,
``eval`` → the eval step, ``drift_audit`` → the SDC audit program,
``forward`` → the serve forward) gets a predicted-vs-measured row with a
gap percentage, and phases the model cannot price (host-side input work:
``data_wait``/``host_augment``/``h2d``) are listed measured-only, so the
table is honest about coverage.

The ledger also records the spill's *serial-coverage fraction* (the
non-overlap span sum over wall, obs/export.py's wall identity): a gap
table computed from a spill whose serial lanes only tile 40% of wall is
answering a different question than one at 95%, and the consumer
(``tools/bench_trend.py``, BENCH_r11.json) should see that number next
to the gaps.

Mesh caveat, inherited from the calibration bench: the cost model prices
ONE shard's body; a virtual CPU mesh (``--xla_force_host_platform_
device_count``) serializes its shards, so measured ≈ n_dev × predicted
there.  ``pred_scale`` (the CLI's ``--ledger_scale``, bench's device
count) applies that known factor so the residual gap is signal, not
mesh artifact.
"""
from __future__ import annotations

import statistics
from typing import Dict, List, Optional

from .export import phase_summary

# Traced phase -> audited program-name prefix (analysis/programs.py
# registry names are "<prefix>@<mesh>"). The preferred variant is the
# plain data-parallel one; explicit calib keys win over prefix search.
PHASE_PROGRAM_PREFIX = {
    "dispatch": "train_step",
    "eval": "eval_step",
    "drift_audit": "drift_audit",
    "forward": "serve_forward",
}


def _pick_program(prefix: str, predicted: Dict[str, float]
                  ) -> Optional[str]:
    """The calibration record's program for a phase: the plain ``@dp``
    variant when present (accum/zero/tp variants answer narrower
    questions), else the first match in sorted order."""
    candidates = sorted(n for n in predicted
                        if n == prefix or n.startswith(prefix + "@"))
    for name in candidates:
        tail = name.split("@", 1)[-1]
        if tail.startswith("dp"):
            return name
    return candidates[0] if candidates else None


def build_ledger(spans: List[dict], calib: dict, *,
                 pred_scale: float = 1.0) -> dict:
    """Join measured phase timings with calibrated predictions.

    ``calib`` is the ``bench.py --calibrate_cost`` JSON record (needs
    ``predicted_ms_per_step``; ``coefficients`` ride along for
    provenance).  Returns ``{"rows": [...], "unpriced": [...],
    "serial_coverage": f, "pred_scale": k, "coefficients": {...}}``
    where each row carries ``phase, program, count, measured_ms
    (median over steady-state occurrences), predicted_ms, gap_pct,
    first_call_ms`` — ``gap_pct`` positive when the run was slower than
    the model's floor, and the first (compile-paying) occurrence per
    host excluded from the steady stats and reported on its own.
    """
    predicted = calib.get("predicted_ms_per_step") or {}
    if not predicted:
        raise ValueError(
            "calibration record has no 'predicted_ms_per_step' — pass "
            "the JSON emitted by bench.py --calibrate_cost")
    # Each host's FIRST span of a phase is the one that paid the XLA
    # compile (jit caches per process), so folding it into the phase's
    # median poisons low-count phases: BENCH_r11's eval row showed a
    # +458% gap that was really one compile plus one steady eval.  The
    # first occurrence per (phase, host) is split out as
    # ``first_call_ms`` and the steady stats are computed from the rest;
    # a phase that only ever ran once per host keeps its measurement but
    # says so (``first_call_only``) instead of presenting compile time
    # as steady state.
    by_phase: Dict[str, Dict[int, List[dict]]] = {}
    for s in spans:
        if not s.get("overlap"):
            by_phase.setdefault(s["phase"], {}).setdefault(
                int(s.get("host", 0)), []).append(s)
    rows: List[dict] = []
    unpriced: List[dict] = []
    for phase in sorted(by_phase):
        firsts: List[float] = []
        steady: List[float] = []
        for host_spans in by_phase[phase].values():
            host_spans.sort(key=lambda s: float(s.get("start_s", 0.0)))
            firsts.append(float(host_spans[0]["dur_s"]) * 1e3)
            steady.extend(float(s["dur_s"]) * 1e3
                          for s in host_spans[1:])
        first_call = statistics.median(firsts)
        first_only = not steady
        durs = steady or firsts
        measured = statistics.median(durs)
        prefix = PHASE_PROGRAM_PREFIX.get(phase)
        prog = _pick_program(prefix, predicted) if prefix else None
        if prog is None:
            unpriced.append({"phase": phase, "count": len(durs),
                             "measured_ms": round(measured, 3),
                             "first_call_ms": round(first_call, 3)})
            continue
        pred = float(predicted[prog]) * float(pred_scale)
        gap = ((measured - pred) / pred * 100.0) if pred > 0 else None
        row = {
            "phase": phase, "program": prog, "count": len(durs),
            "measured_ms": round(measured, 3),
            "predicted_ms": round(pred, 3),
            "gap_pct": round(gap, 1) if gap is not None else None,
            "first_call_ms": round(first_call, 3),
        }
        if first_only:
            row["first_call_only"] = True
        rows.append(row)
    _, wall_s, critical_s = phase_summary(spans)
    return {
        "rows": rows,
        "unpriced": unpriced,
        "serial_coverage": round(critical_s / wall_s, 4) if wall_s else 0.0,
        "pred_scale": float(pred_scale),
        "coefficients": calib.get("coefficients", {}),
    }


def format_ledger(ledger: dict) -> str:
    """The ``python -m ddp_tpu.obs --ledger`` terminal table."""
    lines = [f"{'phase':<14} {'program':<22} {'count':>6} "
             f"{'measured ms':>12} {'predicted ms':>13} {'gap':>8} "
             f"{'first ms':>10}"]
    for r in ledger["rows"]:
        gap = f"{r['gap_pct']:+.1f}%" if r["gap_pct"] is not None else "-"
        first = f"{r['first_call_ms']:.3f}" + \
            ("*" if r.get("first_call_only") else "")
        lines.append(f"{r['phase']:<14} {r['program']:<22} "
                     f"{r['count']:>6} {r['measured_ms']:>12.3f} "
                     f"{r['predicted_ms']:>13.3f} {gap:>8} "
                     f"{first:>10}")
    if not ledger["rows"]:
        lines.append("  (no priceable phases in this spill)")
    for r in ledger["unpriced"]:
        lines.append(f"{r['phase']:<14} {'(unpriced)':<22} "
                     f"{r['count']:>6} {r['measured_ms']:>12.3f} "
                     f"{'-':>13} {'-':>8} "
                     f"{r['first_call_ms']:>10.3f}")
    if any(r.get("first_call_only") for r in ledger["rows"]):
        lines.append("  * phase ran once per host: its only measurement "
                     "IS the first (compile-tainted) call")
    lines.append(
        f"serial coverage {ledger['serial_coverage'] * 100:.1f}% of wall; "
        f"predictions scaled x{ledger['pred_scale']:g} "
        "(virtual-mesh shard serialization)")
    return "\n".join(lines)
