"""Live run introspection — rank-0 HTTP endpoints + periodic .prom + the
on-demand profile trigger.

A running training job used to be a black box until its end-of-run
artifacts landed.  This module is the in-run observation surface, all of
it off by default and costing nothing when off (the same kill-switch
contract as ``--obs_off``):

- :class:`InspectServer` — a stdlib ``ThreadingHTTPServer`` on
  ``--inspect_port`` (rank 0, loopback) serving

  * ``GET /metrics``  — the live registry exposition (strict v0.0.4
    text, round-trips ``obs.registry.parse_exposition``),
  * ``GET /healthz``  — step/epoch, last guard decision, last
    drift-audit step, mirror lag, prefetch occupancy, watchdog
    last-beat age (the same snapshot the flight recorder bundles),
  * ``GET /spans``    — the tracer's completed-span ring as JSON,
  * ``GET /debug/profile?steps=N`` — arm the profile trigger;

- :class:`ProfileTrigger` — captures the NEXT ``N`` steps' spans (plus a
  ``jax.profiler`` trace directory when the backend supports it and no
  ``--profile_dir`` trace already owns the profiler) and writes one
  ``profile_capture_<step>.json`` artifact.  Armed over HTTP or by
  SIGUSR1 (:func:`install_sigusr1`) for headless boxes;

- :class:`PromFileWriter` — rewrites ``<metrics_path>.prom`` every
  ``--log_every`` optimizer steps so file-based scrapers see a live run,
  each rewrite crash-atomic via :func:`obs.blackbox.atomic_write_text`
  (temp + fsync + ``os.replace``): a concurrent scrape reads either the
  previous complete exposition or the new one, never a torn file.

Nothing here touches the training hot path beyond one bounded callable
per optimizer step (the trainer's ``step_probe``), and none of it is
constructed at all unless the flags ask for it — with ``--inspect_port``
unset the run binds no socket and behaves bit-identically.
"""
from __future__ import annotations

import json
import signal
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, List, Optional
from urllib.parse import parse_qs, urlsplit

from .blackbox import atomic_write_text
from .registry import CONTENT_TYPE, MetricsRegistry

# SIGUSR1 has no query string to carry N — capture a fixed, useful window.
SIGUSR1_PROFILE_STEPS = 16


class PromFileWriter:
    """Periodic crash-atomic ``<metrics_path>.prom`` rewrite.

    ``step(n)`` is the trainer's per-step probe: it rewrites when ``n``
    crosses the ``every`` cadence (same cadence as the live-stats
    emitter).  ``write()`` forces one — the end-of-run path uses it so
    the final exposition always lands even when the run dies between
    cadence points.  Failures warn once and disable the writer: a
    read-only disk must not fail a step, and must not warn per step."""

    def __init__(self, registry: MetricsRegistry, path: str,
                 every: int) -> None:
        self._registry = registry
        self.path = path
        self._every = max(int(every), 1)
        self._last_written = -1
        self._dead = False

    def step(self, step: int) -> None:
        if self._dead or step < 0:
            return
        if step // self._every != self._last_written // self._every:
            self._last_written = step
            self.write()

    def write(self) -> None:
        if self._dead:
            return
        try:
            atomic_write_text(self.path, self._registry.exposition())
        except OSError as e:
            print(f"WARNING: cannot write metrics scrape file "
                  f"{self.path!r} ({e}); periodic .prom rewrite disabled",
                  file=sys.stderr)
            self._dead = True


class ProfileTrigger:
    """Arm-and-capture profiler for the next N optimizer steps.

    ``request(n)`` (HTTP handler thread or SIGUSR1 handler) only sets an
    integer under a lock; the capture itself starts and ends on the
    training loop thread inside ``step()``, so the jax profiler start /
    stop bracket and the span-window read happen where the work happens.
    ``profiler_available=False`` (cli passes it when ``--profile_dir``
    already owns the process-wide profiler) keeps the span capture and
    skips the trace dir."""

    def __init__(self, tracer, out_dir: str, *,
                 profiler_available: bool = True) -> None:
        import os
        self._tracer = tracer
        self._out_dir = out_dir or os.getcwd()
        self._profiler_available = profiler_available
        self._lock = threading.Lock()
        self._pending = 0      # analysis: shared-under(_lock)
        self._remaining = 0    # active capture's steps left
        self._t0 = 0.0
        self._start_step = 0
        self._trace_dir: Optional[str] = None
        self.captures: List[str] = []  # artifact paths, oldest first

    def request(self, steps: int) -> None:
        steps = max(int(steps), 1)
        with self._lock:
            if self._pending == 0 and self._remaining == 0:
                self._pending = steps

    @property
    def armed(self) -> bool:
        """True while a capture is requested or in flight."""
        with self._lock:
            return self._pending > 0 or self._remaining > 0

    def step(self, step: int) -> None:
        start, finish = 0, False
        with self._lock:
            if self._remaining > 0:
                self._remaining -= 1
                finish = self._remaining == 0
            elif self._pending > 0:
                start = self._pending
                self._pending = 0
                self._remaining = start
        if start:
            self._start(step, start)  # counts down from the NEXT step
        elif finish:
            self._finish(step)

    def _start(self, step: int, steps: int) -> None:
        import os
        self._start_step = step
        self._t0 = self._tracer.now() if getattr(
            self._tracer, "enabled", False) else 0.0
        self._trace_dir = None
        if self._profiler_available:
            trace_dir = os.path.join(self._out_dir,
                                     f"profile_trace_step{step}")
            try:
                import jax
                jax.profiler.start_trace(trace_dir)
                self._trace_dir = trace_dir
            except Exception as e:  # backend without profiler support
                print(f"note: jax profiler trace unavailable ({e}); "
                      "capturing spans only", file=sys.stderr)
        print(f"profile trigger: capturing the next {steps} step(s) "
              f"from step {step}", file=sys.stderr)

    def _finish(self, step: int) -> None:
        import os
        if self._trace_dir is not None:
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception:
                self._trace_dir = None
        spans = (self._tracer.spans_since(self._t0)
                 if getattr(self._tracer, "enabled", False) else [])
        doc = {"schema": "profile_capture/1",
               "start_step": self._start_step, "end_step": step,
               "trace_dir": self._trace_dir, "spans": spans}
        path = os.path.join(self._out_dir,
                            f"profile_capture_step{self._start_step}.json")
        try:
            atomic_write_text(path, json.dumps(doc, indent=1) + "\n")
            self.captures.append(path)
            print(f"profile trigger: wrote {path}"
                  + (f" (trace: {self._trace_dir})" if self._trace_dir
                     else ""), file=sys.stderr)
        except OSError as e:
            print(f"WARNING: profile capture write failed: {e}",
                  file=sys.stderr)


def install_sigusr1(trigger: ProfileTrigger,
                    steps: int = SIGUSR1_PROFILE_STEPS
                    ) -> Optional[Callable[[], None]]:
    """SIGUSR1 arms the profile trigger (headless boxes with no port
    open to curl).  Returns an uninstaller restoring the previous
    handler, or None when not on the main thread (signal.signal is
    main-thread-only — embedded callers keep their own handlers)."""
    if threading.current_thread() is not threading.main_thread():
        return None
    prev = signal.signal(signal.SIGUSR1,
                         lambda signum, frame: trigger.request(steps))

    def _uninstall() -> None:
        signal.signal(signal.SIGUSR1, prev)

    return _uninstall


class _Handler(BaseHTTPRequestHandler):
    # One in-run server per process; request logging to stderr would
    # interleave with training prints — drop it.
    def log_message(self, fmt, *args):  # noqa: A002
        pass

    def _send(self, code: int, body: str, content_type: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        try:
            self.wfile.write(data)
        except (BrokenPipeError, ConnectionResetError):
            pass  # scraper went away mid-write; nothing to clean up

    def _send_json(self, doc, code: int = 200) -> None:
        self._send(code, json.dumps(doc, sort_keys=True) + "\n",
                   "application/json; charset=utf-8")

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        srv: "InspectServer" = self.server.inspect  # type: ignore[attr-defined]
        url = urlsplit(self.path)
        try:
            if url.path == "/metrics":
                self._send(200, srv.registry.exposition(), CONTENT_TYPE)
            elif url.path == "/healthz":
                self._send_json(srv.health_snapshot())
            elif url.path == "/spans":
                tracer = srv.tracer
                spans = (tracer.spans_since(0.0)
                         if getattr(tracer, "enabled", False) else [])
                self._send_json({"spans": spans})
            elif url.path == "/debug/profile":
                if srv.profile is None:
                    self._send_json({"error": "profile trigger off "
                                     "(--obs_off run?)"}, code=503)
                    return
                q = parse_qs(url.query)
                try:
                    steps = int(q.get("steps", ["8"])[0])
                except ValueError:
                    self._send_json({"error": "steps must be an int"},
                                    code=400)
                    return
                srv.profile.request(steps)
                self._send_json({"armed": True, "steps": max(steps, 1),
                                 "out_dir": srv.profile._out_dir})
            else:
                self._send_json({"error": f"no route {url.path}",
                                 "routes": ["/metrics", "/healthz",
                                            "/spans", "/debug/profile"]},
                                code=404)
        except Exception as e:
            # An endpoint bug must not take down the scrape loop, let
            # alone the run — report it to the caller instead.
            self._send_json({"error": repr(e)}, code=500)


class InspectServer:
    """The rank-0 in-run HTTP server.  Constructed ONLY when
    ``--inspect_port`` is given (the off path binds no socket); serves on
    loopback from a daemon thread, so a wedged run's endpoints stay
    readable right up to the watchdog's ``os._exit``."""

    def __init__(self, port: int, *, registry: MetricsRegistry, tracer,
                 health: Callable[[], dict],
                 profile: Optional[ProfileTrigger] = None,
                 host: str = "127.0.0.1") -> None:
        self.registry = registry
        self.tracer = tracer
        self._health = health
        self.profile = profile
        self._httpd = ThreadingHTTPServer((host, int(port)), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.inspect = self  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, kwargs={"poll_interval": 0.5},
            daemon=True, name="obs-inspect")
        self._thread.start()

    @property
    def port(self) -> int:
        """The actual bound port (``--inspect_port 0`` = ephemeral)."""
        return int(self._httpd.server_address[1])

    def health_snapshot(self) -> dict:
        try:
            return dict(self._health())
        except Exception as e:
            return {"ok": False, "error": repr(e)}

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        self._thread.join(timeout=3.0)
