"""Cross-host straggler attribution: per-phase medians gathered over the
mesh, the slowest host named per phase with its skew vs the median.

Mesh-wide aggregation of per-device/per-host timings is what makes
multi-chip behavior legible (Mesh-TensorFlow, arxiv 1811.02084); here
each host summarises its own tracer ring into per-phase median
durations, the vectors are gathered through the same device-collective
pattern as ``mesh.process_min_mib`` (asymmetric-topology-safe, no
``process_allgather`` reshape assumptions), and every host derives the
identical per-epoch verdict: for each phase, which host is slowest and
by how much.  Rank 0 logs the record (``phase_stragglers``) into the
metrics stream once per epoch.

Multi-host only runs the collective when ``mesh`` is given and there is
more than one process; single-host (including the CPU test tier, whose
backend must not enqueue extra programs behind an in-flight epoch —
see trainer._save_checkpoint's hazard note) takes a pure-numpy path
with the same record shape, so the record's consumers are exercised
everywhere even though the interesting skews only exist on pods.

The *stall*-time counterpart (when an epoch never completes and no
record can be gathered) is the watchdog's per-host last-completed-span
report (resilience/watchdog.py ``context`` hook): collectives are wedged
by definition during a stall, so each host prints its own tail locally.
"""
from __future__ import annotations

import statistics
from typing import Dict, List, Optional

import numpy as np

from .export import PHASE_ORDER

# Phases excluded from the cross-host verdict because they are
# structurally rank-ASYMMETRIC: only rank 0 pays the checkpoint write
# (the join/snapshot serial span AND the writer thread — the rank-0 gate
# is the reference's own design, multigpu.py:118), so host 0 would be
# named its "straggler" every save epoch, burying real skew.  The phase
# stays in per-host reports and bench phase_ms; it just cannot be
# compared ACROSS hosts.
STRAGGLER_EXCLUDED_PHASES = frozenset(("ckpt_write", "ckpt_upload"))


def phase_medians(spans: List[dict],
                  include_overlap: bool = True) -> Dict[str, float]:
    """Median duration (ms) per phase over a span window.

    ``include_overlap=False`` restricts to serial (consumer-loop) spans —
    what the cross-host straggler gather compares: overlap spans are
    structurally rank-ASYMMETRIC (only rank 0 runs the checkpoint writer
    thread), so pooling them would flag the writer rank as a ckpt_write
    "straggler" every epoch.  A genuinely slow producer still surfaces
    in the gather through its serial consequence, ``data_wait``.  Bench's
    ``phase_ms`` block keeps the full (overlap-included) medians."""
    durs: Dict[str, List[float]] = {}
    for s in spans:
        if not include_overlap and s.get("overlap"):
            continue
        durs.setdefault(s["phase"], []).append(float(s["dur_s"]))
    return {p: statistics.median(d) * 1e3 for p, d in durs.items()}


def _median_vector(medians: Dict[str, float]) -> np.ndarray:
    """Fixed-order vector over the canonical phases (absent phase = 0) —
    the gather needs every host to contribute the same-shaped row."""
    return np.asarray([medians.get(p, 0.0) for p in PHASE_ORDER],
                      np.float32)


def _gather_host_rows(mesh, vec: np.ndarray) -> List[tuple]:
    """All-gather one float32 row per host over the mesh's devices;
    returns ``[(host_id, row), ...]`` — a device COLLECTIVE, so every
    process must call it at the same point (the trainer calls it once
    per epoch boundary, before the preemption collective)."""
    import jax

    from ..parallel.mesh import (assemble_from_local, batch_sharding,
                                 local_replica_ids, replicated_sharding)
    n_local = len(local_replica_ids(mesh))
    local = np.tile(vec[None, :], (n_local, 1))
    vals = assemble_from_local(batch_sharding(mesh), local, 0)
    rep = np.asarray(jax.jit(
        lambda x: x + 0.0,
        out_shardings=replicated_sharding(mesh))(vals))
    rows, seen = [], set()
    for i, d in enumerate(mesh.devices.flat):
        if d.process_index not in seen:
            seen.add(d.process_index)
            rows.append((int(d.process_index), rep[i]))
    return rows


def straggler_report(medians: Dict[str, float], mesh=None
                     ) -> Dict[str, dict]:
    """Per-phase straggler verdict: ``{phase: {slowest_host, slowest_ms,
    median_ms, skew_pct}}``.

    With ``mesh`` and >1 process this is a collective (every rank must
    call it); otherwise it degrades to the single-host identity record.
    Phases nobody timed this epoch are omitted.
    """
    import jax
    # Call contract (docstring + _log_stragglers): every rank passes the
    # same mesh, or every rank passes None — the branch is uniform.
    # analysis: divergence-ok(mesh passed uniformly by call contract)
    if mesh is not None and jax.process_count() > 1:
        rows = _gather_host_rows(mesh, _median_vector(medians))
    else:
        rows = [(0, _median_vector(medians))]
    report: Dict[str, dict] = {}
    for j, phase in enumerate(PHASE_ORDER):
        if phase in STRAGGLER_EXCLUDED_PHASES:
            continue  # rank-asymmetric by design: skew is structural
        vals = [(h, float(row[j])) for h, row in rows]
        if all(v == 0.0 for _, v in vals):
            continue  # nobody recorded this phase this epoch
        med = float(np.median([v for _, v in vals]))
        slowest_host, slowest = max(vals, key=lambda hv: hv[1])
        report[phase] = {
            "slowest_host": slowest_host,
            "slowest_ms": round(slowest, 3),
            "median_ms": round(med, 3),
            "skew_pct": round((slowest - med) / med * 100.0, 1)
            if med > 0 else 0.0,
        }
    return report


def epoch_straggler_record(tracer, mesh, since: float,
                           metrics=None, epoch: Optional[int] = None
                           ) -> Optional[Dict[str, dict]]:
    """One epoch's cross-host attribution: summarise the tracer window,
    gather, and (rank 0, when ``metrics`` is given) log the
    ``phase_stragglers`` event.  Returns the report (all ranks)."""
    if not getattr(tracer, "enabled", False):
        # analysis: divergence-ok(enabled is shared CLI config)
        return None
    report = straggler_report(
        phase_medians(tracer.spans_since(since), include_overlap=False),
        mesh=mesh)
    if metrics is not None and report:
        metrics.log_event("phase_stragglers", epoch=epoch, phases=report)
    return report
