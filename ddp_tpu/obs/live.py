"""Live run statistics: rolling step-time window, samples/sec, MFU, and
prefetch occupancy, emitted through :class:`~ddp_tpu.utils.metrics.
MetricsLogger` (JSONL + TensorBoard) every ``--log_every`` steps.

This is the always-on answer to "is the run healthy *right now*" —
median/p90 step time over a rolling window (p90 >> median is the local
straggler/input-stall signature), achieved samples/sec, MFU against the
measured MXU peak when the model has a FLOP model, and the prefetch
engine's occupancy (consumer wait ≈ 0 means the input pipeline is fully
hidden behind compute).  The offline twin — exact per-step attribution —
is the span spill (obs/tracer.py + ``python -m ddp_tpu.obs``).

The FLOP model and measured-peak tables live HERE (single home);
bench.py imports them for its offline MFU records, so the live and
bench numbers can never disagree on the denominator.
"""
from __future__ import annotations

from collections import deque
from typing import Dict, Optional

# MFU is reported against the bf16-pass MXU peak MEASURED on the chip
# family actually running — the right denominator for fp32 too, because
# the fp32 path's convs also run as single-pass bf16-input/fp32-accum
# MXU passes (BASELINE.md).  A device kind with no entry gets a
# runtime-probed peak (:func:`probed_peak_tflops`) instead of a silent
# null: the old behaviour omitted MFU entirely off-TPU, which left every
# ``bench.py --tp_sweep`` cell with ``"mfu": null`` on the CPU boxes the
# committed BENCH records come from.  The probe is still a MEASURED
# denominator — never a datasheet guess, per ADVICE r4; ``mfu_peak``
# reports which kind fed the number so records can say so.
PEAK_TFLOPS_BF16_PASS = {"TPU v5 lite": 197.0}  # measured, BASELINE.md

# Per-sample train FLOPs, derived per model from the SAME cost model
# BUDGETS.json gates (analysis/costmodel.py counts the fwd+bwd heavy
# ops of the traced grad) — every registered model gets a live MFU from
# one source of truth, instead of the old hand-maintained {"vgg": 3.6}
# table that silently omitted MFU for deepnn/resnet18 runs.  None caches
# a failed derivation so a broken model costs one attempt, not one per
# emission.
_GFLOP_CACHE: Dict[str, Optional[float]] = {}


def train_gflop_per_sample(model_name: Optional[str]) -> Optional[float]:
    """GFLOP per sample of one training step (forward + backward heavy
    ops), counted by tracing ``grad(loss)`` abstractly at batch 1 through
    :func:`~ddp_tpu.analysis.costmodel.cost_of_jaxpr`.  Cached per model;
    None when the model is unknown or untraceable."""
    if not model_name:
        return None
    if model_name in _GFLOP_CACHE:
        return _GFLOP_CACHE[model_name]
    try:
        import jax
        import jax.numpy as jnp

        from ..analysis.costmodel import cost_of_jaxpr
        from ..models import get_model
        model = get_model(model_name)
        params, stats = jax.eval_shape(model.init, jax.random.key(0))

        def _sds(tree):
            return jax.tree_util.tree_map(
                lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                               jnp.result_type(x)), tree)

        def loss(p, s, x, y, rng):
            logits, _ = model.apply(p, s, x, train=True, rng=rng)
            logp = jax.nn.log_softmax(logits)
            return -jnp.mean(jnp.take_along_axis(logp, y[:, None], 1))

        closed = jax.make_jaxpr(jax.grad(loss))(
            _sds(params), _sds(stats),
            jax.ShapeDtypeStruct((1, 32, 32, 3), jnp.float32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
            _sds(jax.random.key(0)))
        gflop = cost_of_jaxpr(closed.jaxpr).flops / 1e9
    except Exception:  # no MFU beats a wrong or crashing one
        gflop = None
    _GFLOP_CACHE[model_name] = gflop
    return gflop


# Runtime-probed matmul peak per device kind, TFLOP/s.  None caches a
# failed probe so a broken backend costs one attempt per process.
_PROBED_PEAK: Dict[str, Optional[float]] = {}


def probed_peak_tflops(device_kind: Optional[str] = None
                       ) -> Optional[float]:
    """Best-of-N square-matmul throughput of ONE device of ``device_kind``
    (default: the default backend's first device), in TFLOP/s — the MFU
    denominator fallback for device kinds absent from the offline
    ``PEAK_TFLOPS_BF16_PASS`` table.  bf16 inputs with fp32 accumulation
    (the MXU pass the table's peaks were measured in) except on the CPU
    backend, where bf16 matmul is an emulated slow path and fp32 is the
    honest machine peak.  Cached per kind per process; ~0.5 s once."""
    import time

    import jax
    import jax.numpy as jnp
    try:
        dev = None
        if device_kind:
            dev = next((d for d in jax.devices()
                        if d.device_kind == device_kind), None)
            if dev is None:
                return None
        else:
            dev = jax.devices()[0]
        kind = dev.device_kind
        if kind in _PROBED_PEAK:
            return _PROBED_PEAK[kind]
        n = 1024
        dtype = jnp.float32 if dev.platform == "cpu" else jnp.bfloat16
        x = jax.device_put(jnp.ones((n, n), dtype), dev)

        @jax.jit
        def mm(a):
            return jax.lax.dot(a, a,
                               preferred_element_type=jnp.float32)

        mm(x).block_until_ready()  # compile outside the timed window
        best = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            mm(x).block_until_ready()
            best = min(best, time.perf_counter() - t0)
        peak = 2.0 * n ** 3 / best / 1e12
    except Exception:  # no MFU beats a crashing one
        peak = None
        kind = device_kind or ""
    _PROBED_PEAK[kind] = peak
    return peak


def mfu_peak(device_kind: Optional[str]) -> Optional[tuple]:
    """The MFU denominator for a device kind: ``(tflops, source)`` where
    source is ``"measured"`` (offline table) or ``"probed"`` (runtime
    matmul probe); None when neither exists."""
    peak = PEAK_TFLOPS_BF16_PASS.get(device_kind or "")
    if peak is not None:
        return peak, "measured"
    peak = probed_peak_tflops(device_kind)
    if peak is not None:
        return peak, "probed"
    return None


def model_mfu(samples_per_sec_per_chip: float, model: Optional[str],
              device_kind: Optional[str]) -> Optional[float]:
    """MFU for a measured per-chip rate: counted-jaxpr FLOPs achieved
    per second over the device's peak (offline-measured, else
    runtime-probed — :func:`mfu_peak`).  None only when the model cannot
    be FLOP-counted or no peak is obtainable at all."""
    gflop = train_gflop_per_sample(model)
    peak = mfu_peak(device_kind)
    if gflop is None or peak is None:
        return None
    return samples_per_sec_per_chip * gflop * 1e9 / (peak[0] * 1e12)


class LiveStats:
    """Rolling-window live stats engine, fed per-step durations by the
    trainer's streaming loop; every ``log_every`` steps one ``live``
    record lands in the metrics stream (rank 0 — the caller gates).

    ``prefetch_stats`` (a :class:`~ddp_tpu.data.prefetch.PrefetchStats`)
    is sampled differentially per emission, so occupancy describes the
    window just measured, not the whole run's average.
    """

    def __init__(self, metrics, *, global_batch: int, n_chips: int,
                 log_every: int = 50, window: int = 100,
                 model: Optional[str] = None,
                 device_kind: Optional[str] = None,
                 prefetch_stats=None):
        self._metrics = metrics
        self.global_batch = int(global_batch)
        self.n_chips = max(int(n_chips), 1)
        self.log_every = max(int(log_every), 1)
        self._durs: deque = deque(maxlen=max(int(window), 2))
        self._count = 0
        self.model = model
        self.device_kind = device_kind
        self._pf = prefetch_stats
        self._pf_prev = self._pf_snapshot()
        # Consumer-loop seconds accumulated since the last emission — the
        # occupancy denominator.  Wall-clock since the last emit would
        # fold in compile, epoch boundaries (flush/checkpoint/eval) and
        # pre-training setup, reporting ~1.0 occupancy for a first window
        # that in truth waited on input the whole time.
        self._win_s = 0.0

    def _pf_snapshot(self) -> Dict[str, float]:
        if self._pf is None:
            return {}
        return {"wait_s": self._pf.wait_s, "host_s": self._pf.host_s,
                "h2d_s": self._pf.h2d_s, "batches": self._pf.batches}

    def step(self, dur_s: float, step: int) -> None:
        """Record one consumer-loop step duration; emits on the cadence."""
        self._durs.append(float(dur_s))
        self._win_s += float(dur_s)
        self._count += 1
        if self._count % self.log_every == 0:
            self._emit(step)

    def _emit(self, step: int) -> None:
        durs = sorted(self._durs)
        n = len(durs)
        median = durs[n // 2] if n % 2 else (durs[n // 2 - 1]
                                             + durs[n // 2]) / 2.0
        # Nearest-rank p90: ceil(0.9 n)-th order statistic — with a small
        # window this still surfaces a single straggler step (an
        # interpolating quantile would average it away).
        p90 = durs[min(-(-9 * n // 10) - 1, n - 1)]
        fields: Dict[str, float] = {
            "step_ms_median": round(median * 1e3, 3),
            "step_ms_p90": round(p90 * 1e3, 3),
            "window_steps": n,
        }
        if median > 0:
            sps = self.global_batch / median
            fields["samples_per_sec"] = round(sps, 2)
            fields["samples_per_sec_per_chip"] = round(sps / self.n_chips, 2)
            mfu = model_mfu(sps / self.n_chips, self.model, self.device_kind)
            if mfu is not None:
                fields["mfu"] = round(mfu, 4)
        if self._pf is not None:
            cur = self._pf_snapshot()
            db = cur["batches"] - self._pf_prev["batches"]
            elapsed = max(self._win_s, 1e-9)
            dwait = max(cur["wait_s"] - self._pf_prev["wait_s"], 0.0)
            if db > 0:
                fields["prefetch_wait_ms_per_step"] = round(
                    dwait / db * 1e3, 3)
                fields["prefetch_host_ms_per_step"] = round(
                    max(cur["host_s"] - self._pf_prev["host_s"], 0.0)
                    / db * 1e3, 3)
                fields["prefetch_h2d_ms_per_step"] = round(
                    max(cur["h2d_s"] - self._pf_prev["h2d_s"], 0.0)
                    / db * 1e3, 3)
            # Occupancy: fraction of the window the consumer loop was NOT
            # blocked waiting for a batch — 1.0 means the input pipeline
            # is fully hidden behind compute (PrefetchStats' wait_s is
            # exactly the measured pipeline bubble).
            fields["prefetch_occupancy"] = round(
                min(max(1.0 - dwait / elapsed, 0.0), 1.0), 4)
            self._pf_prev = cur
        self._win_s = 0.0
        self._metrics.log_live(step=step, **fields)
