"""Flight recorder — the postmortem a dead run leaves behind.

A crashed, aborted, stalled or drift-poisoned run used to leave an exit
code, a truncated stderr, and whatever the spill file happened to hold.
The :class:`FlightRecorder` keeps a bounded in-memory ring of the run's
recent telemetry — metrics records (every ``MetricsLogger`` line taps
in), the tracer's completed-span ring, guard/drift state, and a JSON-safe
snapshot of the CLI config — and, on any abnormal exit path, dumps one
schema-validated ``postmortem.json`` bundle next to the metrics JSONL.
The supervisor's failure ledger and exit-87 ``diagnosis.json`` link the
bundle (resilience/supervisor.py), so a chaos-campaign failure is
diagnosable from artifacts alone.

Dump sites (wired in cli.py):

- watchdog expiry — composed into the watchdog ``on_expire`` hook with
  the same bounded-lock discipline as the spill flush: the expire path
  exists to escape a wedged run, so the dump runs on a side thread with
  a join timeout and tracer reads take ``lock_timeout``;
- the trainer-lifetime exception wrap — ``PreemptionInterrupt``
  (reason ``preemption``), guard aborts (``guard_abort``), drift aborts
  (``drift_abort``), and any other exception (``crash``) all dump
  before the error propagates to :func:`cli.run`'s teardown.

The write itself reuses the fsync-ordered manifest-commit pattern
(resilience/lineage.py): temp file in the same directory, fsync, then
``os.replace`` — a reader (or the supervisor, racing the child's death)
sees either the previous complete bundle or the new complete bundle,
never a torn one.  Like every telemetry path, a failed dump warns and
returns; it never kills (or re-kills) the run it observes.
"""
from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional

POSTMORTEM_SCHEMA = "postmortem/1"
POSTMORTEM_BASENAME = "postmortem.json"

# Dump reasons — the closed vocabulary validate_postmortem accepts.
REASONS = ("crash", "preemption", "watchdog_stall", "guard_abort",
           "drift_abort", "exit")


def _fsync_dir(d: str) -> None:
    """Durable-rename helper (same shape as lineage.py): fsync a
    directory, tolerating platforms where directories cannot be fsynced."""
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_text(path: str, text: str) -> None:
    """Crash-atomic text write: temp sibling + flush + fsync +
    ``os.replace`` + directory fsync.  A concurrent reader sees either
    the old complete file or the new complete file — the torn-scrape
    contract both ``postmortem.json`` and the periodic ``.prom`` rewrite
    (obs/inspect.py) rely on."""
    d = os.path.dirname(os.path.abspath(path)) or os.getcwd()
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            f.write(text)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        _fsync_dir(d)
    except BaseException:
        if os.path.exists(tmp):
            try:
                os.unlink(tmp)
            except OSError:
                pass
        raise


def _json_safe(v: Any) -> Any:
    """Best-effort JSON projection for config values (argparse namespaces
    hold only scalars/strings/None in this codebase, but stay defensive)."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    return repr(v)


class FlightRecorder:
    """Bounded telemetry ring + one-shot postmortem bundle writer.

    ``path`` is the bundle destination (``postmortem.json`` next to the
    metrics JSONL); ``config`` a dict snapshot of the CLI args;
    ``tracer`` the live span tracer (read at dump time, bounded);
    ``context`` an optional callable returning a dict of live run state
    (cli.py passes the /healthz snapshot provider, so the bundle and the
    inspect endpoint describe the run identically).
    """

    def __init__(self, path: str, *, config: Optional[dict] = None,
                 tracer=None, context: Optional[Callable[[], dict]] = None,
                 ring: int = 256):
        self.path = path
        self._config = _json_safe(dict(config or {}))
        self._tracer = tracer
        self._context = context
        self._lock = threading.Lock()
        # analysis: shared-under(_lock)
        self._events: collections.deque = collections.deque(maxlen=ring)
        self._ring = int(ring)
        self._t0 = time.monotonic()
        self.dumped: Optional[str] = None  # reason of the landed dump

    # -- recording ---------------------------------------------------------

    def record(self, rec: dict) -> None:
        """Tap for every MetricsLogger record (utils/metrics.py): per-step
        scalars, guard/drift/preemption events, live telemetry.  One dict
        append under a lock — cheap enough for the per-step stream."""
        with self._lock:
            self._events.append(rec)

    # -- dumping -----------------------------------------------------------

    def _spans(self, bounded: bool) -> List[dict]:
        tracer = self._tracer
        if tracer is None or not getattr(tracer, "enabled", False):
            return []
        if bounded:
            # The expire path must never block behind a wedged spill
            # writer holding the tracer lock: last_spans takes a lock
            # timeout; the full ring read does not, so skip it.
            return sorted(tracer.last_spans(lock_timeout=2.0).values(),
                          key=lambda r: r["start_s"])
        spans = tracer.spans_since(0.0)
        return spans[-self._ring:]

    def _build(self, reason: str, *, exit_status: Optional[int],
               error: Optional[str], bounded: bool) -> dict:
        with self._lock:
            events = list(self._events)
        ctx: Optional[dict] = None
        if self._context is not None:
            try:
                ctx = _json_safe(self._context())
            except Exception as e:  # context must not block the dump
                ctx = {"context_error": repr(e)}
        return {
            "schema": POSTMORTEM_SCHEMA,
            "reason": reason,
            "exit_status": exit_status,
            "error": error,
            "time_unix": time.time(),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "config": self._config,
            "health": ctx,
            "spans": self._spans(bounded),
            "events": events,
        }

    def dump(self, reason: str, *, exit_status: Optional[int] = None,
             error: Optional[str] = None, bounded: bool = False) -> bool:
        """Write the bundle; returns True when it landed.  ``bounded``
        (the watchdog expire path) runs the whole dump on a side thread
        with a join timeout, so a hung filesystem cannot keep the expire
        path from reaching exit 124."""
        if reason not in REASONS:
            reason = "crash"
        if bounded:
            done: List[bool] = []

            def _run() -> None:
                done.append(self._dump_now(reason, exit_status, error,
                                           bounded=True))

            t = threading.Thread(target=_run, daemon=True,
                                 name="obs-postmortem")
            t.start()
            t.join(timeout=3.0)
            return bool(done and done[0])
        return self._dump_now(reason, exit_status, error, bounded=False)

    def _dump_now(self, reason: str, exit_status: Optional[int],
                  error: Optional[str], *, bounded: bool) -> bool:
        try:
            doc = self._build(reason, exit_status=exit_status, error=error,
                              bounded=bounded)
            validate_postmortem(doc)  # never ship a bundle we'd reject
            atomic_write_text(self.path, json.dumps(doc, indent=1,
                                                    sort_keys=True) + "\n")
            self.dumped = reason
            return True
        except Exception as e:
            print(f"WARNING: postmortem dump failed ({e}); the run's "
                  "exit status is still authoritative", file=sys.stderr)
            return False


# ---------------------------------------------------------------------------
# Schema validation + rendering (python -m ddp_tpu.obs --postmortem).
# ---------------------------------------------------------------------------

_REQUIRED: Dict[str, tuple] = {
    "schema": (str,),
    "reason": (str,),
    "exit_status": (int, type(None)),
    "error": (str, type(None)),
    "time_unix": (int, float),
    "uptime_s": (int, float),
    "config": (dict,),
    "health": (dict, type(None)),
    "spans": (list,),
    "events": (list,),
}


def validate_postmortem(doc: Any) -> dict:
    """Strictly validate a postmortem bundle; returns the doc or raises
    :class:`ValueError` with a one-line diagnosis.  The executable
    contract the chaos campaign, the supervisor link, and the renderer
    all share — a bundle that parses but fails here is treated exactly
    like a torn one."""
    if not isinstance(doc, dict):
        raise ValueError(f"postmortem bundle is {type(doc).__name__}, "
                         "expected a JSON object")
    if doc.get("schema") != POSTMORTEM_SCHEMA:
        raise ValueError(f"schema {doc.get('schema')!r} != "
                         f"{POSTMORTEM_SCHEMA!r}")
    for key, kinds in _REQUIRED.items():
        if key not in doc:
            raise ValueError(f"missing required key {key!r}")
        if not isinstance(doc[key], kinds):
            raise ValueError(
                f"key {key!r} is {type(doc[key]).__name__}, expected "
                f"{'/'.join(k.__name__ for k in kinds)}")
    if doc["reason"] not in REASONS:
        raise ValueError(f"reason {doc['reason']!r} not in {REASONS}")
    for i, s in enumerate(doc["spans"]):
        if not isinstance(s, dict) or "phase" not in s or "dur_s" not in s:
            raise ValueError(f"spans[{i}] is not a span record")
    for i, e in enumerate(doc["events"]):
        if not isinstance(e, dict):
            raise ValueError(f"events[{i}] is not a record object")
    return doc


# Event kinds that form the guard/drift/resilience timeline in the
# rendered report (everything else in the ring is scalar curve noise).
_TIMELINE_EVENTS = ("guard_decision", "drift_detected", "drift_audit",
                    "restore_from_checkpoint", "preemption_checkpoint",
                    "batch_skipped", "watchdog")


def format_postmortem(doc: dict) -> str:
    """Human-rendered bundle: header, config, guard/drift timeline, last
    spans — newest last, the way you read a black box."""
    out: List[str] = []
    status = ("" if doc["exit_status"] is None
              else f" (exit {doc['exit_status']})")
    out.append(f"postmortem: reason={doc['reason']}{status} after "
               f"{doc['uptime_s']:.1f}s")
    if doc.get("error"):
        out.append(f"error: {doc['error']}")
    health = doc.get("health") or {}
    if health:
        out.append("health at dump: " + ", ".join(
            f"{k}={v}" for k, v in sorted(health.items())))
    cfg = doc.get("config") or {}
    if cfg:
        keys = [k for k in ("model", "total_epochs", "batch_size",
                            "mesh_shape", "num_devices", "watchdog_secs",
                            "drift_audit_every", "drift_action", "on_nan",
                            "guard_action", "metrics_path") if k in cfg]
        out.append("config: " + ", ".join(f"{k}={cfg[k]}" for k in keys))
    timeline = [e for e in doc["events"]
                if e.get("event") in _TIMELINE_EVENTS]
    out.append(f"timeline ({len(timeline)} resilience event(s) of "
               f"{len(doc['events'])} recorded):")
    for e in timeline[-20:]:
        t = e.get("wall_s")
        stamp = f"{t:10.3f}s" if isinstance(t, (int, float)) else " " * 11
        rest = {k: v for k, v in e.items() if k not in ("event", "wall_s")}
        out.append(f"  {stamp}  {e['event']}  "
                   + " ".join(f"{k}={v}" for k, v in rest.items()))
    if not timeline:
        out.append("  (none)")
    spans = doc["spans"]
    out.append(f"last spans ({len(spans)}):")
    for s in spans[-20:]:
        step = f" step {s['step']}" if s.get("step") is not None else ""
        out.append(f"  {s.get('start_s', 0.0):10.3f}s  "
                   f"{s['phase']:<14}{step}  "
                   f"{s['dur_s'] * 1e3:9.2f} ms")
    if not spans:
        out.append("  (none)")
    return "\n".join(out)
