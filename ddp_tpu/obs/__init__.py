"""Run-wide telemetry (observability) subsystem.

- ``tracer``    — low-overhead span tracer every hot path reports into
                  (bounded ring + JSONL spill; ``--obs_off`` = no-op).
- ``export``    — Perfetto ``trace_event`` export + the terminal reports
                  behind ``python -m ddp_tpu.obs``.
- ``live``      — rolling live stats (median/p90 step time, samples/sec,
                  MFU, prefetch occupancy) through MetricsLogger.
- ``aggregate`` — cross-host per-phase straggler attribution.
"""
from .tracer import NullTracer, SpanTracer, get_tracer, set_tracer

__all__ = ["NullTracer", "SpanTracer", "get_tracer", "set_tracer"]
