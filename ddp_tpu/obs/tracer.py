"""Low-overhead span tracer — the one clock every hot path reports into.

The reference's only telemetry is an epoch-header print and two
``time.time()`` calls (SURVEY.md §5); before this subsystem our own
replacements were fragmented (``MetricsLogger`` scalars, offline xplane
analysis, ``PrefetchStats`` counters, one-off attribution math in
bench.py) and none could answer "where did step 4817 go" on a live run.
A :class:`SpanTracer` records one *span* per phase occurrence —

    with tracer.span("h2d", step=s):
        shard_batch(batch, mesh)

— with ``time.monotonic()`` timestamps (NTP/clock-jump safe, same basis
as the watchdog), into a bounded in-memory ring (the watchdog's
last-completed-span stall report and the per-epoch straggler aggregation
read it) and, when a spill path is given, as append-only JSON lines the
offline tooling consumes (``python -m ddp_tpu.obs``: phase breakdown,
step histogram, slowest-K, Perfetto export — obs/export.py).

Phases are free-form strings; the canonical training phases live in
:data:`~ddp_tpu.obs.export.PHASE_ORDER` (data_wait, host_augment, h2d,
dispatch, loss_flush, ckpt_write, eval).  ``overlap=True`` marks spans
recorded on *producer* threads (prefetch workers, the async checkpoint
writer) whose wall time hides behind the consumer loop — reports sum
only non-overlap spans when comparing against wall time, or concurrent
work would be double-counted.

Kill-switch contract (``--obs_off``): the module-level default tracer is
a :class:`NullTracer` whose ``span()`` returns one shared, reusable
no-op context manager — no allocation, no lock, no clock read — so
instrumented hot paths cost two trivial method calls when tracing is
off.  Spans are recorded only on *clean* exit: a span whose body raises
(including the ``StopIteration`` probe at iterator exhaustion) never
lands, which is also what makes "last completed span" the right stall
diagnostic.

Thread safety: producers (prefetch pool/thread, checkpoint writer) and
the consumer loop record concurrently; the ring, last-span table and
spill handle are guarded by one lock taken only *after* the body ran —
never around user code.
"""
from __future__ import annotations

import collections
import json
import os
import threading
import time
from typing import IO, Dict, List, Optional


def default_spill_path(snapshot_path: str, filename: str) -> str:
    """Default spill location for a run: next to its checkpoint head,
    NOT the process CWD.  A bare-CWD default litters whatever directory
    the CLI happened to launch from (and once landed a spill in the repo
    root); anchoring on ``--snapshot_path`` puts the telemetry where the
    run's other artifacts live.  Explicit ``--trace_spill`` paths are
    always honored verbatim — this only fills the unset default."""
    head = os.path.dirname(snapshot_path)
    return os.path.join(head, filename) if head else filename


class _NullSpan:
    """Shared no-op context manager — the entire cost of a disabled span."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class NullTracer:
    """The disabled tracer: every operation is a no-op (``--obs_off``)."""
    enabled = False

    def span(self, phase: str, step: Optional[int] = None,
             overlap: bool = False,
             req: Optional[str] = None) -> _NullSpan:
        return _NULL_SPAN

    def add_span(self, phase: str, start_monotonic: float, dur_s: float,
                 step: Optional[int] = None, overlap: bool = False,
                 req: Optional[str] = None) -> None:
        pass

    def now(self) -> float:
        return 0.0

    def spans_since(self, t: float) -> List[dict]:
        return []

    def last_spans(self, lock_timeout: Optional[float] = None
                   ) -> Dict[str, dict]:
        return {}

    def describe_last(self, lock_timeout: Optional[float] = None) -> str:
        return ""

    def flush(self, fsync: bool = False,
              lock_timeout: Optional[float] = None) -> None:
        pass

    def close(self) -> None:
        pass


class _Span:
    """One in-flight span; records itself on clean ``__exit__`` only."""
    __slots__ = ("_tracer", "phase", "step", "overlap", "req", "_start")

    def __init__(self, tracer: "SpanTracer", phase: str,
                 step: Optional[int], overlap: bool,
                 req: Optional[str] = None):
        self._tracer = tracer
        self.phase = phase
        self.step = step
        self.overlap = overlap
        self.req = req

    def __enter__(self) -> "_Span":
        self._start = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc_type is None:  # an aborted body is not a completed phase
            end = time.monotonic()
            self._tracer._record(self.phase, self.step, self._start,
                                 end - self._start, self.overlap, self.req)
        return False


class SpanTracer:
    """Per-process span recorder: bounded ring + optional JSONL spill.

    ``host`` tags every record with this process's rank so multi-host
    spills merge into one timeline (one Perfetto process per host);
    pass ``jax.process_index()`` — the tracer itself is jax-free.
    ``ring`` bounds in-memory retention (the spill file is the full
    record); ``t0`` anchors relative timestamps and defaults to
    construction time.

    The spill is TRUNCATED per run (the same overwrite-in-place
    discipline as ``checkpoint.pt``): timestamps are relative to this
    tracer's construction, so appending a second run's spans onto a
    first's would stack two timelines at t=0 and double-count every
    report built from the file.
    """

    enabled = True

    def __init__(self, spill_path: Optional[str] = None, *,
                 ring: int = 4096, host: int = 0):
        self.host = int(host)
        self.spill_path = spill_path
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=ring)
        self._last: Dict[str, tuple] = {}
        self._f: Optional[IO[str]] = (open(spill_path, "w")
                                      if spill_path else None)

    # -- recording ---------------------------------------------------------

    def span(self, phase: str, step: Optional[int] = None,
             overlap: bool = False, req: Optional[str] = None) -> _Span:
        return _Span(self, phase, step, overlap, req)

    def add_span(self, phase: str, start_monotonic: float, dur_s: float,
                 step: Optional[int] = None, overlap: bool = False,
                 req: Optional[str] = None) -> None:
        """Record a span measured by the caller (``start_monotonic`` on
        the ``time.monotonic`` clock) — for sites that only know AFTER
        timing whether the interval was a real phase occurrence (e.g. the
        prefetch consumer's queue get, which may return the end-of-stream
        sentinel rather than a batch)."""
        self._record(phase, step, start_monotonic, dur_s, overlap, req)

    def _record(self, phase: str, step: Optional[int], start: float,
                dur: float, overlap: bool,
                req: Optional[str] = None) -> None:
        rec = (phase, step, start - self._t0, dur, overlap, req)
        # Serialize OUTSIDE the lock: json.dumps is pure CPU on local
        # data, and holding the one shared lock through it would make
        # every producer thread contend on exactly the work being timed.
        body = {
            "phase": phase, "step": step,
            "start_s": round(rec[2], 6), "dur_s": round(dur, 6),
            "overlap": overlap, "host": self.host,
        }
        if req is not None:  # request-scoped spans only — lines stay lean
            body["req"] = req
        line = (json.dumps(body) + "\n") if self._f is not None else None
        with self._lock:
            self._ring.append(rec)
            self._last[phase] = rec
            if line is not None and self._f is not None:
                try:
                    self._f.write(line)
                except OSError as e:
                    # Telemetry must never kill the run it observes: a
                    # disk-full/read-only spill mid-run (hours in) gets
                    # the same degrade-to-ring-only treatment cli.py
                    # applies when the spill cannot be OPENED — warn
                    # once, keep tracing in memory.
                    import sys
                    print(f"WARNING: span spill write failed ({e}); "
                          "dropping the spill file, tracing continues "
                          "in-memory only", file=sys.stderr)
                    try:
                        self._f.close()
                    except OSError:
                        pass
                    self._f = None

    # -- reading -----------------------------------------------------------

    def now(self) -> float:
        """Current time on the tracer's own clock (span ``start_s`` basis)
        — the window marker ``spans_since`` consumes."""
        return time.monotonic() - self._t0

    @staticmethod
    def _as_dict(rec: tuple) -> dict:
        phase, step, start, dur, overlap, req = rec
        return {"phase": phase, "step": step, "start_s": start,
                "dur_s": dur, "overlap": overlap, "req": req}

    def spans_since(self, t: float) -> List[dict]:
        """Completed spans whose start is at or after tracer-time ``t``
        (ring-bounded: at most the newest ``ring`` spans survive)."""
        with self._lock:
            return [self._as_dict(r) for r in self._ring if r[2] >= t]

    def last_spans(self, lock_timeout: Optional[float] = None
                   ) -> Dict[str, dict]:
        """Newest completed span per phase — the stall diagnostic.

        ``lock_timeout`` bounds the lock wait: the watchdog's expire path
        calls this while another thread may be WEDGED inside ``_record``
        (a spill write to a hung mount holds the lock), and the expire
        path must never block — it exists to escape exactly such stalls.
        On timeout the answer is empty rather than late."""
        if not self._lock.acquire(
                timeout=-1 if lock_timeout is None else lock_timeout):
            return {}
        try:
            return {p: self._as_dict(r) for p, r in self._last.items()}
        finally:
            self._lock.release()

    def describe_last(self, lock_timeout: Optional[float] = None) -> str:
        """One-line 'last completed span per phase' summary, newest first
        — what the watchdog prints per host when a run stalls."""
        last = sorted(self.last_spans(lock_timeout).values(),
                      key=lambda r: r["start_s"] + r["dur_s"], reverse=True)
        if not last:
            return "no spans completed"
        return "; ".join(
            f"{r['phase']}"
            + (f"[step {r['step']}]" if r["step"] is not None else "")
            + f" ended @{r['start_s'] + r['dur_s']:.3f}s "
            + f"({r['dur_s'] * 1e3:.2f} ms)"
            for r in last)

    # -- lifecycle ---------------------------------------------------------

    def flush(self, fsync: bool = False,
              lock_timeout: Optional[float] = None) -> None:
        """Flush the spill buffer; ``fsync=True`` additionally forces the
        bytes to disk — the preemption emergency-checkpoint path uses it
        so the span tail survives the SIGTERM that is about to land.
        ``lock_timeout`` (watchdog expire path) gives up rather than
        block behind a wedged writer."""
        if not self._lock.acquire(
                timeout=-1 if lock_timeout is None else lock_timeout):
            return
        try:
            if self._f is not None:
                try:
                    self._f.flush()
                    if fsync:
                        os.fsync(self._f.fileno())
                except OSError:
                    pass  # same never-kill-the-run rule as _record
        finally:
            self._lock.release()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()  # flushes the buffered tail
                except OSError:
                    pass  # never-kill-the-run: same rule as _record/flush
                self._f = None

    def __enter__(self) -> "SpanTracer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# Module-level tracer: hot paths that cannot take a tracer argument
# (evaluate(), save_checkpoint()) read this; cli.run installs the real
# tracer for the run's duration and restores the null one after.  The
# default being a NullTracer is the zero-overhead-when-disabled contract.
_tracer: object = NullTracer()


def get_tracer():
    return _tracer


def set_tracer(tracer) -> None:
    global _tracer
    _tracer = tracer if tracer is not None else NullTracer()
