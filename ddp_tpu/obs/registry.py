"""The unified metrics registry: labelled counters/gauges/histograms with
Prometheus text-format exposition.

Every stats surface in the framework (prefetch, serve engine, batcher,
router, guard/drift/watchdog) historically kept its own ad-hoc counter
fields under its own lock.  This module is the one place those numbers
now live or are mirrored into, so one scrape — ``GET /metrics`` on the
serve server, or the end-of-run ``<metrics>.prom`` file on the train
side — sees the whole system with consistent naming and labels.

Design constraints, in order:

- **Thread-safe and cheap.**  ``inc()`` on a hot serve path must not
  contend with an exposition scrape for longer than a dict update; one
  registry-wide lock guards family creation, each instrument guards its
  own value.
- **Per-instance by default.**  A registry is an ordinary object, NOT a
  process singleton: tests and repeated ``cli.run`` calls construct
  components freely without counters bleeding across runs.  Sharing is
  explicit — the serve fleet passes ONE registry to its router, engines
  and batchers (replica-labelled children), the train CLI passes one to
  prefetch/guard/drift/watchdog.
- **Strict, round-trippable exposition.**  :func:`parse_exposition` is
  the validating parser the tests AND the CI fleet smoke use: it rejects
  missing TYPE lines, bad label escaping, and non-monotone histogram
  buckets, so the text format is pinned by an executable contract, not
  by eyeballing curl output.

The text format follows the Prometheus exposition format v0.0.4
(``# HELP``/``# TYPE`` comment lines, ``\\``/``\"``/``\n`` label-value
escapes, cumulative ``_bucket{le=...}`` histogram series ending at
``+Inf`` with matching ``_sum``/``_count``).
"""
from __future__ import annotations

import math
import re
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "MetricsRegistry", "parse_exposition", "CONTENT_TYPE",
    "SECONDS_BUCKETS",
]

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Default histogram buckets: milliseconds-flavoured (queue waits and
# request latencies are the histograms this codebase keeps).
DEFAULT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                   500.0, 1000.0, 2500.0, 5000.0)

# Seconds-flavoured buckets for the coarse timings (supervisor recovery,
# backoff waits) where the ms grid would dump everything in +Inf.
SECONDS_BUCKETS = (0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0, 120.0,
                   300.0, 600.0)


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace("\n", "\\n").replace('"', '\\"')


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if isinstance(v, float) and v != v:
        return "NaN"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _labelset(labelnames: Sequence[str],
              labelvalues: Sequence[str]) -> str:
    if not labelnames:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label_value(str(v))}"'
        for k, v in zip(labelnames, labelvalues))
    return "{" + inner + "}"


class _Counter:
    """A monotone counter child.  ``inc()`` only goes up; ``value`` is
    the read side the legacy ``stats()`` dicts are backed by."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0  # analysis: shared-under(_lock)
        self._fn: Optional[Callable[[], float]] = None  # analysis: shared-under(_lock)

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up; use a gauge")
        with self._lock:
            self._value += amount

    def set_function(self, fn: Callable[[], float]) -> None:
        """Make this child report ``fn()`` at collection time instead of
        an internally stored value (the collector-callback pattern, for
        surfaces whose source of truth stays in the component)."""
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        with self._lock:
            fn = self._fn
        if fn is not None:
            return float(fn())
        with self._lock:
            return self._value


class _Gauge(_Counter):
    """A gauge child: free to move both ways, settable, and optionally
    function-backed (read live from a component at scrape time)."""

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)


class _Histogram:
    """Cumulative-bucket histogram child (Prometheus semantics: each
    ``le`` bucket counts ALL observations <= its bound)."""

    def __init__(self, buckets: Sequence[float]) -> None:
        self._lock = threading.Lock()
        self._bounds = tuple(sorted(float(b) for b in buckets))
        if not self._bounds:
            raise ValueError("histogram needs at least one bucket bound")
        # analysis: shared-under(_lock)
        self._counts = [0] * (len(self._bounds) + 1)  # + the +Inf bucket
        self._sum = 0.0    # analysis: shared-under(_lock)
        self._count = 0    # analysis: shared-under(_lock)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self._bounds):
                if v <= b:
                    self._counts[i] += 1
                    break
            else:
                self._counts[-1] += 1

    def snapshot(self) -> Tuple[Tuple[float, ...], List[int], float, int]:
        """(bounds, cumulative counts incl +Inf, sum, count)."""
        with self._lock:
            cum, acc = [], 0
            for c in self._counts:
                acc += c
                cum.append(acc)
            return self._bounds, cum, self._sum, self._count

    @property
    def value(self) -> float:
        """The observation count — so histograms satisfy the same
        ``.value`` read contract counters do."""
        with self._lock:
            return float(self._count)


_KINDS = {"counter": _Counter, "gauge": _Gauge, "histogram": _Histogram}


class _Family:
    """One named metric family: a TYPE, a HELP string, a label schema,
    and the children keyed by label values."""

    def __init__(self, name: str, kind: str, help: str,
                 labelnames: Sequence[str],
                 buckets: Sequence[float]) -> None:
        self.name = name
        self.kind = kind
        self.help = help
        self.labelnames = tuple(labelnames)
        self._buckets = tuple(buckets)
        self._lock = threading.Lock()
        # analysis: shared-under(_lock)
        self._children: Dict[Tuple[str, ...], object] = {}

    def _make_child(self):
        if self.kind == "histogram":
            return _Histogram(self._buckets)
        return _KINDS[self.kind]()

    def labels(self, **kv):
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}")
        key = tuple(str(kv[k]) for k in self.labelnames)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._make_child()
                self._children[key] = child
            return child

    def children(self) -> List[Tuple[Tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    # Zero-label conveniences: the family IS its single child.
    def _solo(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labelled {self.labelnames}; call "
                ".labels(...) first")
        return self.labels()

    def inc(self, amount: float = 1.0) -> None:
        self._solo().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._solo().dec(amount)

    def set(self, v: float) -> None:
        self._solo().set(v)

    def observe(self, v: float) -> None:
        self._solo().observe(v)

    def set_function(self, fn: Callable[[], float]) -> None:
        self._solo().set_function(fn)

    @property
    def value(self) -> float:
        return self._solo().value


class MetricsRegistry:
    """A collection of metric families with Prometheus exposition.

    Families are created idempotently: asking again for the same name
    with the same kind/labelnames returns the existing family (so every
    component can declare what it uses); a kind or schema mismatch is a
    programming error and raises.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # analysis: shared-under(_lock)
        self._families: Dict[str, _Family] = {}

    def _family(self, name: str, kind: str, help: str,
                labelnames: Sequence[str],
                buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Family:
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln == "le":
                raise ValueError(f"bad label name {ln!r} on {name}")
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name} re-registered as {kind}"
                        f"{tuple(labelnames)} but exists as {fam.kind}"
                        f"{fam.labelnames}")
                return fam
            fam = _Family(name, kind, help, labelnames, buckets)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, "counter", help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> _Family:
        return self._family(name, "gauge", help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> _Family:
        return self._family(name, "histogram", help, labelnames, buckets)

    def families(self) -> List[_Family]:
        with self._lock:
            return [self._families[k] for k in sorted(self._families)]

    # -- exposition --------------------------------------------------------

    def exposition(self) -> str:
        """The Prometheus text format v0.0.4 for every family, sorted by
        name (a deterministic scrape diffs cleanly in CI logs)."""
        out: List[str] = []
        for fam in self.families():
            if fam.help:
                out.append(f"# HELP {fam.name} "
                           f"{fam.help.replace(chr(10), ' ')}")
            out.append(f"# TYPE {fam.name} {fam.kind}")
            for key, child in fam.children():
                if fam.kind == "histogram":
                    bounds, cum, h_sum, h_count = child.snapshot()
                    for b, c in zip(bounds + (math.inf,), cum):
                        ls = _labelset(fam.labelnames + ("le",),
                                       key + (_fmt_value(b),))
                        out.append(f"{fam.name}_bucket{ls} {c}")
                    ls = _labelset(fam.labelnames, key)
                    out.append(f"{fam.name}_sum{ls} {_fmt_value(h_sum)}")
                    out.append(f"{fam.name}_count{ls} {h_count}")
                else:
                    ls = _labelset(fam.labelnames, key)
                    out.append(
                        f"{fam.name}{ls} {_fmt_value(child.value)}")
        return "\n".join(out) + ("\n" if out else "")

    def snapshot(self) -> Dict[str, Dict[Tuple[Tuple[str, str], ...],
                                         float]]:
        """``{family: {((label, value), ...): value}}`` — the join-side
        view the CI smoke compares against ``/stats``."""
        out: Dict[str, Dict[Tuple[Tuple[str, str], ...], float]] = {}
        for fam in self.families():
            fam_out = out.setdefault(fam.name, {})
            for key, child in fam.children():
                fam_out[tuple(zip(fam.labelnames, key))] = child.value
        return out


# ---------------------------------------------------------------------------
# The strict parser: tests and the CI fleet smoke validate scrapes with it.
# ---------------------------------------------------------------------------

_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?"
    r"\s+(?P<value>[^\s]+)\s*$")


def _parse_labels(raw: str, lineno: int) -> Tuple[Tuple[str, str], ...]:
    """Parse the inside of a ``{...}`` labelset, honouring escapes."""
    pairs: List[Tuple[str, str]] = []
    i, n = 0, len(raw)
    while i < n:
        m = re.match(r'\s*([a-zA-Z_][a-zA-Z0-9_]*)\s*=\s*"', raw[i:])
        if not m:
            raise ValueError(
                f"line {lineno}: malformed label segment {raw[i:]!r}")
        name = m.group(1)
        i += m.end()
        val: List[str] = []
        while True:
            if i >= n:
                raise ValueError(
                    f"line {lineno}: unterminated label value for {name}")
            ch = raw[i]
            if ch == "\\":
                if i + 1 >= n:
                    raise ValueError(
                        f"line {lineno}: dangling escape in {name}")
                nxt = raw[i + 1]
                if nxt == "n":
                    val.append("\n")
                elif nxt in ('"', "\\"):
                    val.append(nxt)
                else:
                    raise ValueError(
                        f"line {lineno}: bad escape \\{nxt} in {name}")
                i += 2
            elif ch == '"':
                i += 1
                break
            elif ch == "\n":
                raise ValueError(
                    f"line {lineno}: raw newline in label value {name}")
            else:
                val.append(ch)
                i += 1
        pairs.append((name, "".join(val)))
        rest = raw[i:].lstrip()
        if rest.startswith(","):
            i = n - len(rest) + 1
        elif rest == "":
            break
        else:
            raise ValueError(
                f"line {lineno}: junk after label value: {rest!r}")
    return tuple(pairs)


def _parse_value(s: str, lineno: int) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    if s == "NaN":
        return math.nan
    try:
        return float(s)
    except ValueError:
        raise ValueError(f"line {lineno}: bad sample value {s!r}")


def parse_exposition(text: str) -> Dict[str, dict]:
    """Strictly parse Prometheus text exposition.

    Returns ``{family_name: {"type": kind, "help": str, "samples":
    {(sample_name, ((label, value), ...)): float}}}``.

    Raises :class:`ValueError` (with a line number) on: samples with no
    preceding ``# TYPE``, unknown types, malformed names or label
    escaping, duplicate sample series, histogram families whose
    cumulative ``le`` buckets are non-monotone, missing ``+Inf``,
    or whose ``_count`` disagrees with the ``+Inf`` bucket.
    """
    families: Dict[str, dict] = {}
    types: Dict[str, str] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                continue  # free-form comment — permitted by the format
            _, kw, name = parts[:3]
            rest = parts[3] if len(parts) > 3 else ""
            if not _NAME_RE.match(name):
                raise ValueError(f"line {lineno}: bad metric name {name!r}")
            fam = families.setdefault(
                name, {"type": None, "help": "", "samples": {}})
            if kw == "TYPE":
                if rest not in ("counter", "gauge", "histogram",
                                "summary", "untyped"):
                    raise ValueError(
                        f"line {lineno}: unknown TYPE {rest!r}")
                if fam["samples"]:
                    raise ValueError(
                        f"line {lineno}: TYPE {name} after its samples")
                if fam["type"] is not None:
                    raise ValueError(f"line {lineno}: duplicate TYPE {name}")
                fam["type"] = rest
                types[name] = rest
            else:
                fam["help"] = rest
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            raise ValueError(f"line {lineno}: malformed sample {line!r}")
        sname = m.group("name")
        labels = (_parse_labels(m.group("labels"), lineno)
                  if m.group("labels") else ())
        value = _parse_value(m.group("value"), lineno)
        base = sname
        for suffix in ("_bucket", "_sum", "_count"):
            stem = sname[:-len(suffix)] if sname.endswith(suffix) else None
            if stem and types.get(stem) == "histogram":
                base = stem
                break
        if base not in families or families[base]["type"] is None:
            raise ValueError(
                f"line {lineno}: sample {sname} has no preceding # TYPE")
        key = (sname, labels)
        samples = families[base]["samples"]
        if key in samples:
            raise ValueError(
                f"line {lineno}: duplicate series {sname}{dict(labels)}")
        samples[key] = value
    _check_histograms(families)
    return families


def _check_histograms(families: Dict[str, dict]) -> None:
    for name, fam in families.items():
        if fam["type"] != "histogram":
            continue
        series: Dict[Tuple[Tuple[str, str], ...],
                     List[Tuple[float, float]]] = {}
        sums: Dict[Tuple[Tuple[str, str], ...], float] = {}
        counts: Dict[Tuple[Tuple[str, str], ...], float] = {}
        for (sname, labels), value in fam["samples"].items():
            if sname == name + "_bucket":
                le = [v for k, v in labels if k == "le"]
                if len(le) != 1:
                    raise ValueError(
                        f"{name}_bucket series missing a single le label")
                rest = tuple((k, v) for k, v in labels if k != "le")
                series.setdefault(rest, []).append(
                    (_parse_value(le[0], 0), value))
            elif sname == name + "_sum":
                sums[labels] = value
            elif sname == name + "_count":
                counts[labels] = value
        for key, buckets in series.items():
            buckets.sort(key=lambda bv: bv[0])
            if not buckets or buckets[-1][0] != math.inf:
                raise ValueError(
                    f"{name}{dict(key)}: histogram missing +Inf bucket")
            last = -math.inf
            for le, v in buckets:
                if v < last:
                    raise ValueError(
                        f"{name}{dict(key)}: bucket counts not "
                        f"monotone at le={_fmt_value(le)}")
                last = v
            if key not in counts or key not in sums:
                raise ValueError(
                    f"{name}{dict(key)}: missing _sum or _count")
            if counts[key] != buckets[-1][1]:
                raise ValueError(
                    f"{name}{dict(key)}: _count {counts[key]} != +Inf "
                    f"bucket {buckets[-1][1]}")
