"""The findings model every auditor in this package reports through.

One flat record type — ``Finding(severity, check, where, detail)`` — so the
CLI can render any detector's output in one table and one JSON artifact,
and ``--strict`` has a single rule to apply (nonzero exit on any
``error``).  Severity vocabulary:

- ``error``   — a violated invariant: wrong-axis collective, model-axis
  gather, undonated update buffer, captured-constant bloat, unlocked
  shared attribute, host sync in a step loop.  Fails ``--strict``.
- ``warning`` — a smell the auditor cannot prove is a bug (e.g. a
  non-weak-typed scalar baked into a jaxpr: one extra compile per distinct
  value, not wrong math).  Reported, never fatal.
- ``info``    — inventory/context lines (collective counts per program).

``check`` is a stable machine-readable slug (``collective-axis``,
``donation``, ``lockset`` ...) — the JSON artifact's join key for trend
dashboards; ``where`` locates the finding (a registry program name or
``file:line``); ``detail`` is the human sentence.
"""
from __future__ import annotations

from typing import List, NamedTuple

SEVERITIES = ("error", "warning", "info")


class Finding(NamedTuple):
    severity: str
    check: str
    where: str
    detail: str

    def as_json(self) -> dict:
        return {"severity": self.severity, "check": self.check,
                "where": self.where, "detail": self.detail}


def make_finding(severity: str, check: str, where: str,
                 detail: str) -> Finding:
    if severity not in SEVERITIES:
        raise ValueError(f"unknown severity {severity!r}; "
                         f"expected one of {SEVERITIES}")
    return Finding(severity, check, where, detail)


def count_by_severity(findings: List[Finding]) -> dict:
    out = {s: 0 for s in SEVERITIES}
    for f in findings:
        out[f.severity] += 1
    return out


def format_table(findings: List[Finding]) -> str:
    """The findings table the CLI prints: severity-sorted, fixed columns.
    An empty list renders the explicit all-clear line (the absence of a
    table must be distinguishable from a crashed auditor)."""
    if not findings:
        return "no findings"
    order = {s: i for i, s in enumerate(SEVERITIES)}
    rows = sorted(findings, key=lambda f: (order[f.severity], f.check,
                                           f.where))
    cols = ("severity", "check", "where", "detail")
    body = [(f.severity, f.check, f.where, f.detail) for f in rows]
    widths = [max(len(c), *(len(r[i]) for r in body))
              for i, c in enumerate(cols[:3])]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths) + "  {}"
    lines = [fmt.format(*cols)]
    lines += [fmt.format(*r) for r in body]
    counts = count_by_severity(findings)
    lines.append(", ".join(f"{counts[s]} {s}{'s' if counts[s] != 1 else ''}"
                           for s in SEVERITIES if counts[s]))
    return "\n".join(lines)
