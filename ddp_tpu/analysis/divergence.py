"""AST lint for multi-host control-flow divergence.

SPMD's contract is that every process runs the SAME sequence of
collectives.  A collective (a ``psum``, a process-level barrier, the
preemption stop decision, the straggler gather) that is only *sometimes*
reached — under a rank check, inside an exception handler, behind
queue/timing state — is the classic whole-pod hang: the hosts that enter
it wait forever for the hosts that didn't.  This pass flags exactly that
shape, host-side (the traced SPMD bodies are uniform by construction —
``lax.cond`` traces both branches — and belong to the jaxpr auditor, so
``step.py``/``zero.py``/``epoch.py``/``layers.py`` are excluded here).

Two rules, per function:

1. **Guarded collective** — a collective call lexically under a
   condition the pass cannot prove uniform across hosts (anything but
   constants, ``process_count``/``device_count``-style topology reads,
   and locals derived only from those).  ``except`` handlers are
   host-local by definition (one host's I/O error is not another's).
   A collective in an ``if``'s TEST position is fine — the test itself
   executes unconditionally (the preemption guard's
   ``if _process_any(mesh, local):`` is the sanctioned pattern: decide
   *collectively*, then branch).
2. **Host-local early exit** — a ``return`` under a non-uniform
   condition, followed later in the same function by a collective: the
   host that returned early skips a collective the others enter.  Same
   deadlock, no lexical nesting.

Deliberate exceptions carry ``# analysis: divergence-ok(<why all hosts
agree>)`` on the flagged line, the line above, or the guard line — the
same greppable decision-trail vocabulary as ``host-sync-ok`` /
``unlocked-ok``.  The annotation's argument should say why the condition
is in fact uniform (constructor-time config identical on every host, a
value that is itself the result of a collective, ...).
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, NamedTuple, Optional, Tuple

from .findings import Finding, make_finding

SCAN_PACKAGES = ("train", "resilience", "obs", "parallel", "serve", "data")

# Traced-SPMD module basenames: uniform by construction, owned by the
# jaxpr auditor (collectives there live under jnp/lax control flow that
# traces both sides).
EXCLUDE_BASENAMES = ("step.py", "zero.py", "epoch.py", "layers.py")

# A call is "a collective" when its last dotted component is one of
# these: the jax named-axis collectives plus this codebase's host-level
# coordination helpers (each is, or transitively runs, a cross-process
# rendezvous).
COLLECTIVE_CALLS = frozenset((
    "psum", "pmean", "pmax", "pmin", "all_gather", "reduce_scatter",
    "psum_scatter", "ppermute", "all_to_all", "pbroadcast",
    "process_allgather", "sync_global_devices", "broadcast_one_to_all",
    # repo coordination helpers (resilience/, obs/):
    "should_stop", "_process_any", "straggler_report",
    "epoch_straggler_record", "_gather_host_rows",
))

# Calls whose result is identical on every host: mesh topology reads and
# the runtime-semantics probe.  (``process_index`` is deliberately NOT
# here — a rank check is the canonical divergent condition.)
UNIFORM_CALLS = frozenset(("process_count", "device_count",
                           "local_device_count", "vma_semantics"))

_OK_RE = re.compile(r"#\s*analysis:\s*divergence-ok\(([^)]*)\)")


class _Guard(NamedTuple):
    lineno: int
    reason: str


class _Exit(NamedTuple):
    lineno: int
    guard: _Guard


def _annotated_ok(lines: List[str], *linenos: int) -> bool:
    for ln in linenos:
        for cand in (ln, ln - 1):
            if 1 <= cand <= len(lines) and _OK_RE.search(lines[cand - 1]):
                return True
    return False


def _call_name(node: ast.Call) -> str:
    parts: List[str] = []
    f = node.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return ".".join(reversed(parts))


def _describe(node: ast.AST, limit: int = 48) -> str:
    try:
        text = ast.unparse(node)
    except Exception:
        text = type(node).__name__
    return text if len(text) <= limit else text[:limit - 3] + "..."


def _own_statements(fn: ast.AST):
    """Walk a function's own statements, not those of nested defs."""
    stack = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if not isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.Lambda)):
                stack.append(child)


_NONUNIFORM = ast.Call(func=ast.Name(id="<nonuniform>", ctx=ast.Load()),
                       args=[], keywords=[])


def _uniform_names(fn: ast.AST) -> frozenset:
    """Locals provably uniform: assigned only from uniform expressions
    (fixpoint, so ``multi = dist.process_count() > 1`` then
    ``quiet = not multi`` both qualify).  A name bound by a loop target,
    an augmented assignment, tuple unpacking, or a ``with ... as`` is
    never provable."""
    assigns: Dict[str, List[ast.AST]] = {}

    def taint(target: ast.AST) -> None:
        for t in ast.walk(target):
            if isinstance(t, ast.Name):
                assigns.setdefault(t.id, []).append(_NONUNIFORM)

    for node in _own_statements(fn):
        if isinstance(node, ast.Assign):
            for tgt in node.targets:
                if isinstance(tgt, ast.Name):
                    assigns.setdefault(tgt.id, []).append(node.value)
                else:
                    taint(tgt)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            if isinstance(node.target, ast.Name):
                assigns.setdefault(node.target.id, []).append(node.value)
            else:
                taint(node.target)
        elif isinstance(node, (ast.AugAssign, ast.For)):
            taint(node.target)
        elif isinstance(node, ast.With):
            for item in node.items:
                if item.optional_vars is not None:
                    taint(item.optional_vars)
        elif isinstance(node, ast.NamedExpr):
            taint(node.target)
    uniform: set = set()
    for _ in range(len(assigns) + 1):
        changed = False
        for name, values in assigns.items():
            if name in uniform:
                continue
            if all(_is_uniform(v, frozenset(uniform)) for v in values):
                uniform.add(name)
                changed = True
        if not changed:
            break
    return frozenset(uniform)


def _is_uniform(node: ast.AST, uniform_names: frozenset) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Name):
        return node.id in uniform_names
    if isinstance(node, ast.UnaryOp):
        return _is_uniform(node.operand, uniform_names)
    if isinstance(node, ast.BoolOp):
        return all(_is_uniform(v, uniform_names) for v in node.values)
    if isinstance(node, ast.Compare):
        return (_is_uniform(node.left, uniform_names)
                and all(_is_uniform(c, uniform_names)
                        for c in node.comparators))
    if isinstance(node, ast.BinOp):
        return (_is_uniform(node.left, uniform_names)
                and _is_uniform(node.right, uniform_names))
    if isinstance(node, ast.IfExp):
        return all(_is_uniform(n, uniform_names)
                   for n in (node.test, node.body, node.orelse))
    if isinstance(node, ast.Call):
        name = _call_name(node).rsplit(".", 1)[-1]
        return (name in UNIFORM_CALLS
                and all(_is_uniform(a, uniform_names) for a in node.args))
    return False


class _FunctionScan:
    def __init__(self, path: str, lines: List[str], fn: ast.AST):
        self.path = path
        self.lines = lines
        self.fn = fn
        self.uniform = _uniform_names(fn)
        self.findings: List[Finding] = []
        self.exits: List[_Exit] = []
        self.unguarded: List[Tuple[int, str]] = []

    def run(self) -> List[Finding]:
        self._scan(self.fn.body, [])
        for lineno, name in self.unguarded:
            prior = [e for e in self.exits if e.lineno < lineno]
            if not prior:
                continue
            e = prior[0]
            if _annotated_ok(self.lines, lineno, e.lineno, e.guard.lineno):
                continue
            self.findings.append(make_finding(
                "error", "divergence", f"{self.path}:{lineno}",
                f"collective {name}() is only reached past a host-local "
                f"early return at line {e.lineno} (condition at line "
                f"{e.guard.lineno}: {e.guard.reason}) — a host that "
                "returns early skips a collective the others enter and "
                "the pod hangs; make the exit condition uniform or "
                "annotate '# analysis: divergence-ok(why all hosts "
                "agree)'"))
        return self.findings

    # -- statement walk ---------------------------------------------------

    def _scan(self, stmts, guards: List[_Guard]) -> None:
        for node in stmts:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue                       # scanned as its own function
            if isinstance(node, ast.If):
                self._check_expr(node.test, guards)
                new = guards
                if not _is_uniform(node.test, self.uniform):
                    new = guards + [_Guard(node.lineno,
                                           f"`{_describe(node.test)}`")]
                self._scan(node.body, new)
                self._scan(node.orelse, new)
            elif isinstance(node, ast.While):
                self._check_expr(node.test, guards)
                new = guards
                if not _is_uniform(node.test, self.uniform):
                    new = guards + [_Guard(node.lineno,
                                           f"`{_describe(node.test)}`")]
                self._scan(node.body, new)
                self._scan(node.orelse, new)
            elif isinstance(node, ast.For):
                self._check_expr(node.iter, guards)
                self._scan(node.body, guards)
                self._scan(node.orelse, guards)
            elif isinstance(node, ast.Try):
                self._scan(node.body, guards)
                for handler in node.handlers:
                    hg = guards + [_Guard(
                        handler.lineno,
                        "except handler (a host-local failure path)")]
                    self._scan(handler.body, hg)
                self._scan(node.orelse, guards)
                self._scan(node.finalbody, guards)
            elif isinstance(node, ast.With):
                for item in node.items:
                    self._check_expr(item.context_expr, guards)
                self._scan(node.body, guards)
            elif isinstance(node, ast.Return):
                if guards:
                    self.exits.append(_Exit(node.lineno, guards[-1]))
                if node.value is not None:
                    self._check_expr(node.value, guards)
            else:
                self._check_expr(node, guards)

    def _check_expr(self, node: ast.AST, guards: List[_Guard]) -> None:
        for call in ast.walk(node):
            if not isinstance(call, ast.Call):
                continue
            name = _call_name(call)
            if name.rsplit(".", 1)[-1] not in COLLECTIVE_CALLS:
                continue
            if not guards:
                self.unguarded.append((call.lineno, name))
                continue
            g = guards[-1]
            if _annotated_ok(self.lines, call.lineno, g.lineno):
                continue
            self.findings.append(make_finding(
                "error", "divergence", f"{self.path}:{call.lineno}",
                f"collective {name}() under a host-local condition "
                f"(line {g.lineno}: {g.reason}) — hosts that disagree on "
                "it run different collective sequences and the pod "
                "hangs; decide collectively first (the "
                "`if _process_any(...)` pattern), make the condition "
                "uniform, or annotate '# analysis: divergence-ok(why "
                "all hosts agree)'"))


def _functions(tree: ast.AST):
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def scan_source(path: str, source: str) -> List[Finding]:
    """Divergence findings for one module's source text."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [make_finding("warning", "divergence", path,
                             f"unparseable: {e}")]
    lines = source.splitlines()
    out: List[Finding] = []
    for fn in _functions(tree):
        out.extend(_FunctionScan(path, lines, fn).run())
    return out


def scan_packages(root: str,
                  packages: Tuple[str, ...] = SCAN_PACKAGES,
                  exclude: Tuple[str, ...] = EXCLUDE_BASENAMES
                  ) -> List[Finding]:
    """Walk the given subpackages of the ddp_tpu package root."""
    out: List[Finding] = []
    for pkg in packages:
        pkg_dir = os.path.join(root, pkg)
        if not os.path.isdir(pkg_dir):
            continue
        for dirpath, _dirs, files in os.walk(pkg_dir):
            for fname in sorted(files):
                if not fname.endswith(".py") or fname in exclude:
                    continue
                fpath = os.path.join(dirpath, fname)
                rel = os.path.relpath(fpath, os.path.dirname(root))
                with open(fpath, "r", encoding="utf-8") as fh:
                    out.extend(scan_source(rel, fh.read()))
    return out
