"""AST pass for implicit host syncs inside step/epoch loops.

JAX dispatch is asynchronous: the step loop stays ahead of the device
precisely as long as nothing in the loop body forces a device->host
transfer.  One ``jax.device_get`` (or a ``float()`` on a device scalar,
or ``np.asarray`` on a device array) inside the hot loop serializes every
iteration on the previous step's completion — the classic silent 2x.
This pass walks ``train/``, ``data/``, ``serve/`` and flags, inside any
``for``/``while`` body:

- ``jax.device_get(...)`` / bare ``device_get(...)`` — always a sync;
- ``float(x)`` / ``int(x)`` / ``x.item()`` / ``np.asarray(x)`` /
  ``np.array(x)`` where ``x`` was assigned IN THE SAME LOOP BODY from a
  call whose name ends in ``step``/``forward``/``apply``/``fwd`` — the
  device-value dataflow we can prove statically (the trainer's
  ``state, loss = self.train_step(...)`` shape) without drowning the
  report in false positives on host arrays.

Deliberate syncs (an epoch-boundary flush, a d2h span in the serve
pipeline) carry the annotation ``# analysis: host-sync-ok(<reason>)`` on
the statement line or the line above; the annotation is the audit trail
that someone DECIDED the sync is off the hot path.
"""
from __future__ import annotations

import ast
import os
import re
from typing import Iterator, List, Tuple

from .findings import Finding, make_finding

SCAN_PACKAGES = ("train", "data", "serve")
DEVICE_PRODUCER_SUFFIXES = ("step", "forward", "apply", "fwd")
_OK_RE = re.compile(r"#\s*analysis:\s*host-sync-ok\(([^)]*)\)")


def _annotated_ok(lines: List[str], lineno: int) -> bool:
    """True when line ``lineno`` (1-based) or the line above carries the
    host-sync-ok annotation."""
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines) and _OK_RE.search(lines[ln - 1]):
            return True
    return False


def _call_name(node: ast.Call) -> str:
    """Dotted name of a call target, '' when not a plain name/attr."""
    parts: List[str] = []
    f = node.func
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
    return ".".join(reversed(parts))


def _is_device_get(name: str) -> bool:
    return name.endswith("device_get")


def _is_host_cast(name: str) -> bool:
    return name in ("float", "int") or name.endswith((".item",
                                                      "np.asarray",
                                                      "np.array",
                                                      "numpy.asarray",
                                                      "numpy.array"))


def _assigned_names(node: ast.AST) -> Iterator[str]:
    for t in ast.walk(node):
        if isinstance(t, ast.Name) and isinstance(t.ctx, ast.Store):
            yield t.id


def _loops(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.For, ast.While)):
            yield node


def _device_names_in_loop(loop: ast.AST) -> set:
    """Names assigned inside this loop body from a device-producing call
    (``state, loss = self.train_step(...)``)."""
    names: set = set()
    for node in ast.walk(loop):
        if isinstance(node, ast.Assign) and isinstance(node.value,
                                                       ast.Call):
            callee = _call_name(node.value)
            last = callee.rsplit(".", 1)[-1]
            if last.endswith(DEVICE_PRODUCER_SUFFIXES):
                for tgt in node.targets:
                    names.update(_assigned_names(tgt))
    return names


def scan_source(path: str, source: str) -> List[Finding]:
    """Host-sync findings for one module's source text."""
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [make_finding("warning", "host-sync", path,
                             f"unparseable: {e}")]
    lines = source.splitlines()
    out: List[Finding] = []
    seen: set = set()
    for loop in _loops(tree):
        device_names = _device_names_in_loop(loop)
        for node in ast.walk(loop):
            if not isinstance(node, ast.Call) or node.lineno in seen:
                continue
            name = _call_name(node)
            where = f"{path}:{node.lineno}"
            if _is_device_get(name):
                if not _annotated_ok(lines, node.lineno):
                    seen.add(node.lineno)
                    out.append(make_finding(
                        "error", "host-sync", where,
                        f"{name}() inside a loop — a device->host sync "
                        "per iteration serializes the step loop on device "
                        "completion; hoist it past the loop (or annotate "
                        "'# analysis: host-sync-ok(reason)' if it is "
                        "deliberately off the hot path)"))
            elif _is_host_cast(name) and node.args:
                arg = node.args[0]
                if (isinstance(arg, ast.Name)
                        and arg.id in device_names
                        and not _annotated_ok(lines, node.lineno)):
                    seen.add(node.lineno)
                    out.append(make_finding(
                        "error", "host-sync", where,
                        f"{name}({arg.id}) inside a loop, on a value "
                        "produced by a jitted step/forward in the same "
                        "loop body — an implicit per-iteration device "
                        "sync; keep it on device (append the raw value) "
                        "and read the batch once after the loop"))
    return out


def scan_packages(root: str,
                  packages: Tuple[str, ...] = SCAN_PACKAGES
                  ) -> List[Finding]:
    """Walk the given subpackages of the ddp_tpu package root."""
    out: List[Finding] = []
    for pkg in packages:
        pkg_dir = os.path.join(root, pkg)
        if not os.path.isdir(pkg_dir):
            continue
        for dirpath, _dirs, files in os.walk(pkg_dir):
            for fname in sorted(files):
                if not fname.endswith(".py"):
                    continue
                fpath = os.path.join(dirpath, fname)
                rel = os.path.relpath(fpath, os.path.dirname(root))
                with open(fpath, "r", encoding="utf-8") as fh:
                    out.extend(scan_source(rel, fh.read()))
    return out
