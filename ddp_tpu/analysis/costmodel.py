"""Static cost model: per-program FLOP / byte / collective-volume
accounting over traced jaxprs.

PR 7's auditor proves a program is *shaped* right (collectives on the
right axes, no captured consts); this pass says how *big* it is — the
quantities the measured-cost auto-sharding planner (ROADMAP 5, arXiv
2004.13336) and pipeline stage partitioning (ROADMAP 1, Mesh-TensorFlow's
named-axis cost reasoning, arXiv 1811.02084) take as inputs.  Everything
works on the abstract trace: no XLA compile, no device memory.

Accounting rules, per equation (depth-first through ``pjit`` /
``shard_map`` / ``custom_*`` sub-jaxprs, so the unit is the PER-DEVICE
program — the shard_map body's shapes are per-shard, which is the unit a
step's wall-clock is set by):

- ``conv_general_dilated`` — ``2 * prod(out_shape) * (kernel_in_feat *
  prod(kernel_spatial))`` from the equation's own ConvDimensionNumbers.
  The formula is direction-agnostic: forward, input-gradient and
  weight-gradient convs all carry their contraction in the rhs spec, so
  autodiff's transpose convs account exactly.
- ``dot_general`` — ``2 * B * M * N * K`` from the equation's
  dimension_numbers (batch dims B, contraction K, remaining M x N).
- reductions (``reduce_sum`` ...) — one flop per INPUT element.
- data movement (reshape/broadcast/slice/convert/...) — zero flops.
- everything else — one flop per output element (``elementwise``).
- collectives (``jaxpr_audit.COLLECTIVE_PRIMITIVES``) — zero flops, but
  counted with their per-device payload (operand bytes) per named axis:
  the volume term a ring all-reduce's time is linear in.
- ``scan`` multiplies its body by ``length``; ``cond`` takes the most
  expensive branch; ``while`` counts one trip and flags the program as
  having an unknown trip count.

``bytes`` is operand+result bytes summed over leaf equations — a proxy
for memory traffic (every buffer assumed touched once per use, no cache
modeling), the roofline denominator next to flops.

Budgets: ``make_budgets`` snapshots the per-program table into the
``BUDGETS.json`` schema; ``check_budgets`` diffs a fresh table against it
and emits ``budget`` error findings on regressions past the tolerance —
the CI gate that turns "this PR made the train step 30% more expensive"
into a red build instead of archaeology.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .findings import Finding, make_finding
from .jaxpr_audit import COLLECTIVE_PRIMITIVES, MIB, _sub_jaxprs

# Pure data-movement / metadata primitives: zero flops (bytes still count).
_ZERO_FLOP = frozenset((
    "reshape", "broadcast_in_dim", "transpose", "squeeze", "expand_dims",
    "slice", "dynamic_slice", "dynamic_update_slice", "concatenate",
    "pad", "rev", "iota", "convert_element_type", "bitcast_convert_type",
    "copy", "device_put", "sharding_constraint", "stop_gradient",
    "gather", "scatter", "split", "axis_index", "pvary",
))

_REDUCE = frozenset(("reduce_sum", "reduce_max", "reduce_min",
                     "reduce_prod", "reduce_and", "reduce_or",
                     "argmax", "argmin"))

# The budget file's per-program metrics, in check order.
BUDGET_METRICS = ("flops", "bytes", "peak_live_bytes",
                  "collective_payload_bytes")
DEFAULT_TOLERANCE_PCT = 10.0

FLOP_CLASSES = ("conv", "dot", "elementwise", "reduce")


class Cost:
    """One program's (or sub-jaxpr's) cost rollup.  Mutable accumulator;
    ``+`` and ``scaled`` return new instances."""

    __slots__ = ("flops", "bytes", "by_class", "collectives",
                 "unknown_trip_loops")

    def __init__(self) -> None:
        self.flops = 0
        self.bytes = 0
        self.by_class: Dict[str, int] = {c: 0 for c in FLOP_CLASSES}
        # {(primitive, axes): [count, payload_bytes]}
        self.collectives: Dict[Tuple[str, Tuple[str, ...]], List[int]] = {}
        self.unknown_trip_loops = 0

    def _merge(self, other: "Cost", k: int = 1) -> "Cost":
        self.flops += other.flops * k
        self.bytes += other.bytes * k
        for c in FLOP_CLASSES:
            self.by_class[c] += other.by_class[c] * k
        for key, (n, b) in other.collectives.items():
            cur = self.collectives.setdefault(key, [0, 0])
            cur[0] += n * k
            cur[1] += b * k
        self.unknown_trip_loops += other.unknown_trip_loops
        return self

    def __add__(self, other: "Cost") -> "Cost":
        return Cost()._merge(self)._merge(other)

    def scaled(self, k: int) -> "Cost":
        return Cost()._merge(self, k)

    @property
    def collective_count(self) -> int:
        return sum(n for n, _ in self.collectives.values())

    @property
    def collective_payload_bytes(self) -> int:
        return sum(b for _, b in self.collectives.values())

    def as_json(self) -> dict:
        return {
            "flops": int(self.flops),
            "bytes": int(self.bytes),
            "flops_by_class": {c: int(v) for c, v in self.by_class.items()},
            "collectives": [
                {"primitive": p, "axes": list(a),
                 "count": int(n), "payload_bytes": int(b)}
                for (p, a), (n, b) in sorted(self.collectives.items())],
            "collective_count": int(self.collective_count),
            "collective_payload_bytes": int(self.collective_payload_bytes),
            "unknown_trip_loops": int(self.unknown_trip_loops),
        }

    def budget_row(self) -> dict:
        return {"flops": int(self.flops), "bytes": int(self.bytes),
                "collective_count": int(self.collective_count),
                "collective_payload_bytes":
                    int(self.collective_payload_bytes)}


def _dtype_bytes(dtype) -> int:
    try:
        return int(np.dtype(dtype).itemsize)
    except TypeError:     # extended dtypes (prng keys): count the backing
        return int(getattr(dtype, "itemsize", 4))


def aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * _dtype_bytes(dtype)


def _var_bytes(v) -> int:
    if hasattr(v, "val"):             # Literal: inlined scalar, no buffer
        return 0
    return aval_bytes(getattr(v, "aval", None))


def _out_elems(eqn) -> int:
    return sum(int(np.prod(v.aval.shape, dtype=np.int64))
               for v in eqn.outvars if hasattr(v, "aval"))


def _in_elems(eqn) -> int:
    return sum(int(np.prod(v.aval.shape, dtype=np.int64))
               for v in eqn.invars
               if not hasattr(v, "val") and hasattr(v, "aval"))


def _conv_flops(eqn) -> int:
    """2 * output elements * contraction size, from the equation's own
    ConvDimensionNumbers — exact for fwd, dgrad and wgrad convs alike
    (grouped convs: the kernel's in_feat dim is already cin/groups)."""
    dn = eqn.params["dimension_numbers"]
    rhs_shape = eqn.invars[1].aval.shape
    rhs_spec = dn.rhs_spec              # (out_feat, in_feat, *spatial)
    contraction = rhs_shape[rhs_spec[1]]
    for d in rhs_spec[2:]:
        contraction *= rhs_shape[d]
    out = int(np.prod(eqn.outvars[0].aval.shape, dtype=np.int64))
    return 2 * out * int(contraction)


def _dot_flops(eqn) -> int:
    (lc, rc), (lb, rb) = eqn.params["dimension_numbers"]
    lhs = eqn.invars[0].aval.shape
    rhs = eqn.invars[1].aval.shape
    k = int(np.prod([lhs[d] for d in lc], dtype=np.int64)) if lc else 1
    b = int(np.prod([lhs[d] for d in lb], dtype=np.int64)) if lb else 1
    m = int(np.prod([lhs[d] for d in range(len(lhs))
                     if d not in set(lc) | set(lb)], dtype=np.int64))
    n = int(np.prod([rhs[d] for d in range(len(rhs))
                     if d not in set(rc) | set(rb)], dtype=np.int64))
    return 2 * b * m * n * k


def _collective_axes(eqn) -> Tuple[str, ...]:
    raw = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if not isinstance(raw, (tuple, list)):
        raw = (raw,)
    return tuple(a for a in raw if isinstance(a, str))


def cost_of_eqn(eqn) -> Cost:
    name = eqn.primitive.name
    if name == "scan":
        body = cost_of_jaxpr(eqn.params["jaxpr"].jaxpr)
        return body.scaled(int(eqn.params["length"]))
    if name == "while":
        c = Cost()
        for sub in _sub_jaxprs(eqn.params):     # cond + body, one trip
            c._merge(cost_of_jaxpr(sub))
        c.unknown_trip_loops += 1
        return c
    if name == "cond":
        branches = [cost_of_jaxpr(sub) for sub in _sub_jaxprs(eqn.params)]
        return max(branches, key=lambda c: (c.flops, c.bytes),
                   default=Cost())
    subs = list(_sub_jaxprs(eqn.params))
    if subs:                                    # pjit / shard_map / custom_*
        c = Cost()
        for sub in subs:
            c._merge(cost_of_jaxpr(sub))
        return c

    c = Cost()
    c.bytes = sum(_var_bytes(v) for v in eqn.invars) + \
        sum(_var_bytes(v) for v in eqn.outvars)
    if name == "conv_general_dilated":
        c.flops = _conv_flops(eqn)
        c.by_class["conv"] = c.flops
    elif name == "dot_general":
        c.flops = _dot_flops(eqn)
        c.by_class["dot"] = c.flops
    elif name in COLLECTIVE_PRIMITIVES:
        payload = sum(_var_bytes(v) for v in eqn.invars)
        c.collectives[(name, _collective_axes(eqn))] = [1, payload]
    elif name in _REDUCE:
        c.flops = _in_elems(eqn)
        c.by_class["reduce"] = c.flops
    elif name not in _ZERO_FLOP:
        c.flops = _out_elems(eqn)
        c.by_class["elementwise"] = c.flops
    return c


def cost_of_jaxpr(jaxpr) -> Cost:
    total = Cost()
    for eqn in jaxpr.eqns:
        total._merge(cost_of_eqn(eqn))
    return total


def program_cost(closed_jaxpr) -> Cost:
    """Per-device cost of one traced program (the shard_map body's
    per-shard shapes are what the walk sees)."""
    return cost_of_jaxpr(closed_jaxpr.jaxpr)


def _fmt(n: float, unit: str = "") -> str:
    for scale, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "K")):
        if abs(n) >= scale:
            return f"{n / scale:.2f}{suffix}{unit}"
    return f"{n:.0f}{unit}"


def cost_summary(cost: Cost, peak_live: Optional[int] = None) -> str:
    """One human line per program for the findings table."""
    dominant = ", ".join(
        f"{c} {100.0 * v / max(cost.flops, 1):.0f}%"
        for c, v in sorted(cost.by_class.items(), key=lambda kv: -kv[1])
        if v)
    parts = [f"flops {_fmt(cost.flops)} ({dominant or 'none'})",
             f"bytes {cost.bytes / MIB:.1f} MiB"]
    if peak_live is not None:
        parts.append(f"peak-live {peak_live / MIB:.1f} MiB")
    parts.append(
        f"collectives x{cost.collective_count}, "
        f"{cost.collective_payload_bytes / MIB:.2f} MiB payload"
        if cost.collective_count else "collective-free")
    if cost.unknown_trip_loops:
        parts.append(f"{cost.unknown_trip_loops} unknown-trip loop(s), "
                     "counted as one iteration")
    return " | ".join(parts)


# ---------------------------------------------------------------------------
# Per-layer forward costs (the plan table's predicted-cost column).
# ---------------------------------------------------------------------------

def layer_forward_costs(model, plan, params, batch_stats,
                        *, image_shape=(32, 32, 3)) -> Optional[Dict[str,
                                                                     int]]:
    """``{recipe layer path: forward flops per image}`` by tracing the
    UNSHARDED forward at batch 1 and matching its conv/dot equations
    positionally to the recipe — valid exactly when the counts align
    (deepnn: 4 convs + 2 dots = 6 recipe layers, in network order).
    Returns None when they don't (a model whose recipe doesn't map 1:1
    onto heavy ops gets no cost column rather than a wrong one)."""
    import jax
    import jax.numpy as jnp

    from .jaxpr_audit import iter_eqns

    def _sds(tree):
        return jax.tree_util.tree_map(
            lambda x: jax.ShapeDtypeStruct(jnp.shape(x),
                                           jnp.result_type(x)), tree)

    x = jax.ShapeDtypeStruct((1,) + tuple(image_shape), jnp.float32)
    closed = jax.make_jaxpr(
        lambda p, s, im: model.apply(p, s, im, train=False)[0])(
            _sds(params), _sds(batch_stats), x)
    heavy = [e for e in iter_eqns(closed.jaxpr)
             if e.primitive.name in ("conv_general_dilated", "dot_general")]
    if len(heavy) != len(plan.layers):
        return None
    out: Dict[str, int] = {}
    for (path, _style), eqn in zip(plan.layers, heavy):
        out[path] = (_conv_flops(eqn)
                     if eqn.primitive.name == "conv_general_dilated"
                     else _dot_flops(eqn))
    return out


# ---------------------------------------------------------------------------
# Budgets: BUDGETS.json make / check.
# ---------------------------------------------------------------------------

def make_budgets(table: Dict[str, dict], model: str,
                 mesh_shape: Tuple[int, int],
                 tolerance_pct: float = DEFAULT_TOLERANCE_PCT) -> dict:
    """The BUDGETS.json document for one (model, mesh) audit: the current
    per-program metrics become the ceilings future runs diff against."""
    return {
        "model": model,
        "mesh_shape": list(mesh_shape),
        "tolerance_pct": tolerance_pct,
        "programs": {
            name: {m: int(row[m]) for m in BUDGET_METRICS if m in row}
            for name, row in sorted(table.items())},
    }


def check_budgets(table: Dict[str, dict], budgets: dict, model: str,
                  mesh_shape: Tuple[int, int],
                  partial: bool = False) -> List[Finding]:
    """Diff a fresh cost table against a budget file.

    Applicability first: budgets are per (model, mesh shape); a run on a
    different model or mesh gets one ``info`` finding and no gate (the
    numbers aren't comparable).  Then, per budgeted program x metric: a
    value past ``budget * (1 + tolerance_pct/100)`` is an ``error`` (the
    CI regression gate); a program missing on either side is a
    ``warning`` pointing at ``--write-budgets`` re-baselining —
    suppressed under ``partial`` (a ``--programs`` subset run legally
    builds only part of the registry)."""
    out: List[Finding] = []
    b_model = budgets.get("model")
    b_mesh = list(budgets.get("mesh_shape") or ())
    if b_model != model or b_mesh != list(mesh_shape):
        return [make_finding(
            "info", "budget", "budgets",
            f"budget file is for {b_model!r} on mesh {b_mesh}, this audit "
            f"is {model!r} on {list(mesh_shape)} — budget gate skipped "
            "(not comparable)")]
    tol = float(budgets.get("tolerance_pct", DEFAULT_TOLERANCE_PCT))
    programs = budgets.get("programs", {})
    for name, brow in sorted(programs.items()):
        row = table.get(name)
        if row is None:
            if not partial:
                out.append(make_finding(
                    "warning", "budget", name,
                    "budgeted program was not built in this audit — "
                    "stale budget entry; re-baseline with "
                    "--write-budgets"))
            continue
        for metric in BUDGET_METRICS:
            if metric not in brow or metric not in row:
                continue
            cur, limit = int(row[metric]), int(brow[metric])
            ceiling = limit * (1.0 + tol / 100.0)
            if cur > ceiling:
                pct = 100.0 * (cur - limit) / max(limit, 1)
                out.append(make_finding(
                    "error", "budget", name,
                    f"{metric} {_fmt(cur)} exceeds budget {_fmt(limit)} "
                    f"by {pct:.1f}% (tolerance {tol:.0f}%) — an intended "
                    "cost change must re-baseline BUDGETS.json with "
                    "--write-budgets; an unintended one is a regression"))
    for name in sorted(set(table) - set(programs)):
        out.append(make_finding(
            "warning", "budget", name,
            "program has no budget entry — add one with --write-budgets"))
    return out
