"""Lockset lint for the threaded runtime subsystems.

The threaded modules (data/prefetch.py, serve/batcher.py,
serve/engine.py, the checkpoint writer thread in train/trainer.py,
resilience/watchdog.py) each follow the same discipline: shared mutable
attributes are guarded by a named ``threading.Lock``, thread-safe
primitives (Queue/Event/deque) synchronize themselves, and the few
deliberately lock-free shared values (a monotonic heartbeat float, an
error slot read only after ``join()``) are DOCUMENTED races.  This lint
makes the discipline machine-checked from the AST:

**Annotation vocabulary** (inline comments on the ``__init__`` assignment
line, or the line above):

- ``# analysis: shared-under(<lock>)`` — every read/write of the
  attribute outside ``__init__``'s top level must happen lexically inside
  ``with self.<lock>:``; any access outside is an ``error``.
- ``# analysis: unlocked-ok(<reason>)`` — the attribute is shared but
  deliberately unsynchronized (or synchronized by something the AST
  can't see, e.g. ``Thread.join``); the lint skips it, the reason is the
  audit trail.

**Discovery** (no annotation needed): a class that spawns a thread
(``threading.Thread(target=self.m)`` or ``target=<nested fn>``) gets its
methods partitioned into worker-reachable and caller-reachable sets via
the intra-class call graph.  An attribute that is MUTATED outside
``__init__``, accessed from BOTH sides, is not a lock/thread-safe
primitive, carries no annotation, and has at least one access under no
lock at all, is flagged as a lock-free shared attribute — the data-race
shape, caught before a chip run instead of in one.

Nested functions handed to ``Thread(target=...)`` (the trainer's
checkpoint ``write()`` closure) count as worker context; ``nonlocal``
declarations inside such a function are flagged too (a shared mutable
local with no lock to name).
"""
from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

from .findings import Finding, make_finding

# Modules whose classes are held to the lockset discipline.
SCAN_MODULES = ("data/prefetch.py", "serve/batcher.py", "serve/engine.py",
                "serve/router.py", "serve/fleet.py",
                "train/trainer.py", "train/checkpoint.py",
                "resilience/watchdog.py", "resilience/store.py",
                "obs/registry.py")

_ANN_RE = re.compile(
    r"#\s*analysis:\s*(shared-under|unlocked-ok)\(([^)]*)\)")

LOCK_CTORS = {"Lock", "RLock"}
# Constructors whose instances synchronize themselves (or are only ever
# touched through their own thread-safe methods).
SAFE_CTORS = {"Lock", "RLock", "Event", "Condition", "Semaphore",
              "BoundedSemaphore", "Barrier", "Queue", "SimpleQueue",
              "LifoQueue", "PriorityQueue", "deque", "Thread",
              "ThreadPoolExecutor"}


class Access(NamedTuple):
    attr: str
    method: str          # defining method name (worker closures keep it)
    lineno: int
    is_store: bool
    locks: frozenset     # lock attr names lexically held
    worker: bool         # True when reached from a thread target closure
    init_top: bool       # top-level __init__ statement (pre-publication)


def _last_name(node: ast.AST) -> str:
    while isinstance(node, ast.Attribute):
        node = node.attr if isinstance(node.attr, ast.AST) else node.attr
        break
    if isinstance(node, str):
        return node
    return ""


def _ctor_name(value: ast.AST) -> str:
    """Class name of ``self.x = <Ctor>(...)``, '' otherwise."""
    if not isinstance(value, ast.Call):
        return ""
    f = value.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _self_attr(node: ast.AST) -> Optional[str]:
    if (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _annotation_on(lines: List[str], lineno: int):
    for ln in (lineno, lineno - 1):
        if 1 <= ln <= len(lines):
            m = _ANN_RE.search(lines[ln - 1])
            if m:
                return m.group(1), m.group(2).strip()
    return None


class _ClassInfo:
    def __init__(self, node: ast.ClassDef, lines: List[str]):
        self.node = node
        self.name = node.name
        self.lines = lines
        self.lock_attrs: Set[str] = set()
        self.safe_attrs: Set[str] = set()
        self.annotations: Dict[str, Tuple[str, str]] = {}
        self.accesses: List[Access] = []
        self.thread_targets: Set[str] = set()   # method names
        self.calls: Dict[str, Set[str]] = {}    # method -> self.m() called
        self.nonlocal_findings: List[Tuple[str, int]] = []
        self._collect()

    # -- collection --------------------------------------------------------

    def _collect(self) -> None:
        for item in self.node.body:
            if isinstance(item, ast.FunctionDef):
                if item.name == "__init__":
                    self._collect_init_decls(item)
                self._collect_method(item)

    def _collect_init_decls(self, init: ast.FunctionDef) -> None:
        for stmt in init.body:
            targets = []
            if isinstance(stmt, ast.Assign):
                targets, value = stmt.targets, stmt.value
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                targets, value = [stmt.target], stmt.value
            else:
                continue
            ctor = _ctor_name(value)
            for tgt in targets:
                attr = _self_attr(tgt)
                if attr is None:
                    continue
                if ctor in LOCK_CTORS:
                    self.lock_attrs.add(attr)
                if ctor in SAFE_CTORS:
                    self.safe_attrs.add(attr)
                ann = _annotation_on(self.lines, stmt.lineno)
                if ann is not None:
                    self.annotations[attr] = ann

    def _collect_method(self, method: ast.FunctionDef) -> None:
        """Record self-attribute accesses, lexical lock context, nested
        thread-target closures, and the intra-class call graph."""
        info = self
        calls: Set[str] = set()
        info.calls[method.name] = calls
        # Nested function defs that are Thread targets in this method.
        nested_targets: Set[str] = set()
        for node in ast.walk(method):
            if isinstance(node, ast.Call):
                f = node.func
                is_thread = ((isinstance(f, ast.Attribute)
                              and f.attr == "Thread")
                             or (isinstance(f, ast.Name)
                                 and f.id == "Thread"))
                if is_thread:
                    for kw in node.keywords:
                        if kw.arg != "target":
                            continue
                        tgt_attr = _self_attr(kw.value)
                        if tgt_attr is not None:
                            info.thread_targets.add(tgt_attr)
                        elif isinstance(kw.value, ast.Name):
                            nested_targets.add(kw.value.id)

        class V(ast.NodeVisitor):
            def __init__(self):
                self.locks: List[str] = []
                self.fn_stack: List[str] = [method.name]
                self.worker = False

            def visit_With(self, node: ast.With) -> None:
                held = []
                for item in node.items:
                    expr = item.context_expr
                    # with self._lock:  /  with self._lock, self._other:
                    attr = _self_attr(expr)
                    if attr is not None and attr in info.lock_attrs:
                        held.append(attr)
                self.locks.extend(held)
                for child in node.body:
                    self.visit(child)
                for _ in held:
                    self.locks.pop()

            def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
                was_worker = self.worker
                if node.name in nested_targets:
                    self.worker = True
                    for sub in ast.walk(node):
                        if isinstance(sub, ast.Nonlocal):
                            info.nonlocal_findings.extend(
                                (n, sub.lineno) for n in sub.names)
                self.fn_stack.append(node.name)
                for child in node.body:
                    self.visit(child)
                self.fn_stack.pop()
                self.worker = was_worker

            visit_AsyncFunctionDef = visit_FunctionDef

            def visit_Attribute(self, node: ast.Attribute) -> None:
                attr = _self_attr(node)
                if attr is not None:
                    init_top = (method.name == "__init__"
                                and len(self.fn_stack) == 1)
                    info.accesses.append(Access(
                        attr, method.name, node.lineno,
                        isinstance(node.ctx, (ast.Store, ast.Del)),
                        frozenset(self.locks),
                        self.worker or method.name in info.thread_targets,
                        init_top))
                self.generic_visit(node)

            def visit_Call(self, node: ast.Call) -> None:
                attr = _self_attr(node.func)
                if attr is not None:
                    calls.add(attr)
                self.generic_visit(node)

        v = V()
        for child in method.body:
            v.visit(child)

    # -- context partition -------------------------------------------------

    def _reach(self, roots: Set[str]) -> Set[str]:
        seen = set(roots)
        frontier = list(roots)
        while frontier:
            m = frontier.pop()
            for callee in self.calls.get(m, ()):
                if callee in self.calls and callee not in seen:
                    seen.add(callee)
                    frontier.append(callee)
        return seen

    def contexts(self) -> Tuple[Set[str], Set[str]]:
        """(worker-reachable, caller-reachable) method-name sets."""
        worker = self._reach(self.thread_targets & set(self.calls))
        called_by: Set[str] = set()
        for m, callees in self.calls.items():
            called_by |= callees
        caller_roots = {m for m in self.calls
                        if m not in self.thread_targets
                        and m not in called_by}
        caller = self._reach(caller_roots)
        return worker, caller


def lint_class(info: _ClassInfo, where_prefix: str) -> List[Finding]:
    out: List[Finding] = []
    # -- annotated contract: shared-under --------------------------------
    for attr, (kind, arg) in sorted(info.annotations.items()):
        if kind != "shared-under":
            continue
        locks = {a.strip() for a in arg.split(",") if a.strip()}
        unknown = locks - info.lock_attrs
        if unknown:
            out.append(make_finding(
                "error", "lockset", where_prefix,
                f"{info.name}.{attr}: shared-under names unknown lock(s) "
                f"{sorted(unknown)}; locks declared in __init__: "
                f"{sorted(info.lock_attrs)}"))
            continue
        for acc in info.accesses:
            if acc.attr != attr or acc.init_top:
                continue
            if not locks & acc.locks:
                op = "write" if acc.is_store else "read"
                out.append(make_finding(
                    "error", "lockset", f"{where_prefix}:{acc.lineno}",
                    f"{info.name}.{attr} is declared shared-under"
                    f"({arg}) but this {op} in {acc.method}() holds "
                    f"{sorted(acc.locks) or 'no lock'}"))
    # -- discovery: unannotated cross-thread mutable state ---------------
    worker_m, caller_m = info.contexts()
    # A class is "threaded" when it hands ANY target to Thread(): one of
    # its own methods (worker_m) or a nested closure (accesses carry
    # worker=True but no method name lands in worker_m).
    if worker_m or any(a.worker for a in info.accesses):
        by_attr: Dict[str, List[Access]] = {}
        for acc in info.accesses:
            if not acc.init_top:
                by_attr.setdefault(acc.attr, []).append(acc)
        for attr, accs in sorted(by_attr.items()):
            if (attr in info.annotations or attr in info.safe_attrs
                    or attr in info.lock_attrs):
                continue
            mutated = any(a.is_store for a in accs)
            in_worker = any(a.worker or a.method in worker_m for a in accs)
            in_caller = any(not a.worker and a.method in caller_m
                            for a in accs)
            some_unlocked = any(not a.locks for a in accs)
            if mutated and in_worker and in_caller and some_unlocked:
                lines = sorted({a.lineno for a in accs})
                out.append(make_finding(
                    "error", "lockset", f"{where_prefix}:{lines[0]}",
                    f"{info.name}.{attr} is mutated and reached from "
                    f"both the spawned thread and its caller (lines "
                    f"{lines}) with no lock held and no annotation — a "
                    "data race; guard it (shared-under) or document the "
                    "benign race (unlocked-ok)"))
    for name, lineno in info.nonlocal_findings:
        out.append(make_finding(
            "error", "lockset", f"{where_prefix}:{lineno}",
            f"nonlocal {name!r} inside a Thread target closure — a "
            "shared mutable local no lock can be named for; hoist it "
            "into an attribute with a declared lock"))
    return out


def lint_source(path: str, source: str) -> List[Finding]:
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [make_finding("warning", "lockset", path,
                             f"unparseable: {e}")]
    lines = source.splitlines()
    out: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            out.extend(lint_class(_ClassInfo(node, lines), path))
    return out


def scan_modules(root: str,
                 modules: Tuple[str, ...] = SCAN_MODULES) -> List[Finding]:
    """Lint the configured threaded modules under the package ``root``."""
    out: List[Finding] = []
    for mod in modules:
        fpath = os.path.join(root, *mod.split("/"))
        if not os.path.exists(fpath):
            out.append(make_finding(
                "warning", "lockset", mod,
                "configured threaded module is missing — update "
                "analysis/lockset.py SCAN_MODULES"))
            continue
        rel = os.path.join(os.path.basename(root), *mod.split("/"))
        with open(fpath, "r", encoding="utf-8") as fh:
            out.extend(lint_source(rel, fh.read()))
    return out
