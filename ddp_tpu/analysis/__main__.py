"""``python -m ddp_tpu.analysis`` — the program auditor CLI.

Runs, against the registered head programs (``analysis/programs.py``) on
a virtual mesh:

1. the jaxpr collective auditor (axis/count invariants per program),
2. the constant-capture and donation checks,
3. the static cost model (per-program FLOPs / bytes / collective
   payload, ``costmodel``) and the donation-aware peak-liveness
   estimate (``liveness``), diffed against ``BUDGETS.json`` when one
   applies (``--budgets``/``--write-budgets``) — the cost-regression
   gate,
4. the host-sync AST pass over ``train/``, ``data/``, ``serve/``,
5. the lockset lint over the threaded subsystems,
6. the multi-host divergence lint (``divergence``) over the host-side
   coordination code,

prints one findings table, optionally writes the JSON artifact CI
uploads (now including the per-program cost table), and with
``--strict`` exits nonzero on any ``error`` finding — the CI gate.
``--fixture <name>`` runs one seeded-faulty fixture instead (every
error-level fixture must fail ``--strict``; that is tested).  Tracing is
abstract: no XLA compile, no device memory — the full default registry
audits in seconds on one CPU process.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def _parse(argv: Optional[List[str]]) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="python -m ddp_tpu.analysis",
        description="Audit the registered SPMD programs and threaded "
                    "runtime before a chip run.")
    p.add_argument("--strict", action="store_true",
                   help="exit nonzero when any error-severity finding "
                        "is reported (the CI gate)")
    p.add_argument("--json", metavar="PATH",
                   help="write the findings + per-program collective "
                        "inventories as a JSON artifact")
    p.add_argument("--programs", metavar="A,B,...",
                   help="comma-separated registry names to audit "
                        "(default: every program the model supports)")
    p.add_argument("--model", default=None,
                   help="model to build programs for (default: the "
                        "registry default, deepnn)")
    p.add_argument("--mesh-shape", "--mesh_shape", dest="mesh_shape",
                   default=None, metavar="D,M[,S]",
                   help="(data, model[, pipeline stage]) mesh shape, "
                        "default 2,4; the 1-D programs use all D*M "
                        "devices; a third entry S>1 also audits the "
                        "staged pipeline programs (pp_*@pp) on a "
                        "(D,M,S) mesh of D*M*S devices")
    from .fixtures import fixture_names
    p.add_argument("--fixture", metavar="NAME",
                   help="run one seeded-faulty fixture instead of the "
                        "registry: " + ", ".join(fixture_names()))
    p.add_argument("--budgets", metavar="PATH", default=None,
                   help="per-program cost budget file to diff against "
                        "(default: BUDGETS.json at the repo root, when "
                        "present)")
    p.add_argument("--write-budgets", action="store_true",
                   help="re-baseline: write the current cost table to "
                        "the budget file instead of diffing against it")
    p.add_argument("--skip-programs", action="store_true",
                   help="skip the jaxpr auditors and the cost/liveness "
                        "passes (static passes only)")
    p.add_argument("--skip-static", action="store_true",
                   help="skip the host-sync, lockset and divergence "
                        "passes")
    p.add_argument("--list", action="store_true",
                   help="list registered programs and fixtures, exit")
    return p.parse_args(argv)


def _prepare_backend(num_devices: int) -> None:
    """Trace-only audit: default to the CPU backend with enough virtual
    devices for the requested mesh.  Must run before jax's backend
    initializes; explicit user env always wins."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count="
            f"{num_devices}").strip()


def _mesh_shape(arg: Optional[str]):
    from .programs import DEFAULT_MESH_2D
    if not arg:
        return DEFAULT_MESH_2D
    try:
        parts = [int(v) for v in arg.replace("x", ",").split(",") if v]
    except ValueError:
        parts = []
    if len(parts) not in (2, 3) or min(parts, default=0) < 1:
        raise SystemExit(
            f"--mesh-shape wants 'D,M' or 'D,M,S' — positive ints in "
            f"(data, model, pipeline stage) order (got {arg!r})")
    return tuple(parts)


def _default_budgets_path() -> str:
    """BUDGETS.json at the repo root (the package's parent directory)."""
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "BUDGETS.json")


def _select_budgets(budgets: dict, model_name, mesh_shape) -> dict:
    """The budget section applying to this (model, mesh): the top-level
    document, or a matching ``extra_contexts`` entry (the per-mesh
    sections ``--write-budgets`` appends for non-default shapes, e.g.
    the staged-pipeline (2,1,2) audit).  Falls back to the top-level doc
    so a genuinely un-budgeted context still gets check_budgets' single
    not-comparable info finding, never a silent pass."""
    def matches(doc):
        return (doc.get("model") == model_name
                and list(doc.get("mesh_shape") or ()) == list(mesh_shape))
    if matches(budgets):
        return budgets
    for doc in budgets.get("extra_contexts", ()):
        if matches(doc):
            return doc
    return budgets


def _budget_pass(args, cost_table, model_name, mesh_shape, *,
                 partial: bool, out):
    """Write or diff the per-program budget file.  Diffing is skipped
    (silently) when no budget file exists — a fresh checkout without a
    baseline must not fail ``--strict``.  One file carries every audited
    context: the default (2,4) document at top level, other (model,
    mesh) pairs as ``extra_contexts`` entries; ``--write-budgets``
    updates only the section matching the current audit."""
    from .costmodel import check_budgets, make_budgets
    path = args.budgets or _default_budgets_path()
    if args.write_budgets:
        table = {name: {m: row[m] for m in
                        ("flops", "bytes", "peak_live_bytes",
                         "collective_payload_bytes")}
                 for name, row in cost_table.items()}
        doc = make_budgets(table, model_name, mesh_shape)
        existing = {}
        if os.path.exists(path):
            with open(path, "r", encoding="utf-8") as fh:
                existing = json.load(fh)
        top_matches = (not existing or
                       (existing.get("model") == model_name and
                        list(existing.get("mesh_shape") or ())
                        == list(mesh_shape)))
        if top_matches:
            extras = existing.get("extra_contexts")
            if extras:
                doc["extra_contexts"] = extras
        else:
            doc, top = existing, doc
            extras = [e for e in doc.get("extra_contexts", ())
                      if not (e.get("model") == model_name and
                              list(e.get("mesh_shape") or ())
                              == list(mesh_shape))]
            doc["extra_contexts"] = extras + [top]
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh, indent=2, sort_keys=True)
            fh.write("\n")
        print(f"wrote budgets to {path}", file=out)
        return []
    if not os.path.exists(path):
        return []
    with open(path, "r", encoding="utf-8") as fh:
        budgets = json.load(fh)
    return check_budgets(cost_table,
                         _select_budgets(budgets, model_name, mesh_shape),
                         model_name, mesh_shape, partial=partial)


def _inventory_summary(inv) -> str:
    if not inv:
        return "collective-free"
    return ", ".join(f"{prim}({','.join(axes) or '-'}) x{n}"
                     for (prim, axes), n in sorted(inv.items()))


def run(argv: Optional[List[str]] = None,
        out=None) -> int:
    args = _parse(argv)
    out = out or sys.stdout

    if args.list:
        from .fixtures import fixture_names
        from .programs import program_names
        print("programs:", file=out)
        for name in program_names():
            print(f"  {name}", file=out)
        print("fixtures:", file=out)
        for name in fixture_names():
            print(f"  {name}", file=out)
        return 0

    mesh_shape = _mesh_shape(args.mesh_shape)
    n_devices = 1
    for v in mesh_shape:
        n_devices *= v
    _prepare_backend(n_devices)

    from .findings import count_by_severity, format_table, make_finding

    findings = []
    inventories = {}
    cost_table = {}
    model_name = None

    if args.fixture:
        from .fixtures import run_fixture
        findings.extend(run_fixture(args.fixture))
    else:
        if not args.skip_programs:
            from .costmodel import cost_summary, program_cost
            from .jaxpr_audit import (audit_collectives, audit_constants,
                                      audit_donation, collective_inventory,
                                      inventory_as_json, trace_jaxpr)
            from .liveness import liveness_of
            from .programs import (DEFAULT_MODEL, build_context,
                                   build_programs)
            names = ([n.strip() for n in args.programs.split(",")
                      if n.strip()] if args.programs else None)
            model_name = args.model or DEFAULT_MODEL
            ctx = build_context(model_name, mesh_2d=mesh_shape)
            for prog in build_programs(ctx, names):
                closed = trace_jaxpr(prog.fn, prog.args)
                inv = collective_inventory(closed)
                inventories[prog.name] = inventory_as_json(inv)
                findings.append(make_finding(
                    "info", "inventory", prog.name,
                    _inventory_summary(inv)))
                cost = program_cost(closed)
                live = liveness_of(closed)
                cost_table[prog.name] = {**cost.as_json(), **live}
                findings.append(make_finding(
                    "info", "cost", prog.name,
                    cost_summary(cost, live["peak_live_bytes"])))
                findings.extend(audit_collectives(
                    prog.name, prog.kind, inv, plan=prog.plan,
                    zero=prog.zero,
                    model_psum_budget=prog.model_psum_budget))
                findings.extend(audit_constants(prog.name, closed))
                findings.extend(audit_donation(
                    prog.name, prog.kind, prog.fn, prog.args))
            findings.extend(_budget_pass(args, cost_table, model_name,
                                         mesh_shape,
                                         partial=names is not None,
                                         out=out))
        if not args.skip_static:
            from .divergence import scan_packages as divergence_scan
            from .hostsync import scan_packages
            from .lockset import scan_modules
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))
            findings.extend(scan_packages(pkg_root))
            findings.extend(scan_modules(pkg_root))
            findings.extend(divergence_scan(pkg_root))

    print(format_table(findings), file=out)
    counts = count_by_severity(findings)

    if args.json:
        artifact = {"counts": counts,
                    "findings": [f.as_json() for f in findings],
                    "inventories": inventories,
                    "cost_table": cost_table,
                    "mesh_shape": list(mesh_shape)}
        with open(args.json, "w", encoding="utf-8") as fh:
            json.dump(artifact, fh, indent=2, sort_keys=True)
        print(f"wrote {args.json}", file=out)

    if args.strict and counts["error"]:
        print(f"--strict: {counts['error']} error finding(s)", file=out)
        return 1
    return 0


def main() -> None:
    raise SystemExit(run())


if __name__ == "__main__":
    main()
