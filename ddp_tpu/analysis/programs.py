"""The audited-program registry: every SPMD program family, buildable on
a virtual mesh, with its declarative invariants.

One entry per (program family x mesh regime): the 1-D data-parallel
train/accum/ZeRO steps, their (d, m) tensor-parallel variants, the
evaluation step, and the serve forward — the complete set of programs a
chip run executes (train/step.py, train/zero.py, serve/engine.py).  Each
entry builds the REAL head builder's jitted function plus abstract
(``ShapeDtypeStruct``) example arguments, so auditing traces the exact
program the trainer runs, never a reimplementation — and tracing abstract
args costs no device memory and no XLA compile.

The registry is tiny on purpose: entries are (name, kind, zero, tp,
build), invariants derive from (kind, zero, plan) in
``jaxpr_audit.audit_collectives``.  ``kind``:

- ``update``  — optimizer steps: data-axis grad reduction required, full
  state donation required, ZeRO pair iff ``zero``.
- ``forward`` — the serve logits program: collective-free off (and, here,
  on) the data axis.
- ``eval``    — the counter-psum evaluation step.
- ``audit``   — the drift-audit fingerprint program (resilience/drift.py):
  psum-over-data only, params NOT donated (they are the live train state),
  payload budgeted tiny (2 x n_leaves x 4 bytes — the SDC audit must stay
  cheap enough to run every K steps, BENCH_r10.json).
- ``pp_*``    — the staged pipeline programs (parallel/pp/schedule.py),
  registered only under a 3-entry ``--mesh-shape`` with s>1: one
  forward/backward per non-last stage, the fused last-stage FB, one
  update per stage — each audited against its EXACT per-stage
  psum-over-model budget and required to stay 2-D (activation handoffs
  are device transfers, never collectives).
"""
from __future__ import annotations

import functools
import os
from typing import Any, Callable, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

DEFAULT_MODEL = "deepnn"
DEFAULT_MESH_2D = (2, 4)
_BATCH = 32      # global rows per step for the audit trace
_ACCUM = 2       # micro-batches for the accum variants
_LM_T = 32       # sequence length the LM train-step audit traces
_LM_SLOTS = 8    # KV-cache slots the decode audit traces
_LM_BUCKET = 16  # padded prompt bucket the prefill audit traces


class BuiltProgram(NamedTuple):
    name: str
    kind: str                 # "update" | "forward" | "eval" | "pp_*"
    zero: bool
    fn: Any                   # the jitted callable (head builder output)
    args: Tuple               # abstract example args for make_jaxpr/lower
    plan: Optional[Any]       # TPPlan when tensor-parallel, else None
    # Exact psum-over-model budget for the pp_* kinds (the per-stage slice
    # of expected_collectives — parallel/pp/partition.stage_model_psums);
    # None everywhere else (the TPPlan drives the budget instead).
    model_psum_budget: Optional[int] = None


class ProgramSpec(NamedTuple):
    name: str
    kind: str
    zero: bool
    tp: bool
    build: Callable[["_Ctx", str], BuiltProgram]
    # Which workload family the entry belongs to: "image" (the CIFAR
    # classifier programs), "lm" (the tinylm decoder: LM train step +
    # the KV-cache serving programs), or None (workload-agnostic, e.g.
    # the drift audit — a params fingerprint prices identically).
    workload: Optional[str] = "image"


class _Ctx(NamedTuple):
    """Shared build context: model + meshes + abstract state, built once
    per audit run (model init is the only concrete computation).
    ``mesh3d``/``pp_plan`` exist only under a 3-entry ``--mesh-shape``
    with s>1 AND a model that declares PP_BLOCKS — the staged programs
    are registered exactly then."""
    model: Any
    mesh1d: Any
    mesh2d: Any
    plan: Optional[Any]
    params: Any
    stats: Any
    model_name: str = DEFAULT_MODEL
    mesh3d: Any = None
    pp_plan: Optional[Any] = None
    workload: str = "image"


def _sds(tree):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)),
        tree)


def _batch(stacked: bool = False):
    shape = (_ACCUM, _BATCH) if stacked else (_BATCH,)
    return {"image": jax.ShapeDtypeStruct(shape + (32, 32, 3), jnp.uint8),
            "label": jax.ShapeDtypeStruct(shape, jnp.int32)}


def _eval_batch():
    b = _batch()
    b["mask"] = jax.ShapeDtypeStruct((_BATCH,), jnp.bool_)
    return b


def _rng():
    return _sds(jax.random.key(0))


def _sgd():
    from ..optim import SGDConfig, triangular_lr
    return SGDConfig(lr=0.1), functools.partial(
        triangular_lr, base_lr=0.1, num_epochs=2, steps_per_epoch=4)


def _train_state(ctx: _Ctx, mesh, *, zero: bool, plan):
    from ..train.step import init_train_state
    state = init_train_state(ctx.params, ctx.stats)
    if zero:
        from ..train.zero import init_opt_shard
        state = state._replace(
            opt_state=init_opt_shard(state.params, mesh, plan=plan))
    return _sds(state)


def _build_step(ctx: _Ctx, name: str, *, accum: bool, zero: bool,
                tp: bool) -> BuiltProgram:
    mesh = ctx.mesh2d if tp else ctx.mesh1d
    plan = ctx.plan if tp else None
    cfg, sched = _sgd()
    if zero:
        from ..train.zero import (make_train_step_zero,
                                  make_train_step_zero_accum)
        builder = make_train_step_zero_accum if accum else \
            make_train_step_zero
    else:
        from ..train.step import make_train_step, make_train_step_accum
        builder = make_train_step_accum if accum else make_train_step
    fn = builder(ctx.model, cfg, sched, mesh, plan=plan)
    state = _train_state(ctx, mesh, zero=zero, plan=plan)
    return BuiltProgram(name, "update", zero, fn,
                        (state, _batch(stacked=accum), _rng()), plan)


def _build_eval(ctx: _Ctx, name: str, *, tp: bool) -> BuiltProgram:
    from ..train.step import make_eval_step
    mesh = ctx.mesh2d if tp else ctx.mesh1d
    plan = ctx.plan if tp else None
    fn = make_eval_step(ctx.model, mesh, plan=plan)
    return BuiltProgram(name, "eval", False, fn,
                        (_sds(ctx.params), _sds(ctx.stats), _eval_batch()),
                        plan)


def _build_forward(ctx: _Ctx, name: str, *, tp: bool) -> BuiltProgram:
    from ..train.step import make_eval_forward
    mesh = ctx.mesh2d if tp else ctx.mesh1d
    plan = ctx.plan if tp else None
    fn = make_eval_forward(ctx.model, mesh, plan=plan)
    images = jax.ShapeDtypeStruct((_BATCH, 32, 32, 3), jnp.uint8)
    return BuiltProgram(name, "forward", False, fn,
                        (_sds(ctx.params), _sds(ctx.stats), images), plan)


def _build_drift(ctx: _Ctx, name: str) -> BuiltProgram:
    from ..resilience.drift import make_drift_audit
    fn = make_drift_audit(ctx.mesh1d)
    return BuiltProgram(name, "audit", False, fn, (_sds(ctx.params),), None)


def auto_plan_path(model_name: str, mesh_2d: Tuple[int, int]) -> str:
    """Repo-root path of the COMMITTED searched plan for a (model, mesh)
    pair — ``plans/<model>_<d>x<m>.autoplan.json``, written by
    ``python -m ddp_tpu.parallel.tp --search --out``.  The golden plan
    CI audits and trains against lives at this path for the default
    (deepnn, (2,4)) context."""
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    d, m = int(mesh_2d[0]), int(mesh_2d[1])
    return os.path.join(root, "plans",
                        f"{model_name}_{d}x{m}.autoplan.json")


def _ctx_mesh_2d(ctx: _Ctx) -> Tuple[int, int]:
    from ..parallel.mesh import DATA_AXIS, MODEL_AXIS
    shape = dict(ctx.mesh2d.shape)
    return int(shape[DATA_AXIS]), int(shape[MODEL_AXIS])


def _auto_doc(ctx: _Ctx) -> Optional[dict]:
    """The committed searched plan doc for this context's (model, mesh),
    or None when no plan is committed.  A file that EXISTS but fails
    validation or names a different model/mesh raises — a corrupt
    committed plan must fail the audit, not silently vanish from it."""
    path = auto_plan_path(ctx.model_name, _ctx_mesh_2d(ctx))
    if not os.path.exists(path):
        return None
    from ..parallel.tp.autoplan import read_plan_doc
    doc = read_plan_doc(path)
    if doc["model"] != ctx.model_name or \
            tuple(doc["mesh_shape"]) != _ctx_mesh_2d(ctx):
        raise ValueError(
            f"{path} names model {doc['model']!r} mesh "
            f"{doc['mesh_shape']} but its filename claims "
            f"({ctx.model_name!r}, {_ctx_mesh_2d(ctx)})")
    return doc


def _build_auto(ctx: _Ctx, name: str) -> BuiltProgram:
    """The train step under the committed searched plan — the auto-plan
    twin of ``train_step@tp``, built through the same head builders so
    the strict auditor checks the exact program ``--auto_plan`` runs.
    The doc drives the recipe AND the ZeRO choice (the BuiltProgram's
    ``zero`` comes from the doc, not the registry row)."""
    doc = _auto_doc(ctx)
    assert doc is not None  # build_programs skips the entry otherwise
    from ..parallel.tp.autoplan import plan_from_doc
    plan = plan_from_doc(doc, ctx.params, ctx.stats)
    zero = bool(doc.get("zero"))
    cfg, sched = _sgd()
    if zero:
        from ..train.zero import make_train_step_zero
        fn = make_train_step_zero(ctx.model, cfg, sched, ctx.mesh2d,
                                  plan=plan)
    else:
        from ..train.step import make_train_step
        fn = make_train_step(ctx.model, cfg, sched, ctx.mesh2d, plan=plan)
    state = _train_state(ctx, ctx.mesh2d, zero=zero, plan=plan)
    return BuiltProgram(name, "update", zero, fn,
                        (state, _batch(), _rng()), plan)


def _pp_names(pp_plan) -> List[str]:
    """Registry names of the staged programs a context with this stage
    plan registers — one forward/backward per non-last stage, the fused
    forward+backward on the last, one update per stage."""
    s = pp_plan.num_stages
    return ([f"pp_fwd_s{j}@pp" for j in range(s - 1)]
            + ["pp_fb@pp"]
            + [f"pp_bwd_s{j}@pp" for j in range(s - 1)]
            + [f"pp_update_s{k}@pp" for k in range(s)])


def _pp_programs(ctx: _Ctx) -> List[BuiltProgram]:
    """The pipeline stage programs, built through the REAL schedule
    (parallel/pp/schedule._PPStep) over the context's 3-D mesh — the
    exact per-stage jitted shard_map programs a (d, m, s) train step
    dispatches, traced with abstract args.  Each carries its exact
    psum-over-model budget (``stage_model_psums``); activation handoffs
    are device transfers OUTSIDE these programs, so every staged jaxpr
    must stay 2-D — the stage-axis invariant jaxpr_audit enforces."""
    from ..parallel.pp.partition import stage_model_psums, stage_subtree
    from ..parallel.pp.schedule import _PPStep
    cfg, sched = _sgd()
    step = _PPStep(ctx.model_name, cfg, sched, ctx.mesh3d, ctx.pp_plan,
                   tp_plan=ctx.plan, schedule="1f1b")
    state = _train_state(ctx, ctx.mesh3d, zero=False, plan=None)
    step._build(state)
    progs = step._progs
    updates = step._update_programs(_ACCUM)
    plan, s = ctx.pp_plan, ctx.pp_plan.num_stages
    p_sub = [stage_subtree(plan, k, state.params) for k in range(s)]
    imgs, labels = (_batch(stacked=True)["image"],
                    _batch(stacked=True)["label"])
    i32 = jax.ShapeDtypeStruct((), jnp.int32)
    lsum = jax.ShapeDtypeStruct((), jnp.float32)
    rng = _rng()

    def budget(k, role):
        return stage_model_psums(plan, ctx.plan, k, role=role)

    # Activation ShapeDtypeStructs, chained through the real forwards.
    acts, x = {}, imgs
    for j in range(s - 1):
        acts[j + 1] = jax.eval_shape(progs["fwd"][j], p_sub[j], x, rng,
                                     i32, i32)
        x = acts[j + 1]

    out: List[BuiltProgram] = []
    for j in range(s - 1):
        xin = imgs if j == 0 else acts[j]
        out.append(BuiltProgram(
            f"pp_fwd_s{j}@pp", "pp_forward", False, progs["fwd"][j],
            (p_sub[j], xin, rng, i32, i32), None,
            model_psum_budget=budget(j, "forward")))
    out.append(BuiltProgram(
        "pp_fb@pp", "pp_fwdbwd", False, progs["fb"],
        (p_sub[s - 1], p_sub[s - 1], lsum, acts[s - 1], labels, rng,
         i32, i32), None, model_psum_budget=budget(s - 1, "fwdbwd")))
    for j in range(s - 1):
        xin = imgs if j == 0 else acts[j]
        out.append(BuiltProgram(
            f"pp_bwd_s{j}@pp", "pp_backward", False, progs["bwd"][j],
            (p_sub[j], p_sub[j], xin, acts[j + 1], rng, i32, i32), None,
            model_psum_budget=budget(j, "backward")))
    for k in range(s):
        out.append(BuiltProgram(
            f"pp_update_s{k}@pp", "pp_update", False, updates[k],
            (p_sub[k], p_sub[k], p_sub[k], i32), None,
            model_psum_budget=budget(k, "update")))
    return out


def _lm_module():
    from ..models import transformer as tfm
    return tfm


def _lm_cache_sds(slots: int):
    tfm = _lm_module()
    return jax.ShapeDtypeStruct(
        (int(tfm.N_LAYERS), slots, int(tfm.T_MAX), int(tfm.N_HEADS),
         int(tfm.HEAD_DIM)), jnp.float32)


def _build_lm_step(ctx: _Ctx, name: str, *, tp: bool) -> BuiltProgram:
    """The LM optimizer step (train/lm.py) — same invariants as the
    classifier update: psum-over-data on grads, full state donation,
    exactly the plan's model-psum count under TP."""
    from ..train.lm import make_lm_train_step
    mesh = ctx.mesh2d if tp else ctx.mesh1d
    plan = ctx.plan if tp else None
    cfg, sched = _sgd()
    fn = make_lm_train_step(ctx.model, cfg, sched, mesh, plan=plan)
    state = _train_state(ctx, mesh, zero=False, plan=plan)
    tokens = jax.ShapeDtypeStruct((_BATCH, _LM_T), jnp.int32)
    return BuiltProgram(name, "update", False, fn,
                        (state, tokens, _rng()), plan)


def _build_lm_prefill(ctx: _Ctx, name: str, *, tp: bool) -> BuiltProgram:
    """The serve prompt prefill (serve/kvcache.py): forward-kind — no
    data collectives ever; exactly the plan's forward model psums under
    TP (attention heads sharded, same rows as the train forward)."""
    from ..serve.kvcache import make_lm_prefill
    mesh = ctx.mesh2d if tp else ctx.mesh1d
    plan = ctx.plan if tp else None
    fn = make_lm_prefill(_lm_module(), mesh, plan=plan)
    tokens = jax.ShapeDtypeStruct((_LM_BUCKET,), jnp.int32)
    return BuiltProgram(name, "forward", False, fn,
                        (_sds(ctx.params), tokens), plan)


def _build_lm_decode(ctx: _Ctx, name: str, *, tp: bool) -> BuiltProgram:
    """The single-token decode step over the slot-sharded KV cache —
    the ONE executable a serving run decodes every token with."""
    from ..serve.kvcache import make_lm_decode
    mesh = ctx.mesh2d if tp else ctx.mesh1d
    plan = ctx.plan if tp else None
    fn = make_lm_decode(_lm_module(), mesh, plan=plan)
    vec = jax.ShapeDtypeStruct((_LM_SLOTS,), jnp.int32)
    cache = _lm_cache_sds(_LM_SLOTS)
    return BuiltProgram(name, "forward", False, fn,
                        (_sds(ctx.params), vec, vec, cache, cache), plan)


def _build_lm_cache_write(ctx: _Ctx, name: str, *, tp: bool
                          ) -> BuiltProgram:
    """The KV-cache slot scatter: pure ownership arithmetic, audited
    COLLECTIVE-FREE (the BuiltProgram carries no plan even under TP, so
    any psum — model or data — fails the audit)."""
    from ..serve.kvcache import make_cache_write
    tfm = _lm_module()
    mesh = ctx.mesh2d if tp else ctx.mesh1d
    fn = make_cache_write(mesh, ctx.plan if tp else None)
    cache = _lm_cache_sds(_LM_SLOTS)
    kv_new = jax.ShapeDtypeStruct(
        (int(tfm.N_LAYERS), _LM_BUCKET, int(tfm.N_HEADS),
         int(tfm.HEAD_DIM)), jnp.float32)
    slot = jax.ShapeDtypeStruct((), jnp.int32)
    return BuiltProgram(name, "forward", False, fn,
                        (cache, cache, kv_new, kv_new, slot), None)


def _spec(name, kind, *, zero=False, tp=False, accum=False,
          auto=False, workload: Optional[str] = "image",
          builder=None) -> ProgramSpec:
    if builder is not None:
        build = functools.partial(builder, tp=tp)
    elif auto:
        build = _build_auto
    elif kind == "update":
        build = functools.partial(_build_step, accum=accum, zero=zero,
                                  tp=tp)
    elif kind == "eval":
        build = functools.partial(_build_eval, tp=tp)
    elif kind == "audit":
        build = _build_drift
    else:
        build = functools.partial(_build_forward, tp=tp)
    return ProgramSpec(name, kind, zero, tp, build, workload)


# The default registry — all of it traces in seconds; names are stable
# CLI/JSON keys (``--programs`` selects by them).
REGISTRY: Tuple[ProgramSpec, ...] = (
    _spec("train_step@dp8", "update"),
    _spec("train_step_accum@dp8", "update", accum=True),
    _spec("train_step_zero@dp8", "update", zero=True),
    _spec("train_step_zero_accum@dp8", "update", zero=True, accum=True),
    _spec("train_step@tp", "update", tp=True),
    _spec("train_step_accum@tp", "update", tp=True, accum=True),
    _spec("train_step_zero@tp", "update", zero=True, tp=True),
    # The searched plan (plans/<model>_<d>x<m>.autoplan.json) as a
    # first-class audited program: present only when a plan is committed
    # for the context's (model, mesh).
    _spec("train_step@auto", "update", auto=True),
    _spec("eval_step@dp8", "eval"),
    _spec("eval_step@tp", "eval", tp=True),
    _spec("serve_forward@dp8", "forward"),
    _spec("serve_forward@tp", "forward", tp=True),
    _spec("drift_audit@dp8", "audit", workload=None),
    # The tinylm decoder workload (--model tinylm): the LM train step
    # plus the generative serving programs (serve/kvcache.py), priced
    # and audited like every other entry.
    _spec("lm_train_step@dp8", "update", workload="lm",
          builder=_build_lm_step),
    _spec("lm_train_step@tp", "update", tp=True, workload="lm",
          builder=_build_lm_step),
    _spec("lm_prefill@dp8", "forward", workload="lm",
          builder=_build_lm_prefill),
    _spec("lm_prefill@tp", "forward", tp=True, workload="lm",
          builder=_build_lm_prefill),
    _spec("lm_decode@dp8", "forward", workload="lm",
          builder=_build_lm_decode),
    _spec("lm_decode@tp", "forward", tp=True, workload="lm",
          builder=_build_lm_decode),
    _spec("lm_cache_write@dp8", "forward", workload="lm",
          builder=_build_lm_cache_write),
    _spec("lm_cache_write@tp", "forward", tp=True, workload="lm",
          builder=_build_lm_cache_write),
)


def program_names(workload: Optional[str] = None) -> List[str]:
    """All registry names; with ``workload`` given, only the entries
    that build for that workload (workload-``None`` specs — the
    model-agnostic programs — always apply)."""
    if workload is None:
        return [s.name for s in REGISTRY]
    return [s.name for s in REGISTRY
            if s.workload is None or s.workload == workload]


def build_context(model_name: str = DEFAULT_MODEL,
                  mesh_2d: Tuple[int, ...] = DEFAULT_MESH_2D) -> _Ctx:
    """Meshes + model + plan, shared by every registry build.  The 1-D
    mesh spans d*m devices so both regimes audit the same device budget
    (CI: the (2,4)x8 virtual mesh).  A 3-entry shape (d, m, s) with s>1
    additionally builds the (data × model × stage) mesh and the stage
    plan, registering the staged pipeline programs (``pp_*@pp``) — the
    backend then needs d*m*s virtual devices."""
    from ..models import get_model
    from ..models import transformer as tfm
    from ..parallel.mesh import make_mesh
    d, m = int(mesh_2d[0]), int(mesh_2d[1])
    s = int(mesh_2d[2]) if len(mesh_2d) > 2 else 1
    workload = "lm" if model_name == tfm.LM_NAME else "image"
    model = get_model(model_name)
    params, stats = model.init(jax.random.key(0))
    mesh1d = make_mesh(d * m)
    mesh2d = make_mesh(shape=(d, m))
    plan = None
    if m > 1:
        from ..parallel.tp.plan import plan_for_model
        try:
            plan = plan_for_model(model_name, params, stats, model_size=m)
        except ValueError:
            plan = None  # model without a recipe: tp entries are skipped
    mesh3d, pp_plan = None, None
    if s > 1:
        from ..parallel.pp.partition import plan_stages
        try:
            pp_plan = plan_stages(model_name, s, model_size=m,
                                  params=params, batch_stats=stats)
            mesh3d = make_mesh(shape=(d, m, s))
        except ValueError:
            pp_plan = None  # no PP_BLOCKS / infeasible cut: pp skipped
    return _Ctx(model, mesh1d, mesh2d, plan, params, stats, model_name,
                mesh3d, pp_plan, workload)


def build_programs(ctx: _Ctx, names=None) -> List[BuiltProgram]:
    """Build the selected registry entries (default: every entry the
    context supports — tp entries are skipped when the model has no
    TP_RECIPE/plan, the staged ``pp_*@pp`` entries exist only under a
    3-D context with a stage plan)."""
    pp_names = _pp_names(ctx.pp_plan) if ctx.pp_plan is not None else []
    known = set(program_names()) | set(pp_names)
    wanted = set(names) if names else None
    unknown = (wanted or set()) - known
    if unknown:
        raise ValueError(f"unknown program(s) {sorted(unknown)}; "
                         f"registry has {program_names() + pp_names}")
    out = []
    for spec in REGISTRY:
        if wanted is not None and spec.name not in wanted:
            continue
        if spec.workload is not None and spec.workload != ctx.workload:
            continue
        if spec.tp and ctx.plan is None:
            continue
        if spec.name.endswith("@auto") and _auto_doc(ctx) is None:
            continue
        out.append(spec.build(ctx, spec.name))
    if pp_names and (wanted is None or wanted & set(pp_names)):
        built = _pp_programs(ctx)
        out.extend(p for p in built
                   if wanted is None or p.name in wanted)
    return out
