"""Jaxpr-level auditors: collective inventory + invariants, constant
capture, donation.

Everything here works on the TRACED program — ``jax.make_jaxpr`` /
``jit.lower()`` only, no XLA compile, no execution — so the whole default
registry audits in seconds on one CPU.  The walker recurses through every
equation parameter that holds a sub-jaxpr (``pjit``, ``shard_map``,
``scan``, ``custom_vjp_call_jaxpr``, ``cond`` branches ...), which is
where all the interesting equations live: a jitted shard_map program's
top level is a single ``pjit`` equation.

Primitive-name facts this encodes (verified on the jax 0.4.x compat
runtime AND stable on jax>=0.9): ``lax.pmean`` lowers to ``psum`` + div,
so gradient pmeans inventory as ``psum``; the psum equation carries its
axis names in ``params["axes"]``, while ``all_gather`` / ``reduce_scatter``
/ ``ppermute`` carry ``params["axis_name"]``; ``lax.psum_scatter`` is the
``reduce_scatter`` primitive.  Positional (int) axes are filtered out —
only NAMED mesh axes are collective traffic.
"""
from __future__ import annotations

import collections
import warnings
from typing import Dict, Iterator, List, Tuple

import jax
import numpy as np

from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, STAGE_AXIS
from .findings import Finding, make_finding

# Named-axis communication primitives.  axis_index is deliberately absent
# (it reads coordinates, moves no data); pmean is absent because it never
# survives tracing (psum + div).
COLLECTIVE_PRIMITIVES = ("psum", "pmin", "pmax", "all_gather",
                        "reduce_scatter", "ppermute", "all_to_all",
                        "pbroadcast")

MIB = 2 ** 20
LARGE_CONST_BYTES = 1 * MIB     # constant-capture bloat threshold
LARGE_INPUT_BYTES = 1 * MIB     # donation-required input threshold


def trace_jaxpr(fn, args):
    """Closed jaxpr of ``fn(*args)`` — abstract tracing only (args are
    ShapeDtypeStructs), so no compile and no device memory."""
    return jax.make_jaxpr(fn)(*args)


def _sub_jaxprs(params: dict) -> Iterator:
    """Every jaxpr nested in one equation's params, whatever key or
    wrapper (ClosedJaxpr vs raw Jaxpr, single vs tuple-of-branches)."""
    for v in params.values():
        for item in (v if isinstance(v, (list, tuple)) else (v,)):
            if hasattr(item, "eqns"):
                yield item
            elif hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr


def iter_eqns(jaxpr) -> Iterator:
    """Depth-first over every equation, descending into sub-jaxprs."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn.params):
            yield from iter_eqns(sub)


def _axes_of(eqn) -> Tuple[str, ...]:
    raw = eqn.params.get("axes", eqn.params.get("axis_name", ()))
    if not isinstance(raw, (tuple, list)):
        raw = (raw,)
    return tuple(a for a in raw if isinstance(a, str))


def collective_inventory(closed_jaxpr) -> Dict[Tuple[str, Tuple[str, ...]],
                                               int]:
    """``{(primitive, named axes): count}`` over the whole program."""
    inv: collections.Counter = collections.Counter()
    for eqn in iter_eqns(closed_jaxpr.jaxpr):
        if eqn.primitive.name in COLLECTIVE_PRIMITIVES:
            inv[(eqn.primitive.name, _axes_of(eqn))] += 1
    return dict(inv)


def inventory_as_json(inv: Dict) -> List[dict]:
    return [{"primitive": prim, "axes": list(axes), "count": n}
            for (prim, axes), n in sorted(inv.items())]


def _count(inv: Dict, prim: str, axis: str) -> int:
    """Occurrences of ``prim`` whose axis set is exactly ``(axis,)``."""
    return sum(n for (p, axes), n in inv.items()
               if p == prim and axes == (axis,))


def audit_collectives(name: str, kind: str, inv: Dict,
                      plan=None, zero: bool = False,
                      model_psum_budget=None) -> List[Finding]:
    """Check one program's collective inventory against its declarative
    invariants.

    ``kind``: ``update`` (an optimizer step: backward exists, gradients
    must be reduced over ``data``), ``forward`` (a serve/logits program:
    collective-free off the data axis — and in this codebase entirely
    collective-free, the logits gather is an out_sharding, not a
    collective), ``eval`` (the counter-psum evaluation step), or
    ``audit`` (the drift-audit fingerprint program — only the generic
    invariants apply: data-axis psums allowed, everything else banned).
    The staged pipeline programs (parallel/pp/schedule.py) add
    ``pp_forward`` (a stage forward: NO data-axis collectives — it only
    computes an activation), ``pp_backward`` / ``pp_fwdbwd`` (stage
    backward / fused last-stage forward+backward: the per-stage gsum
    reduction must psum over ``data``), and ``pp_update`` (per-stage SGD:
    collective-free on EVERY axis — the grads arrive pre-reduced, a psum
    here would double-count the data axis).
    ``plan`` (a TPPlan) switches on the model-axis budget from
    ``expected_collectives`` — the printed plan table's numbers; without a
    plan, ANY model-axis traffic is a wrong-axis collective.
    ``model_psum_budget`` (the pp entries) pins the model-psum count to an
    EXACT per-stage number instead (``pp/partition.stage_model_psums``)
    and takes precedence over ``plan``.  ``zero`` allows (and requires)
    the ZeRO update's single ``reduce_scatter``/``all_gather`` pair over
    ``data``.

    The stage axis never appears here at all: stage handoff is an
    explicit device transfer between per-stage 2-D programs, so ANY
    collective over ``stage`` is an error regardless of kind.
    """
    out: List[Finding] = []

    def err(check: str, detail: str) -> None:
        out.append(make_finding("error", check, name, detail))

    # -- axis whitelist: nothing may touch an axis we don't know ---------
    known = {DATA_AXIS, MODEL_AXIS}
    for (prim, axes), n in sorted(inv.items()):
        if STAGE_AXIS in axes:
            err("collective-axis",
                f"{prim} over '{STAGE_AXIS}' x{n} — stage handoff is an "
                "explicit device transfer between per-stage programs "
                "(parallel/pp/schedule.py), never a collective; every "
                "staged jaxpr must stay 2-D (data × model)")
        stray = [a for a in axes if a not in known and a != STAGE_AXIS]
        if stray:
            err("collective-axis",
                f"{prim} over unknown axis {stray} (x{n})")

    # -- model-axis budget ----------------------------------------------
    model_psums = _count(inv, "psum", MODEL_AXIS)
    if model_psum_budget is not None:
        if model_psums != int(model_psum_budget):
            err("collective-count",
                f"psum over '{MODEL_AXIS}' x{model_psums}, the stage plan "
                f"expects exactly x{int(model_psum_budget)} for this "
                "stage program (stage_model_psums) — a stage cut moved a "
                "TP layer's collective, or a reduction landed on the "
                "wrong axis")
    elif plan is not None:
        from ..parallel.tp.plan import (expected_collectives,
                                        format_collective_table)
        backward = kind == "update"
        exp = expected_collectives(plan, backward=backward)
        if model_psums != exp["psum_model"]:
            err("collective-count",
                f"psum over '{MODEL_AXIS}' x{model_psums}, plan expects "
                f"x{exp['psum_model']} (fwd {exp['psum_model_fwd']} + bwd "
                f"{exp['psum_model_bwd']}) — a TP layer collective is "
                "missing or duplicated, or a gradient reduction landed on "
                "the wrong axis; the plan's per-layer unit table:\n"
                + format_collective_table(plan, backward=backward))
    elif model_psums:
        err("collective-axis",
            f"psum over '{MODEL_AXIS}' x{model_psums} in a program with "
            f"no tensor-parallel plan — gradient/loss reductions belong "
            f"on '{DATA_AXIS}'")

    # -- zero model-axis gathers, anywhere, ever -------------------------
    model_gathers = _count(inv, "all_gather", MODEL_AXIS)
    if model_gathers:
        err("model-gather",
            f"all_gather over '{MODEL_AXIS}' x{model_gathers} — a "
            "model-axis gather rematerializes the sharded weights (the "
            "perf cliff TP exists to avoid); hot paths must stay "
            "gather-free on the model axis")

    # -- per-kind data-axis shape ----------------------------------------
    data_psums = _count(inv, "psum", DATA_AXIS)
    data_coll = sum(n for (p, axes), n in inv.items() if DATA_AXIS in axes)
    if kind == "update" and data_psums == 0:
        err("collective-count",
            f"no psum over '{DATA_AXIS}' in an update program — the "
            "gradient/loss all-reduce is missing; shards would train on "
            "their local batches only and silently diverge")
    if kind == "forward" and data_coll:
        err("collective-count",
            f"{data_coll} data-axis collective(s) in a serve forward "
            "— per-row logits are independent; the batch gather is "
            "an output sharding, not a collective, so this program "
            "must be collective-free on the data axis")
    if kind == "pp_forward" and data_coll:
        err("collective-count",
            f"{data_coll} data-axis collective(s) in a pipeline stage "
            "forward — a stage forward only computes its activation "
            "shard; nothing is reduced until the backward's gsum psum")
    if kind in ("pp_backward", "pp_fwdbwd") and data_psums == 0:
        err("collective-count",
            f"no psum over '{DATA_AXIS}' in a pipeline stage backward — "
            "the per-stage gsum reduction is missing; the stage's data "
            "shards would accumulate local gradients only and silently "
            "diverge")
    if kind == "pp_update" and data_coll:
        err("collective-count",
            f"{data_coll} data-axis collective(s) in a per-stage update "
            "— the stage's grads arrive pre-reduced from the backward "
            "programs; a reduction here double-counts the data axis")

    # -- ZeRO pair -------------------------------------------------------
    rs_data = _count(inv, "reduce_scatter", DATA_AXIS)
    ag_data = _count(inv, "all_gather", DATA_AXIS)
    if zero:
        if rs_data != 1 or ag_data != 1:
            err("collective-count",
                f"ZeRO update must show exactly one reduce_scatter and "
                f"one all_gather over '{DATA_AXIS}' (the flat-buffer "
                f"grad-shard/param-gather pair); saw reduce_scatter "
                f"x{rs_data}, all_gather x{ag_data}")
    else:
        if rs_data:
            err("collective-count",
                f"reduce_scatter over '{DATA_AXIS}' x{rs_data} in a "
                "non-ZeRO program")
        if ag_data:
            err("collective-count",
                f"all_gather over '{DATA_AXIS}' x{ag_data} in a "
                "non-ZeRO program")

    # -- primitives this codebase never emits ----------------------------
    for prim in ("ppermute", "all_to_all", "pmin", "pmax", "pbroadcast"):
        n = sum(c for (p, _), c in inv.items() if p == prim)
        if n:
            err("collective-axis",
                f"unexpected {prim} x{n} — no registered program family "
                "uses this collective; likely a wrong primitive choice")
    return out


def _is_weak(c) -> bool:
    """jax Arrays carry weak_type on their aval; raw np values are always
    strongly typed; bare Python numbers are weak (and normally never
    reach consts — they inline as literals)."""
    aval = getattr(c, "aval", None)
    if aval is not None:
        return bool(getattr(aval, "weak_type", False))
    if hasattr(c, "weak_type"):
        return bool(c.weak_type)
    return isinstance(c, (bool, int, float, complex))


def _const_bytes(c) -> int:
    try:
        return int(np.asarray(c).nbytes)
    except Exception:
        return 0


def audit_constants(name: str, closed_jaxpr) -> List[Finding]:
    """Constant-capture scan over the closed jaxpr.

    Every registered head program traces with ZERO consts (weak-typed
    Python scalar closures fold in as inline literals and true data flows
    through arguments), so ANY captured const is drift.  Graded:

    - >1 MiB — ``error``: closure-captured bulk data bloats every
      executable and can never be donated or sharded; pass it as an
      argument.
    - size-1 non-weak-typed — ``warning`` (``scalar-closure``): a
      ``np.float32(x)`` / shape-(1,) hyperparameter closure.  Unlike a
      captured Python scalar (weak-typed, folds into the program
      unchanged), it pins a dtype, and the call-site habit it indicates —
      wrapping step-varying hyperparameters in np — retraces per distinct
      value.
    - anything else — ``warning``: a captured host array that should be
      an argument."""
    out: List[Finding] = []
    for c in closed_jaxpr.consts:
        nbytes = _const_bytes(c)
        shape = tuple(np.shape(c))
        if nbytes > LARGE_CONST_BYTES:
            out.append(make_finding(
                "error", "constant-capture", name,
                f"captured constant {shape} "
                f"({nbytes / MIB:.1f} MiB) baked into the jaxpr — pass it "
                "as an argument (donatable, shardable) instead of closing "
                "over it"))
        elif int(np.size(c)) == 1 and not _is_weak(c):
            out.append(make_finding(
                "warning", "scalar-closure", name,
                f"non-weak-typed scalar constant {shape} (dtype "
                f"{np.asarray(c).dtype}) closed into the program — a "
                "Python scalar folds in weak-typed; a np scalar closure "
                "usually means a hyperparameter that will retrace per "
                "value"))
        else:
            out.append(make_finding(
                "warning", "constant-capture", name,
                f"captured constant {shape} "
                f"({nbytes} B) — head programs trace const-free; pass "
                "captured arrays as arguments"))
    return out


def audit_donation(name: str, kind: str, fn, args) -> List[Finding]:
    """Donation check for update programs: every input buffer >= 1 MiB
    must be donated, or the step permanently holds two copies of the
    state (params + momentum are the overwhelming majority of live HBM in
    data-parallel training — the reuse ``donate_argnums=(0,)`` exists
    for).  Forward/eval programs are exempt: their params are shared
    across calls and must NOT be donated.  The staged ``pp_*`` programs
    are exempt too: their params persist across the whole microbatch
    schedule (donating them in any one program would kill the others),
    gsum IS donated where it can alias (the backward/FB accumulators),
    and the per-stage update deliberately leaves gsum undonated — its
    outputs already alias params+momentum, so a third donation has no
    buffer to reuse (see schedule._update_programs)."""
    if kind != "update":
        return []
    try:
        with warnings.catch_warnings():
            # Lowering abstract (uncommitted) args trips jax's
            # "donated buffers were not usable" advisory; donation is
            # what we are here to READ, not a property of these fake
            # inputs.
            warnings.simplefilter("ignore")
            lowered = fn.lower(*args)
        infos = jax.tree_util.tree_leaves(lowered.args_info)
    except Exception as e:  # introspection, never a crash
        return [make_finding(
            "warning", "donation", name,
            f"could not lower for donation introspection: {e!r}")]
    out: List[Finding] = []
    undonated = [i for i in infos
                 if not i.donated and _aval_bytes(i) >= LARGE_INPUT_BYTES]
    for info in undonated:
        aval = getattr(info, "aval", None) or getattr(info, "_aval", None)
        out.append(make_finding(
            "error", "donation", name,
            f"large input buffer {aval} "
            f"({_aval_bytes(info) / MIB:.1f} MiB) is not donated — the "
            "update holds a dead copy of it across steps; add it to "
            "donate_argnums"))
    return out


def _aval_bytes(info) -> int:
    aval = getattr(info, "aval", None) or getattr(info, "_aval", None)
    if aval is None:
        return 0
    return int(np.prod(aval.shape, dtype=np.int64)) * aval.dtype.itemsize
