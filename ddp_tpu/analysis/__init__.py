"""Program auditor: static analysis over the programs this framework
actually runs.

Three detector families behind one findings model and one CLI
(``python -m ddp_tpu.analysis``, see ``__main__.py``):

- **jaxpr auditors** (``jaxpr_audit``) — trace every registered program
  (``programs.REGISTRY``) abstractly and check its collective inventory
  against declarative invariants (gradient psums on ``data`` only, TP
  psums on ``model`` matching the plan's expected counts, zero model-axis
  all_gathers, collective-free serve forwards, the ZeRO
  reduce_scatter/all_gather pair), plus constant-capture and donation
  checks on the same trace.
- **host-sync pass** (``hostsync``) — AST scan of ``train/``, ``data/``,
  ``serve/`` for device->host transfers inside step/epoch loops.
- **lockset lint** (``lockset``) — AST-derived shared-attribute access
  sets vs declared lock scopes in the threaded subsystems, with the
  ``# analysis: shared-under(...)`` / ``unlocked-ok(...)`` /
  ``host-sync-ok(...)`` annotation vocabulary as the audit trail.

``fixtures`` holds one seeded-faulty program per detector — the
auditor's own regression suite.
"""
from .findings import (Finding, SEVERITIES, count_by_severity,  # noqa: F401
                       format_table, make_finding)
from .jaxpr_audit import (COLLECTIVE_PRIMITIVES,  # noqa: F401
                          audit_collectives, audit_constants,
                          audit_donation, collective_inventory,
                          inventory_as_json, trace_jaxpr)
from .hostsync import scan_packages  # noqa: F401
from .lockset import scan_modules  # noqa: F401
from .programs import (REGISTRY, BuiltProgram, ProgramSpec,  # noqa: F401
                       build_context, build_programs, program_names)
from .fixtures import FIXTURES, fixture_names, run_fixture  # noqa: F401
