"""Program auditor: static analysis over the programs this framework
actually runs.

Three detector families behind one findings model and one CLI
(``python -m ddp_tpu.analysis``, see ``__main__.py``):

- **jaxpr auditors** (``jaxpr_audit``) — trace every registered program
  (``programs.REGISTRY``) abstractly and check its collective inventory
  against declarative invariants (gradient psums on ``data`` only, TP
  psums on ``model`` matching the plan's expected counts, zero model-axis
  all_gathers, collective-free serve forwards, the ZeRO
  reduce_scatter/all_gather pair), plus constant-capture and donation
  checks on the same trace.
- **cost model** (``costmodel``) — per-program FLOPs (dot/conv via
  dimension-numbers arithmetic), bytes touched and collective payload
  volume per mesh axis on the same trace, diffed against the
  ``BUDGETS.json`` per-program ceilings (the cost-regression CI gate).
- **liveness** (``liveness``) — donation-aware linear-scan buffer
  liveness over the per-shard program body: the static peak-live-bytes
  estimate that turns TP's ÷m and ZeRO's optimizer-state memory wins
  into asserted numbers.
- **host-sync pass** (``hostsync``) — AST scan of ``train/``, ``data/``,
  ``serve/`` for device->host transfers inside step/epoch loops.
- **lockset lint** (``lockset``) — AST-derived shared-attribute access
  sets vs declared lock scopes in the threaded subsystems.
- **divergence lint** (``divergence``) — AST/CFG scan for collectives
  reachable under host-local conditions (rank checks, exception
  handlers, conditional early returns) — the whole-pod-hang shape.

The annotation vocabulary (``# analysis: shared-under(...)`` /
``unlocked-ok(...)`` / ``host-sync-ok(...)`` / ``divergence-ok(...)``)
is the greppable audit trail for deliberate exceptions.  ``fixtures``
holds one seeded-faulty program per detector — the auditor's own
regression suite.
"""
from .findings import (Finding, SEVERITIES, count_by_severity,  # noqa: F401
                       format_table, make_finding)
from .jaxpr_audit import (COLLECTIVE_PRIMITIVES,  # noqa: F401
                          audit_collectives, audit_constants,
                          audit_donation, collective_inventory,
                          inventory_as_json, trace_jaxpr)
from .costmodel import (BUDGET_METRICS, Cost, check_budgets,  # noqa: F401
                        cost_summary, layer_forward_costs, make_budgets,
                        program_cost)
from .liveness import liveness_of  # noqa: F401
from .hostsync import scan_packages  # noqa: F401
from .lockset import scan_modules  # noqa: F401
from .divergence import scan_source as divergence_scan_source  # noqa: F401
from .divergence import scan_packages as divergence_scan  # noqa: F401
from .programs import (REGISTRY, BuiltProgram, ProgramSpec,  # noqa: F401
                       build_context, build_programs, program_names)
from .fixtures import FIXTURES, fixture_names, run_fixture  # noqa: F401
