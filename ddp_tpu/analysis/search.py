"""Static candidate pricing for the auto-sharding search.

The glue between the search engine (parallel/tp/autoplan.py) and this
package's static-analysis stack: a candidate = (mesh shape, per-layer
recipe, ZeRO on/off), and pricing one means

1. tracing the REAL train-step builder (train/step.py / train/zero.py)
   for that candidate on a deviceless :func:`~ddp_tpu.parallel.mesh.
   abstract_mesh` — ``jax.make_jaxpr`` over abstract state, so a CPU box
   explores v4-128 shapes without owning a chip and without one XLA
   compile;
2. pricing the traced jaxpr through the counted cost model
   (``costmodel``) with the CALIBRATED per-op-class coefficients
   (``bench.py --calibrate_cost``) — the same additive no-overlap model
   the efficiency ledger audits against measurement (obs/ledger.py), so
   the search optimizes a quantity the runtime continuously checks;
3. reading the donation-aware liveness walk (``liveness``) for the
   per-shard peak-HBM estimate — the search's memory-budget pruning
   signal;
4. running the jaxpr collective auditor (``jaxpr_audit``) against the
   candidate plan's ``expected_collectives`` arithmetic — a candidate
   whose traced program violates its own plan's invariants is pruned,
   never emitted.

The prediction prices ONE shard's body (the cost model's unit).  All
candidates in a search share the same total device budget, so per-shard
cost ranks them exactly as per-step wall-clock does on a real pod; on a
virtual CPU mesh the shards serialize, scaling every candidate by the
same factor — the ranking survives (measured ~= n_dev x predicted,
BENCH_r12's ledger ``pred_scale``).
"""
from __future__ import annotations

import functools
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

COEFFICIENT_KEYS = ("conv_s_per_flop", "dot_s_per_flop",
                    "elementwise_s_per_byte",
                    "collective_s_per_payload_byte")


def coefficients_from(doc: dict) -> Dict[str, float]:
    """Extract the four calibrated coefficients from any carrier: a
    ``--calibrate_cost`` record, an auto-plan doc (both nest them under
    ``"coefficients"``), or a bare coefficient mapping."""
    coeffs = doc.get("coefficients", doc)
    missing = [k for k in COEFFICIENT_KEYS if k not in coeffs]
    if missing:
        raise ValueError(
            f"coefficient source is missing {missing}; expected the "
            f"keys {list(COEFFICIENT_KEYS)} (a bench.py --calibrate_cost "
            "record, an auto-plan JSON, or a bare mapping)")
    return {k: float(coeffs[k]) for k in COEFFICIENT_KEYS}


def _sds(tree):
    return jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x)),
        tree)


def _abstract_state(params, stats, mesh_shape, *, zero: bool, plan):
    """The candidate step's ``TrainState`` as ShapeDtypeStructs — the
    ZeRO momentum layouts rebuilt abstractly, because the real
    constructors (train/zero.py:init_opt_shard) materialise device
    arrays a deviceless mesh cannot hold."""
    from ..optim import sgd as sgd_lib
    from ..train.step import TrainState, init_train_state
    if not zero:
        return jax.eval_shape(init_train_state, params, stats)
    d, m = mesh_shape
    if plan is not None:
        from ..parallel.tp.plan import local_param_count
        n = local_param_count(plan)
        n_pad = n + (-n) % d
        mom = jax.ShapeDtypeStruct((plan.model_size, n_pad), jnp.float32)
    else:
        from ..train.zero import padded_size
        n_pad = padded_size(params, d * m)
        mom = jax.ShapeDtypeStruct((n_pad,), jnp.float32)
    return TrainState(params=_sds(params), batch_stats=_sds(stats),
                      opt_state=sgd_lib.SGDState(mom),
                      step=jax.ShapeDtypeStruct((), jnp.int32))


def trace_candidate(model_name: str, mesh_shape: Tuple[int, int], *,
                    recipe: Optional[Dict[str, str]] = None,
                    stem: Optional[str] = None, zero: bool = False,
                    global_batch: int = 32, input_hw=(32, 32, 3)):
    """Trace the real train step for one candidate on an abstract mesh.

    Returns ``(closed_jaxpr, plan)`` where ``plan`` is ``None`` for the
    pure data-parallel program (no recipe at m=1, or a trivial
    all-replicated recipe — train/step.py wires the plain core for those
    anyway, so pricing the plain program is pricing the truth).

    Raises ``ValueError`` for an infeasible candidate — a sharded
    dimension that does not divide the model axis (tp/plan.py's
    divisibility rules) or a batch that does not divide the data axis.
    """
    from ..models import get_model
    from ..parallel.mesh import abstract_mesh
    from ..parallel.tp.plan import is_trivial, plan_for_model
    from .jaxpr_audit import trace_jaxpr
    d, m = int(mesh_shape[0]), int(mesh_shape[1])
    if global_batch % d:
        raise ValueError(f"global batch {global_batch} not divisible by "
                         f"the {d}-way data axis")
    model = get_model(model_name)
    params, stats = jax.eval_shape(model.init, jax.random.key(0))
    plan = None
    if recipe is not None:
        plan = plan_for_model(model_name, params, stats, model_size=m,
                              recipe=recipe, stem=stem)
        if is_trivial(plan):
            plan = None
    elif m > 1:
        plan = plan_for_model(model_name, params, stats, model_size=m)
    mesh = abstract_mesh((d, m))
    from ..optim import SGDConfig, triangular_lr
    cfg = SGDConfig(lr=0.1)
    sched = functools.partial(triangular_lr, base_lr=0.1, num_epochs=2,
                              steps_per_epoch=4)
    if zero:
        from ..train.zero import make_train_step_zero
        fn = make_train_step_zero(model, cfg, sched, mesh, plan=plan)
    else:
        from ..train.step import make_train_step
        fn = make_train_step(model, cfg, sched, mesh, plan=plan)
    state = _abstract_state(params, stats, (d, m), zero=zero, plan=plan)
    batch = {"image": jax.ShapeDtypeStruct((global_batch,) + tuple(input_hw),
                                           jnp.uint8),
             "label": jax.ShapeDtypeStruct((global_batch,), jnp.int32)}
    rng = _sds(jax.random.key(0))
    return trace_jaxpr(fn, (state, batch, rng)), plan


def price_closed(closed, coefficients: Dict[str, float]) -> dict:
    """One traced program -> the search objective row: additive
    predicted ms (per shard) plus the raw static metrics the budget gate
    and the memory pruning read."""
    from .costmodel import program_cost
    from .liveness import liveness_of
    cost = program_cost(closed)
    live = liveness_of(closed)
    pred_s = (cost.by_class["conv"] * coefficients["conv_s_per_flop"]
              + cost.by_class["dot"] * coefficients["dot_s_per_flop"]
              + cost.bytes * coefficients["elementwise_s_per_byte"]
              + cost.collective_payload_bytes
              * coefficients["collective_s_per_payload_byte"])
    return {
        "predicted_ms": round(pred_s * 1e3, 6),
        "flops": int(cost.flops),
        "bytes": int(cost.bytes),
        "collective_payload_bytes": int(cost.collective_payload_bytes),
        "peak_live_bytes": int(live["peak_live_bytes"]),
    }


def audit_candidate(name: str, closed, *, plan, zero: bool) -> List[str]:
    """The strict collective auditor on one candidate trace: the plan's
    ``expected_collectives`` arithmetic, the axis whitelist, the ZeRO
    pair — exactly what ``python -m ddp_tpu.analysis --strict`` enforces
    on registered programs.  Returns the error details (empty = clean);
    the search prunes any candidate with a non-empty list."""
    from .jaxpr_audit import audit_collectives, collective_inventory
    inv = collective_inventory(closed)
    findings = audit_collectives(name, "update", inv, plan=plan, zero=zero)
    return [f"{f.check}: {f.detail}" for f in findings
            if f.severity == "error"]


def model_flops_per_step(model_name: str, global_batch: int = 32,
                         input_hw=(32, 32, 3)) -> Optional[int]:
    """Counted-jaxpr FLOPs of ONE unsharded train step at
    ``global_batch`` rows — the numerator MFU reporting shares with the
    search (obs/live.py).  ``None`` when the model cannot be traced."""
    try:
        closed, _ = trace_candidate(model_name, (1, 1),
                                    global_batch=global_batch,
                                    input_hw=input_hw)
        from .costmodel import program_cost
        return int(program_cost(closed).flops)
    except Exception:  # noqa: BLE001 — reporting-only, never fatal
        return None
