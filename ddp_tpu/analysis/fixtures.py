"""Seeded-faulty fixtures — one per detector, each a known-bad program or
source text the matching auditor MUST flag (and the head registry must
not).  They are the auditor's own regression suite: ``python -m
ddp_tpu.analysis --fixture <name>`` exits nonzero under ``--strict`` for
every name here, and tests/test_analysis.py pins each detector to its
fixture so a refactor that silently blinds a check fails CI.

The jaxpr fixtures trace tiny hand-written shard_map programs (the same
``jax.shard_map``/``make_jaxpr`` path the registry uses) on the
(2, 4) = data x model virtual mesh; the source-text fixtures are inline
Python the AST passes scan.  Nothing here executes on a device.
"""
from __future__ import annotations

import textwrap
from typing import Callable, Dict, List

from .findings import Finding

_MESH_2D = (2, 4)


def _mesh():
    from ..parallel.mesh import make_mesh
    return make_mesh(shape=_MESH_2D)


def _trace(fn, *args):
    import jax
    return jax.make_jaxpr(fn)(*args)


# ---------------------------------------------------------------------------
# jaxpr fixtures
# ---------------------------------------------------------------------------

def wrong_axis_psum() -> List[Finding]:
    """An 'update' whose gradient reduction lands on ``model`` instead of
    ``data`` — each data shard trains on its local batch only."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, MODEL_AXIS
    from .jaxpr_audit import audit_collectives, collective_inventory

    mesh = _mesh()

    def _body(w, x):
        g = jnp.mean(x, axis=0) * w
        return w - 0.1 * lax.psum(g, MODEL_AXIS)       # wrong axis

    fn = jax.jit(jax.shard_map(
        _body, mesh=mesh, in_specs=(P(), P(DATA_AXIS)), out_specs=P()))
    w = jax.ShapeDtypeStruct((16,), jnp.float32)
    x = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    inv = collective_inventory(_trace(fn, w, x))
    return audit_collectives("fixture:wrong_axis_psum", "update", inv)


def model_axis_all_gather() -> List[Finding]:
    """A hot-path ``all_gather`` over ``model`` — rematerializes the
    sharded weights every step, the cliff TP exists to avoid."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import DATA_AXIS, MODEL_AXIS
    from .jaxpr_audit import audit_collectives, collective_inventory

    mesh = _mesh()

    def _body(w, x):
        full_w = lax.all_gather(w, MODEL_AXIS, tiled=True)  # the gather
        loss = jnp.sum(x @ full_w)
        return w - 0.1 * lax.psum(loss, DATA_AXIS) * jnp.ones_like(w)

    fn = jax.jit(jax.shard_map(
        _body, mesh=mesh, in_specs=(P(MODEL_AXIS), P(DATA_AXIS)),
        out_specs=P(MODEL_AXIS)))
    w = jax.ShapeDtypeStruct((16, 8), jnp.float32)
    x = jax.ShapeDtypeStruct((8, 16), jnp.float32)
    inv = collective_inventory(_trace(fn, w, x))
    return audit_collectives("fixture:model_axis_all_gather", "update", inv)


def captured_constant() -> List[Finding]:
    """An ~8 MiB array closed over instead of passed as an argument —
    baked into every executable, never donatable or shardable."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .jaxpr_audit import audit_constants

    table = jnp.asarray(np.ones((1024, 2048), np.float32))   # 8 MiB

    def _body(x):
        return x @ table

    x = jax.ShapeDtypeStruct((4, 1024), jnp.float32)
    return audit_constants("fixture:captured_constant", _trace(_body, x))


def missing_donation() -> List[Finding]:
    """An update step whose 4 MiB state buffer is not donated — the step
    permanently holds a dead second copy of the state in HBM."""
    import jax
    import jax.numpy as jnp

    from .jaxpr_audit import audit_donation

    def _body(w, g):
        return w - 0.1 * g

    fn = jax.jit(_body)      # donate_argnums deliberately absent
    w = jax.ShapeDtypeStruct((1024, 1024), jnp.float32)      # 4 MiB
    g = jax.ShapeDtypeStruct((1024, 1024), jnp.float32)
    return audit_donation("fixture:missing_donation", "update", fn, (w, g))


def budget_buster() -> List[Finding]:
    """A program ~30,000x over its flop budget — the cost-regression
    gate (``costmodel.check_budgets``) must flag it."""
    import jax
    import jax.numpy as jnp

    from .costmodel import check_budgets, program_cost

    def _body(a, b):
        return a @ b

    a = jax.ShapeDtypeStruct((256, 256), jnp.float32)
    b = jax.ShapeDtypeStruct((256, 256), jnp.float32)
    cost = program_cost(_trace(_body, a, b))     # 2*256^3 = 33.6 MFLOP
    table = {"fixture_matmul": cost.budget_row()}
    budgets = {"model": "fixture", "mesh_shape": [1, 1],
               "tolerance_pct": 10.0,
               "programs": {"fixture_matmul": {"flops": 1000}}}
    return check_budgets(table, budgets, "fixture", (1, 1))


def scalar_closure() -> List[Finding]:
    """A strongly-typed np hyperparameter closed into the program — it
    retraces per distinct value (warning-level: slow, not wrong).  Shape
    (1,) rather than 0-d because jax inlines literalable 0-d scalars;
    the np-wrapped-hyperparameter habit is what the check targets."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from .jaxpr_audit import audit_constants

    lr = np.full((1,), 0.1, np.float32)

    def _body(w):
        return w * (1.0 - lr)

    w = jax.ShapeDtypeStruct((8,), jnp.float32)
    return audit_constants("fixture:scalar_closure", _trace(_body, w))


# ---------------------------------------------------------------------------
# source-text fixtures
# ---------------------------------------------------------------------------

_HOT_LOOP_DEVICE_GET = textwrap.dedent("""\
    import jax

    def run_epoch(trainer, batches):
        losses = []
        for batch in batches:
            state, loss = trainer.train_step(trainer.state, batch)
            losses.append(float(loss))        # implicit per-step sync
            host = jax.device_get(state)      # explicit per-step sync
        return losses, host
    """)


def hot_loop_device_get() -> List[Finding]:
    """``jax.device_get`` (and a ``float()`` on the step's loss) inside
    the epoch loop — one device->host round trip per iteration."""
    from .hostsync import scan_source
    return scan_source("fixture:hot_loop_device_get.py",
                       _HOT_LOOP_DEVICE_GET)


_LOCK_FREE_SHARED_ATTR = textwrap.dedent("""\
    import threading

    class Worker:
        def __init__(self):
            self._lock = threading.Lock()
            self.count = 0                    # shared, never guarded
            self._thread = threading.Thread(target=self._run, daemon=True)

        def _run(self):
            while True:
                self.count += 1               # worker-side write, no lock

        def snapshot(self):
            return self.count                 # caller-side read, no lock
    """)


def lock_free_shared_attr() -> List[Finding]:
    """A counter mutated by the spawned thread and read by the caller
    with no lock and no annotation — the data-race shape the lockset
    lint exists to catch."""
    from .lockset import lint_source
    return lint_source("fixture:lock_free_shared_attr.py",
                       _LOCK_FREE_SHARED_ATTR)


_RANK_GATED_COLLECTIVE = textwrap.dedent("""\
    import jax
    from jax import lax

    def flush_epoch(stats):
        if jax.process_index() == 0:      # host-local rank check
            return lax.psum(stats, "data")
        return stats
    """)


def rank_gated_collective() -> List[Finding]:
    """A ``psum`` only rank 0 reaches — the other hosts never enter the
    collective and the pod hangs; the divergence lint's canonical
    finding."""
    from .divergence import scan_source
    return scan_source("fixture:rank_gated_collective.py",
                       _RANK_GATED_COLLECTIVE)


# ---------------------------------------------------------------------------

FIXTURES: Dict[str, Callable[[], List[Finding]]] = {
    "wrong_axis_psum": wrong_axis_psum,
    "model_axis_all_gather": model_axis_all_gather,
    "captured_constant": captured_constant,
    "missing_donation": missing_donation,
    "hot_loop_device_get": hot_loop_device_get,
    "lock_free_shared_attr": lock_free_shared_attr,
    "budget_buster": budget_buster,
    "rank_gated_collective": rank_gated_collective,
    "scalar_closure": scalar_closure,
}

# Every fixture a --strict run must fail on (scalar_closure is the one
# deliberate warning-severity fixture: reported, not fatal).
ERROR_FIXTURES = tuple(n for n in FIXTURES if n != "scalar_closure")


def fixture_names() -> List[str]:
    return list(FIXTURES)


def run_fixture(name: str) -> List[Finding]:
    if name not in FIXTURES:
        raise ValueError(f"unknown fixture {name!r}; "
                         f"have {fixture_names()}")
    return FIXTURES[name]()
