"""Donation-aware buffer-liveness: a static peak-live-bytes estimate per
traced program.

The claim "TP shards the model ÷m" or "ZeRO drops the optimizer state"
is usually folklore backed by an OOM that did or didn't happen.  This
pass turns it into a number the tests assert: walk the per-shard program
body in equation order, carrying the live-buffer set, and report the
peak.

Mechanics:

- **Find the body.**  A registry program traces as one top-level ``pjit``
  equation wrapping one ``shard_map`` equation wrapping the per-shard
  body.  The walk descends single-equation wrappers, carrying each
  input's DONATED flag through by variable identity — the flags live on
  the ``pjit`` equation's ``donated_invars`` param, exactly what
  ``jax.jit(..., donate_argnums=...)`` recorded at trace time.
- **Linear scan.**  Inputs are live at entry.  At each equation the
  candidate peak is (current live set) + (its outputs) + (its internal
  transient); afterwards every buffer whose last use this was is freed —
  but a NON-donated input can never be freed (the caller still owns it:
  that is precisely what donation buys), and program outputs survive to
  the end.  Unused outputs (including dropped ones) cost their bytes at
  the producing equation only.
- **Internal transients.**  A sub-jaxpr-bearing equation (the nested
  ``pjit`` of a fused layer, a ``scan`` body, a ``custom_vjp`` branch)
  can allocate above its boundary: its transient is
  ``max(0, sub_peak - sub_inputs - sub_outputs)``, computed recursively
  with the sub-inputs pinned (the caller's buffers are already counted).
  ``cond`` takes the worst branch.

The estimate is a lower bound on real HBM (XLA may fuse away transients
— good — or materialize layouts we don't see — bad), but it is ORDER
faithful: the same accounting applied to two programs ranks their memory
appetite, which is what the TP-vs-1D and ZeRO-vs-nonZeRO assertions in
tests/test_analysis.py consume.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

from .costmodel import _var_bytes

# Single-equation wrappers the body finder descends through.
_WRAPPER_PRIMITIVES = ("pjit", "shard_map", "closed_call", "core_call",
                       "remat", "checkpoint")


def _is_literal(v) -> bool:
    return hasattr(v, "val")


def _is_drop(v) -> bool:
    return type(v).__name__ == "DropVar"


def _sub_jaxpr_of(eqn):
    for key in ("jaxpr", "call_jaxpr"):
        sub = eqn.params.get(key)
        if sub is None:
            continue
        return sub.jaxpr if hasattr(sub, "jaxpr") else sub
    return None


def find_body(closed_jaxpr) -> Tuple[object, List[bool]]:
    """(per-shard body jaxpr, donated flag per body invar).

    Descends single-equation pjit/shard_map wrappers; a ``pjit``
    equation's ``donated_invars`` ORs into the flags, and flags follow
    variables by identity across each boundary (an inner input is donated
    iff the outer variable feeding it is)."""
    jaxpr = closed_jaxpr.jaxpr
    donated = [False] * len(jaxpr.invars)
    while len(jaxpr.eqns) == 1:
        eqn = jaxpr.eqns[0]
        if eqn.primitive.name not in _WRAPPER_PRIMITIVES:
            break
        inner = _sub_jaxpr_of(eqn)
        if inner is None:
            break
        flag_of = {v: d for v, d in zip(jaxpr.invars, donated)}
        new = []
        pjit_flags = eqn.params.get("donated_invars")
        for i, v in enumerate(eqn.invars):
            d = (not _is_literal(v)) and flag_of.get(v, False)
            if pjit_flags is not None and i < len(pjit_flags):
                d = d or bool(pjit_flags[i])
            new.append(d)
        jaxpr, donated = inner, new
    return jaxpr, donated


def _peak_of(jaxpr, donated: List[bool]) -> int:
    """Peak live bytes of one jaxpr body under the linear-scan rules."""
    n = len(jaxpr.eqns)
    last_use: Dict[object, int] = {}
    for i, eqn in enumerate(jaxpr.eqns):
        for v in eqn.invars:
            if not _is_literal(v):
                last_use[v] = i

    live: Dict[object, int] = {}
    for v in list(jaxpr.constvars):
        live[v] = _var_bytes(v)
        last_use[v] = n                      # consts owned by the caller
    for v, d in zip(jaxpr.invars, donated):
        live[v] = _var_bytes(v)
        if not d:
            last_use[v] = n                  # non-donated: never freeable
    for v in jaxpr.outvars:
        if not _is_literal(v):
            last_use[v] = n                  # outputs survive the program

    cur = sum(live.values())
    peak = cur
    for i, eqn in enumerate(jaxpr.eqns):
        out_bytes = sum(_var_bytes(v) for v in eqn.outvars)
        peak = max(peak, cur + out_bytes + _internal_transient(eqn))
        for v in eqn.outvars:
            if not _is_drop(v):
                live[v] = _var_bytes(v)
                cur += live[v]
        for v in list(eqn.invars) + list(eqn.outvars):
            if _is_literal(v):
                continue
            if v in live and last_use.get(v, i) <= i:
                cur -= live.pop(v)
    return peak


def _internal_transient(eqn) -> int:
    """Bytes a sub-jaxpr-bearing equation can allocate above its own
    input/output boundary (already counted by the caller)."""
    from .jaxpr_audit import _sub_jaxprs
    subs = list(_sub_jaxprs(eqn.params))
    if not subs:
        return 0
    extras = []
    for sub in subs:
        if hasattr(sub, "jaxpr"):              # ClosedJaxpr -> raw Jaxpr
            sub = sub.jaxpr
        boundary = (sum(_var_bytes(v) for v in sub.invars)
                    + sum(_var_bytes(v) for v in sub.outvars))
        sub_peak = _peak_of(sub, [False] * len(sub.invars))
        extras.append(max(0, sub_peak - boundary))
    if eqn.primitive.name == "cond":
        return max(extras)
    return sum(extras)


def liveness_of(closed_jaxpr) -> dict:
    """The per-program liveness report: ``peak_live_bytes`` plus the
    boundary decomposition (input/donated-input/output bytes) the
    memory-win assertions read.  ``donated_input_bytes`` is the state the
    update owns and recycles — params + momentum, the leaves TP shards ÷m
    — so TP-vs-1D compares it directly."""
    body, donated = find_body(closed_jaxpr)
    input_bytes = sum(_var_bytes(v) for v in body.invars)
    donated_bytes = sum(_var_bytes(v)
                        for v, d in zip(body.invars, donated) if d)
    output_bytes = sum(_var_bytes(v) for v in body.outvars
                       if not _is_literal(v))
    return {
        "peak_live_bytes": int(_peak_of(body, donated)),
        "input_bytes": int(input_bytes),
        "donated_input_bytes": int(donated_bytes),
        "output_bytes": int(output_bytes),
        "body_eqns": len(body.eqns),
    }
