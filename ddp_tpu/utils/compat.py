"""Compatibility shims for older jax runtimes (jax 0.4.x).

The framework targets jax>=0.9 (pyproject.toml): top-level
``jax.shard_map`` with the vma type system (``check_vma`` keyword,
``jax.lax.pcast``).  Some container images pin jax 0.4.x, where shard_map
lives at ``jax.experimental.shard_map.shard_map`` with the older
``check_rep`` keyword and no vma types — on such a runtime every
``jax.shard_map`` call site would raise ``AttributeError`` before a single
step ran.  These shims install the new names on the old runtime so ONE
codebase runs on both:

- ``jax.shard_map(f, mesh=..., in_specs=..., out_specs=...,
  check_vma=...)`` -> ``experimental.shard_map(..., check_rep=False)``.
  ``check_rep`` is always False here: the old rep-inference cannot prove
  the replicated ``P()`` out_specs of our train steps (it fails with
  "replication ... can't be statically inferred" on the grad-then-update
  program shape), while the vma system the code was written against can.
  Correctness does not depend on the check — with ``check_rep=False`` the
  transpose of a replicated (``P()``) input still inserts the
  conservative gradient ``psum`` (the check only enables the *efficient*
  transpose that elides redundant ones), and the numeric parity suite
  (tests/test_train_step.py golden traces, torch lockstep) is the
  backstop that this holds on any runtime the shim activates on.
- ``jax.lax.pcast(x, axis, to=...)`` -> identity.  The cast exists to
  satisfy the NEW type system (e.g. marking a scan carry varying before it
  meets sharded operands, train/epoch.py:make_eval_epoch); the old runtime
  has no vma types to satisfy, so the value itself passes through
  unchanged.

Installed idempotently at ``import ddp_tpu`` time; a no-op on jax>=0.9.
"""
from __future__ import annotations

import jax

_SHIMMED = False


def vma_semantics() -> bool:
    """True on jax>=0.9, where the vma type system governs shard_map
    autodiff and a ``custom_vjp`` opts out of the automatic gradient psum
    (so ops/layers.py's bn_relu must all-reduce its scale/bias cotangents
    explicitly — ``bn_grad_axis``).  False when the 0.4.x shim is active:
    there the runtime's own transpose machinery already produces
    globally-reduced cotangents for every replicated input, custom_vjp
    included, and the explicit psum would double-count by the mesh size
    (measured: exactly R x on BN scale/bias, tests/test_train_step.py::
    test_dp_mesh_exact_without_dropout)."""
    return not _SHIMMED


def persistent_cache_safe() -> bool:
    """False when the 0.4.x shim is active: on that image's jaxlib,
    executing a DESERIALIZED XLA:CPU executable corrupts the process heap.
    Measured two ways: warm-cache runs of the torch-parity suite segfault
    deterministically inside ``optimizer.zero_grad`` (cold compiles of the
    identical programs are stable), and a torch-free CLI resume subprocess
    on a warm cache died SIGSEGV after producing a NaN loss from a
    checkpoint that restores cleanly cold.  No process on this runtime may
    load from the persistent compilation cache — everything compiles
    fresh."""
    return not _SHIMMED


def install() -> None:
    global _SHIMMED
    if not hasattr(jax, "shard_map"):
        _SHIMMED = True
        # Persistent-cache kill-switch, applied HERE so every ddp_tpu
        # process gets it regardless of entry point: jax binds
        # JAX_COMPILATION_CACHE_DIR into jax.config at import time, so
        # popping the env var alone leaves the (heap-corrupting, see
        # persistent_cache_safe) cache active in-process — both the bound
        # config value and the env var (inherited by children) must go.
        import os
        os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)
        try:
            jax.config.update("jax_compilation_cache_dir", None)
        except Exception:
            pass  # config knob absent on this build: nothing was bound
        from jax.experimental.shard_map import shard_map as _shard_map

        def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True,
                      **kwargs):
            del check_vma  # see module docstring: always uncheck on 0.4.x
            return _shard_map(f, mesh=mesh, in_specs=in_specs,
                              out_specs=out_specs, check_rep=False,
                              **kwargs)

        jax.shard_map = shard_map
    if not hasattr(jax.lax, "pcast"):
        def pcast(x, axis_name, *, to):
            del axis_name, to
            return x

        jax.lax.pcast = pcast
    if not hasattr(jax.lax, "axis_size"):
        def axis_size(axis_name):
            # psum of the Python constant 1 is constant-folded to the
            # static axis size on 0.4.x (verified int, not a tracer) —
            # exactly what the new jax.lax.axis_size returns.
            return jax.lax.psum(1, axis_name)

        jax.lax.axis_size = axis_size


install()
