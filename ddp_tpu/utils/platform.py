"""Backend-platform pinning helpers.

Device-plugin platforms (e.g. the experimental axon TPU tunnel) override
the standard ``JAX_PLATFORMS`` env var, so any process that must run on a
specific backend needs a ``jax.config`` pin *before* backend init, and any
parent spawning such a process needs a consistent child environment.  This
is the single home for that workaround — bench.py, cli.py (--spawn) and
__graft_entry__.py (dryrun bootstrap) all share it.
"""
from __future__ import annotations

import os
import re
from typing import Dict, Optional

_DEVCOUNT_RE = re.compile(r"--xla_force_host_platform_device_count=\d+")


def pin_platform_from_env() -> None:
    """Apply a DDP_TPU_PLATFORM pin through jax.config (no-op if unset).
    Must run before any JAX backend initialisation."""
    platform = os.environ.get("DDP_TPU_PLATFORM")
    if platform:
        import jax
        jax.config.update("jax_platforms", platform)


def cpu_device_env(n_devices: int,
                   base_env: Optional[Dict[str, str]] = None
                   ) -> Dict[str, str]:
    """Child-process environment forcing an ``n_devices``-wide virtual CPU
    mesh: platform pinned via both JAX_PLATFORMS and DDP_TPU_PLATFORM (the
    latter survives plugin override when the child calls
    :func:`pin_platform_from_env` or imports ``ddp_tpu.cli``/``bench``),
    and exactly one ``--xla_force_host_platform_device_count`` flag."""
    env = dict(os.environ if base_env is None else base_env)
    env["JAX_PLATFORMS"] = "cpu"
    env["DDP_TPU_PLATFORM"] = "cpu"
    flags = _DEVCOUNT_RE.sub("", env.get("XLA_FLAGS", ""))
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    return env
