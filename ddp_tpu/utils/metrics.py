"""Per-step metrics logging.

The reference never logs training loss (SURVEY.md §5: its only telemetry is
the epoch-header print, multigpu.py:102, and end-of-run wall-clock/size/
accuracy prints) — but loss-curve parity can't be measured without a loss
stream, so the survey flags per-step loss emission as a required addition.

``MetricsLogger`` appends one JSON line per step: global step, epoch, loss,
effective LR, wall-clock seconds since construction.  Process-0 only (the
same gate as checkpoint writes, multigpu.py:118) — values are replicated
across the mesh, so one writer suffices.

``tensorboard_dir`` additionally mirrors the stream as TensorBoard scalars
(``train/loss``, ``train/lr``, ``eval/accuracy``) via ``tf.summary``;
tensorflow is imported lazily and only when the option is used — the
framework itself carries no tf dependency.
"""
from __future__ import annotations

import json
import time
from typing import IO, Optional


class MetricsLogger:
    def __init__(self, path: Optional[str], enabled: bool = True,
                 tensorboard_dir: Optional[str] = None):
        self.path = path
        self._f: Optional[IO[str]] = None
        self._tb = None
        self._t0 = time.time()
        if not enabled:
            return
        if path:
            self._f = open(path, "a", buffering=1)  # line-buffered
        if tensorboard_dir:
            try:
                import tensorflow as tf  # lazy: only this option needs it
            except ImportError as e:
                raise SystemExit(
                    "--tensorboard_dir needs tensorflow for tf.summary "
                    f"event files: {e}")
            self._tf = tf
            self._tb = tf.summary.create_file_writer(tensorboard_dir)

    def log_step(self, *, step: int, epoch: int, loss: float,
                 lr: float) -> None:
        if self._f is not None:
            self._f.write(json.dumps({
                "step": step, "epoch": epoch, "loss": round(loss, 6),
                "lr": round(lr, 8),
                "wall_s": round(time.time() - self._t0, 3),
            }) + "\n")
        if self._tb is not None:
            with self._tb.as_default():
                self._tf.summary.scalar("train/loss", loss, step=step)
                self._tf.summary.scalar("train/lr", lr, step=step)

    def log_event(self, kind: str, **fields) -> None:
        """Resilience/lifecycle event record (preemption checkpoint,
        fallback restore, non-finite loss, watchdog) — JSONL only; these
        are discrete events, not scalar curves, so no TensorBoard mirror.
        One line per event: ``{"event": kind, ...fields, "wall_s": t}``."""
        if self._f is not None:
            self._f.write(json.dumps({
                "event": kind, **fields,
                "wall_s": round(time.time() - self._t0, 3),
            }) + "\n")

    def log_eval(self, *, epoch: int, accuracy: float,
                 final: bool = False) -> None:
        """Eval-accuracy record: periodic (--eval_every) or, with
        ``final=True``, the end-of-run accuracy the reference prints
        (multigpu.py:247-248) — the run's headline metric, landed as the
        last record of the stream."""
        if self._f is not None:
            rec = {"epoch": epoch, "eval_accuracy": round(accuracy, 4),
                   "wall_s": round(time.time() - self._t0, 3)}
            if final:
                rec["final"] = True
            self._f.write(json.dumps(rec) + "\n")
        if self._tb is not None:
            with self._tb.as_default():
                self._tf.summary.scalar("eval/accuracy", accuracy,
                                        step=epoch)

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
