"""Run metrics logging — one sink for scalar curves AND discrete events.

The reference never logs training loss (SURVEY.md §5: its only telemetry
is the epoch-header print, multigpu.py:102, and end-of-run wall-clock/
size/accuracy prints) — but loss-curve parity can't be measured without a
loss stream, so the survey flags per-step loss emission as a required
addition.

``MetricsLogger`` appends one JSON line per record to ``path`` and, with
``tensorboard_dir``, mirrors numeric curves as ``tf.summary`` scalars.
Every record — per-step scalars (``log_step``), lifecycle events
(``log_event``), live telemetry (``log_live``, fed by obs/live.py) and
eval accuracy (``log_eval``) — goes through ONE internal ``_emit`` sink,
so the JSONL file and the TensorBoard mirror can never diverge and every
record carries the same ``wall_s`` clock.  That clock is
``time.monotonic()`` since construction: an NTP slew or clock jump
mid-run must not corrupt the one timeline all attribution hangs on
(``time.time()`` deltas did exactly that before round 7).

Process-0 only (the same gate as checkpoint writes, multigpu.py:118) —
values are replicated across the mesh, so one writer suffices.

tensorflow is imported lazily and only when ``tensorboard_dir`` is used —
the framework itself carries no tf dependency.

Durability: the JSONL handle is line-buffered (a crash loses at most the
in-flight line); :meth:`fsync` forces the tail to DISK and is called from
the preemption emergency-checkpoint path, so the records describing the
run's final verified state survive the SIGKILL that follows SIGTERM.
"""
from __future__ import annotations

import json
import os
import time
from typing import IO, Optional


class MetricsLogger:
    def __init__(self, path: Optional[str], enabled: bool = True,
                 tensorboard_dir: Optional[str] = None):
        self.path = path
        self._f: Optional[IO[str]] = None
        self._tb = None
        # Optional flight-recorder tap (obs/blackbox.py): every record
        # that reaches _emit is also appended to the recorder's bounded
        # ring, so a postmortem bundle carries the same event stream the
        # JSONL holds — without re-reading the file at crash time.
        self._recorder = None
        # Monotonic basis: wall_s must survive NTP slews / clock jumps
        # (it is the timeline every cross-record attribution joins on).
        self._t0 = time.monotonic()
        if not enabled:
            return
        if path:
            self._f = open(path, "a", buffering=1)  # line-buffered
        if tensorboard_dir:
            try:
                import tensorflow as tf  # lazy: only this option needs it
            except ImportError as e:
                raise SystemExit(
                    "--tensorboard_dir needs tensorflow for tf.summary "
                    f"event files: {e}")
            self._tf = tf
            self._tb = tf.summary.create_file_writer(tensorboard_dir)

    @property
    def active(self) -> bool:
        """True when at least one sink (JSONL or TensorBoard) is open —
        callers skip building telemetry no sink would receive."""
        return self._f is not None or self._tb is not None

    def attach_recorder(self, recorder) -> None:
        """Tee every emitted record into a flight recorder's ring
        (``recorder.record(rec)``) — cli.py attaches it right after
        constructing the :class:`obs.blackbox.FlightRecorder`."""
        self._recorder = recorder

    def _emit(self, rec: dict, scalars: Optional[dict] = None,
              step: Optional[int] = None) -> None:
        """THE sink: JSONL line (with the shared wall_s clock) plus the
        optional TensorBoard scalar mirror.  Every public log_* method
        lands here — one place for format, clock, and buffering policy."""
        stamped = {**rec, "wall_s": round(time.monotonic() - self._t0, 3)}
        if self._f is not None:
            self._f.write(json.dumps(stamped) + "\n")
        if self._recorder is not None:
            self._recorder.record(stamped)
        if self._tb is not None and scalars:
            with self._tb.as_default():
                for tag, val in scalars.items():
                    self._tf.summary.scalar(tag, val, step=step)

    def log_step(self, *, step: int, epoch: int, loss: float,
                 lr: float) -> None:
        self._emit({"step": step, "epoch": epoch, "loss": round(loss, 6),
                    "lr": round(lr, 8)},
                   scalars={"train/loss": loss, "train/lr": lr}, step=step)

    def log_event(self, kind: str, **fields) -> None:
        """Resilience/lifecycle event record (preemption checkpoint,
        fallback restore, non-finite loss, watchdog, phase stragglers) —
        JSONL only; these are discrete events, not scalar curves, so no
        TensorBoard mirror.  One line per event:
        ``{"event": kind, ...fields, "wall_s": t}``."""
        self._emit({"event": kind, **fields})

    def log_live(self, *, step: int, **fields) -> None:
        """Live telemetry record (obs/live.py: rolling median/p90 step
        time, samples/sec, MFU, prefetch occupancy) — JSONL plus a
        ``live/<field>`` TensorBoard curve per numeric field."""
        self._emit({"event": "live", "step": step, **fields},
                   scalars={f"live/{k}": v for k, v in fields.items()
                            if isinstance(v, (int, float))}, step=step)

    def log_eval(self, *, epoch: int, accuracy: float,
                 final: bool = False) -> None:
        """Eval-accuracy record: periodic (--eval_every) or, with
        ``final=True``, the end-of-run accuracy the reference prints
        (multigpu.py:247-248) — the run's headline metric, landed as the
        last record of the stream."""
        rec = {"epoch": epoch, "eval_accuracy": round(accuracy, 4)}
        if final:
            rec["final"] = True
        self._emit(rec, scalars={"eval/accuracy": accuracy}, step=epoch)

    def fsync(self) -> None:
        """Force the JSONL tail to disk — called from the preemption
        emergency-checkpoint path so the event tail survives SIGTERM
        (line buffering alone only reaches the OS page cache)."""
        if self._f is not None:
            self._f.flush()
            os.fsync(self._f.fileno())

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
