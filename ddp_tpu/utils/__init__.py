from .model_size import Byte, GiB, KiB, MiB, count_params, get_model_size

__all__ = ["Byte", "GiB", "KiB", "MiB", "count_params", "get_model_size"]
