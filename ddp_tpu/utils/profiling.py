"""Per-op summary of a jax.profiler trace — the analysis behind
BASELINE.md's roofline table, as a reusable tool.

The reference's only timing is two ``time.time()`` calls around training
(singlegpu.py:232-234); this framework additionally captures XLA traces
(``--profile_dir`` on the CLI, ``bench.py --profile_dir``) and this module
turns a captured trace into the numbers that matter on TPU: device-busy
time per step and the top ops by total device time, aggregated from the
``.xplane.pb`` the profiler writes.

Parsing uses the tensorflow-bundled xplane proto when available (the
heavyweight tensorboard profile plugin in this image is version-skewed
against its own pywrap helpers, so events are aggregated here directly);
set ``PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python`` if the fast-proto
runtime rejects the generated module.

Usage:
    python -m ddp_tpu.utils.profiling /tmp/prof [--steps 20] [--top 20]
"""
from __future__ import annotations

import argparse
import collections
import glob
import os
from typing import Dict, List, Optional, Tuple


def _load_xspaces(trace_dir: str) -> list:
    """All .xplane.pb files of the newest capture session (multi-host
    traces write one file per host; sessions are timestamped dirs)."""
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError as e:  # pragma: no cover - tf is baked into the image
        raise RuntimeError(
            "xplane parsing needs the tensorflow-bundled xplane proto; "
            f"import failed: {e}")
    sessions = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*")))
    if not sessions:
        raise FileNotFoundError(
            f"no capture sessions under {trace_dir}/plugins/profile/ — "
            "pass the directory given to jax.profiler.start_trace/"
            "--profile_dir")
    spaces = []
    for path in sorted(glob.glob(os.path.join(sessions[-1],
                                              "*.xplane.pb"))):
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        spaces.append(xs)
    if not spaces:
        raise FileNotFoundError(f"no .xplane.pb in {sessions[-1]}")
    return spaces


def device_op_summary(trace_dir: str, steps: int = 1,
                      device_plane: Optional[str] = None
                      ) -> Dict[str, List[Tuple[str, float, float]]]:
    """Aggregate per-op device time from a trace.

    Returns ``{"<plane>/<line>": [(op_name, total_ms, ms_per_step), ...]}``
    for EVERY device plane with events (one per chip; multi-host captures
    contribute one file per host), ops sorted by total time descending —
    nothing is silently dropped on multi-chip traces.  ``device_plane``
    restricts to one plane by exact name; ``steps`` divides totals into
    per-step cost (the number of steps captured in the trace).
    """
    planes = [p for xs in _load_xspaces(trace_dir) for p in xs.planes
              if (p.name == device_plane if device_plane
                  else ("/device:" in p.name
                        and any(len(ln.events) for ln in p.lines)))]
    if not planes:
        raise ValueError(f"no matching device plane with events in "
                         f"{trace_dir}")
    out: Dict[str, List[Tuple[str, float, float]]] = {}
    for plane in planes:
        for line in plane.lines:
            totals: collections.Counter = collections.Counter()
            for ev in line.events:
                totals[plane.event_metadata[ev.metadata_id].name] += \
                    ev.duration_ps
            out[f"{plane.name}/{line.name}"] = [
                (name, ps / 1e9, ps / 1e9 / max(steps, 1))
                for name, ps in totals.most_common()]
    return out


def print_summary(trace_dir: str, steps: int = 1, top: int = 20) -> None:
    summary = device_op_summary(trace_dir, steps=steps)
    for line_name, ops in summary.items():
        if not ops:
            continue
        total_ms = sum(t for _, t, _ in ops)
        print(f"--- {line_name}: {len(ops)} distinct ops, "
              f"{total_ms:.2f} ms total, {total_ms / max(steps, 1):.3f} "
              "ms/step")
        for name, tot, per in ops[:top]:
            print(f"  {per:8.3f} ms/step  {name[:100]}")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("trace_dir")
    p.add_argument("--steps", type=int, default=1,
                   help="Steps captured in the trace (divides totals)")
    p.add_argument("--top", type=int, default=20)
    args = p.parse_args()
    print_summary(args.trace_dir, steps=args.steps, top=args.top)


if __name__ == "__main__":
    main()
