"""Per-op summary of a jax.profiler trace — the analysis behind
BASELINE.md's roofline table, as a reusable tool.

The reference's only timing is two ``time.time()`` calls around training
(singlegpu.py:232-234); this framework additionally captures XLA traces
(``--profile_dir`` on the CLI, ``bench.py --profile_dir``) and this module
turns a captured trace into the numbers that matter on TPU: device-busy
time per step and the top ops by total device time, aggregated from the
``.xplane.pb`` the profiler writes.

Parsing uses the tensorflow-bundled xplane proto when available (the
heavyweight tensorboard profile plugin in this image is version-skewed
against its own pywrap helpers, so events are aggregated here directly);
set ``PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION=python`` if the fast-proto
runtime rejects the generated module.

Usage:
    python -m ddp_tpu.utils.profiling /tmp/prof [--steps 20] [--top 20]
"""
from __future__ import annotations

import argparse
import collections
import glob
import os
from typing import Dict, List, Optional, Tuple


def _load_xspaces(trace_dir: str) -> list:
    """All .xplane.pb files of the newest capture session (multi-host
    traces write one file per host; sessions are timestamped dirs)."""
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError as e:  # pragma: no cover - tf is baked into the image
        raise RuntimeError(
            "xplane parsing needs the tensorflow-bundled xplane proto; "
            f"import failed: {e}")
    sessions = sorted(glob.glob(os.path.join(
        trace_dir, "plugins", "profile", "*")))
    if not sessions:
        raise FileNotFoundError(
            f"no capture sessions under {trace_dir}/plugins/profile/ — "
            "pass the directory given to jax.profiler.start_trace/"
            "--profile_dir")
    spaces = []
    for path in sorted(glob.glob(os.path.join(sessions[-1],
                                              "*.xplane.pb"))):
        xs = xplane_pb2.XSpace()
        with open(path, "rb") as f:
            xs.ParseFromString(f.read())
        spaces.append(xs)
    if not spaces:
        raise FileNotFoundError(f"no .xplane.pb in {sessions[-1]}")
    return spaces


def device_op_summary(trace_dir: str, steps: int = 1,
                      device_plane: Optional[str] = None
                      ) -> Dict[str, List[Tuple[str, float, float]]]:
    """Aggregate per-op device time from a trace.

    Returns ``{"<plane>/<line>": [(op_name, total_ms, ms_per_step), ...]}``
    for EVERY device plane with events (one per chip; multi-host captures
    contribute one file per host), ops sorted by total time descending —
    nothing is silently dropped on multi-chip traces.  ``device_plane``
    restricts to one plane by exact name; ``steps`` divides totals into
    per-step cost (the number of steps captured in the trace).
    """
    planes = [p for xs in _load_xspaces(trace_dir) for p in xs.planes
              if (p.name == device_plane if device_plane
                  else ("/device:" in p.name
                        and any(len(ln.events) for ln in p.lines)))]
    if not planes:
        raise ValueError(f"no matching device plane with events in "
                         f"{trace_dir}")
    out: Dict[str, List[Tuple[str, float, float]]] = {}
    for plane in planes:
        for line in plane.lines:
            totals: collections.Counter = collections.Counter()
            for ev in line.events:
                totals[plane.event_metadata[ev.metadata_id].name] += \
                    ev.duration_ps
            out[f"{plane.name}/{line.name}"] = [
                (name, ps / 1e9, ps / 1e9 / max(steps, 1))
                for name, ps in totals.most_common()]
    return out


# Op-name → phase rules for categorize().  Order matters: first match wins.
# Derived from reading the optimized HLO of the VGG train step on v5e
# (BASELINE.md "fp32 kernel-level attack"): conv work appears as
# %convolution OR as kOutput fusions carrying a
# ``convolution_algorithm_config`` — multiply_reduce_fusion (dgrad conv +
# fused dγ/dβ epilogue), multiply_subtract_fusion (wgrad conv fused with
# the SGD update), and (XLA names these inconsistently) plain
# ``fusion.N`` (the forward convs + their BN-stats epilogues land here),
# which ONLY an HLO dump can disambiguate from elementwise fusions —
# hence conv_ops below.  Max-pool backward is select-and-scatter;
# copy/slice-start are async DMA.
_CATEGORY_RULES = (
    ("conv dgrad (+BN-bwd epilogue)", ("multiply_reduce_fusion",)),
    ("conv wgrad (+SGD update)", ("multiply_subtract_fusion",)),
    ("convolution (unfused)", ("convolution",)),
    ("pool backward", ("select_and_scatter", "select-and-scatter")),
    ("pool / reduce-window", ("reduce_window", "reduce-window")),
    ("collectives", ("all-reduce", "all-gather", "reduce-scatter",
                     "all-to-all", "collective-permute")),
    ("async copies/DMA", ("copy-start", "copy-done", "slice-start",
                          "slice-done", "dynamic-update-slice-start")),
    ("layout copies / bitcasts", ("copy", "bitcast", "transpose")),
    ("elementwise/reduction fusions", ("fusion",)),
)


def conv_fusions_from_hlo(hlo_text: str) -> Dict[str, str]:
    """Map fusion-op names that are really CONVOLUTIONS to a conv
    sub-kind.  The discriminator is ``convolution_algorithm_config`` in
    the backend_config — present exactly on conv emitters (a bare
    ``window_config`` appears on many unrelated TPU ops, including
    copies, and over-matches).  Feed the text from
    ``jitted.lower(...).compile().as_text()`` of the SAME program the
    trace captured — trace op names alone cannot distinguish a kOutput
    conv fusion named ``fusion.164`` from an elementwise one."""
    import re
    out: Dict[str, str] = {}
    for m in re.finditer(
            r"%(\S+) = [^\n]*convolution_algorithm_config", hlo_text):
        name = m.group(1)
        if "multiply_reduce" in name:
            kind = "conv dgrad (+BN-bwd epilogue)"
        elif "multiply_subtract" in name:
            kind = "conv wgrad (+SGD update)"
        else:
            kind = "conv (fused, kind per HLO)"
        out[name] = kind
    return out


def categorize(ops: List[Tuple[str, float, float]],
               conv_ops: Optional[Dict[str, str]] = None
               ) -> List[Tuple[str, float, float]]:
    """Fold a per-op list into phase buckets (same (name, total_ms,
    ms_per_step) tuples, sorted by total).  ``conv_ops`` (from
    :func:`conv_fusions_from_hlo`) reclassifies ambiguous ``fusion.N``
    names that are conv fusions.  Unmatched ops land in 'other'."""
    buckets: collections.Counter = collections.Counter()
    per: collections.Counter = collections.Counter()
    for name, tot, step_ms in ops:
        # Trace op names can be FULL definition lines ("%fusion.2 = (...)
        # fusion(%copy-done.57, ...)"); classify on the op's own name only
        # or operand names pollute the buckets (a fusion consuming
        # %copy-done.57 is not a copy).
        bare = name.lstrip("%").split(" = ")[0].split("(")[0].strip()
        if conv_ops and bare in conv_ops:
            label = conv_ops[bare]
        else:
            low = bare.lower()
            for label, keys in _CATEGORY_RULES:
                if any(k in low for k in keys):
                    break
            else:
                label = "other"
        buckets[label] += tot
        per[label] += step_ms
    return [(label, buckets[label], per[label])
            for label, _ in buckets.most_common()]


def device_busy_ms_per_step(trace_dir: str, steps: int = 1
                            ) -> Dict[str, float]:
    """Total device-busy ms/step per device plane line of a trace — the
    denominator of the streaming-gap attribution: for a profiled streaming
    run, ``wall_ms_per_step - max(busy line)`` is device IDLE per step,
    i.e. time the chip sat waiting on the input pipeline / dispatch
    (exactly how the round-4 resident-vs-step gap was attributed)."""
    return {line: sum(t for _, t, _ in ops) / max(steps, 1)
            for line, ops in device_op_summary(trace_dir,
                                               steps=steps).items()}


def attribute_streaming(host_ms: float, h2d_ms: float, step_ms: float,
                        wall_ms: float) -> Dict[str, float]:
    """Pipeline-model decomposition of a streaming run's per-step wall time
    (the BASELINE.md streaming-gap table; VERDICT r5 weak #5 / next #4).

    Inputs are the three stages measured in ISOLATION at the same shape
    (sequential host materialise+augment, blocking H2D upload,
    steady-state device step — the pipeline-floor model needs each
    stage's uncontended cost; the same run's tracer spans ship alongside
    as the record's ``phase_ms`` block, bench.py --stream_attr) plus the
    measured end-to-end streaming wall time per step.  In a perfectly overlapped pipeline the
    wall time equals the SLOWEST stage (the others hide behind it);
    everything above that floor is serialization the overlap engine
    failed to hide — dispatch gap.  Returns the stage costs, the
    bottleneck stage name, the pipeline floor, ``dispatch_gap_ms`` and
    ``overlap_efficiency`` (floor / wall; 1.0 = every non-bottleneck
    stage fully hidden).

    Edge discipline (measurement noise can put wall *below* the floor —
    e.g. a floor stage timed on a colder cache than the real run): the
    gap is CLAMPED at 0 and efficiency capped at 1.0, so a noisy sample
    reads as "fully overlapped", never as a negative gap a trend
    consumer would mis-sum; ``wall_ms <= 0`` (no steps ran) reports zero
    efficiency and zero gap rather than dividing by it.
    """
    stages = {"host_augment_ms": host_ms, "h2d_ms": h2d_ms,
              "device_step_ms": step_ms}
    bottleneck = max(stages, key=lambda k: stages[k])
    floor = stages[bottleneck]
    return {
        **{k: round(v, 3) for k, v in stages.items()},
        "streaming_wall_ms": round(wall_ms, 3),
        "bottleneck": bottleneck,
        "pipeline_floor_ms": round(floor, 3),
        "dispatch_gap_ms": round(max(wall_ms - floor, 0.0), 3)
        if wall_ms > 0 else 0.0,
        "overlap_efficiency": round(min(floor / wall_ms, 1.0), 4)
        if wall_ms > 0 else 0.0,
    }


def print_summary(trace_dir: str, steps: int = 1, top: int = 20,
                  by_category: bool = False,
                  hlo_path: Optional[str] = None) -> None:
    summary = device_op_summary(trace_dir, steps=steps)
    conv_ops = None
    if hlo_path:
        with open(hlo_path) as f:
            conv_ops = conv_fusions_from_hlo(f.read())
    for line_name, ops in summary.items():
        if not ops:
            continue
        total_ms = sum(t for _, t, _ in ops)
        print(f"--- {line_name}: {len(ops)} distinct ops, "
              f"{total_ms:.2f} ms total, {total_ms / max(steps, 1):.3f} "
              "ms/step")
        rows = categorize(ops, conv_ops) if by_category else ops[:top]
        for name, tot, per in rows:
            print(f"  {per:8.3f} ms/step  {name[:100]}")


def main() -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("trace_dir")
    p.add_argument("--steps", type=int, default=1,
                   help="Steps captured in the trace (divides totals)")
    p.add_argument("--top", type=int, default=20)
    p.add_argument("--by_category", action="store_true",
                   help="Fold ops into phase buckets (conv fwd/dgrad/"
                        "wgrad incl. their fused epilogues, pool, "
                        "collectives, DMA, elementwise) instead of "
                        "listing the top ops — the one-look roofline "
                        "attribution")
    p.add_argument("--hlo", default=None,
                   help="Optimized-HLO text file (from jitted.lower()."
                        "compile().as_text()) used to reclassify "
                        "ambiguous fusion.N names that are really conv "
                        "fusions — without it those land in the "
                        "elementwise bucket.  MUST come from the same "
                        "compiled program the trace captured: fusion "
                        "numbering is not stable across programs")
    args = p.parse_args()
    print_summary(args.trace_dir, steps=args.steps, top=args.top,
                  by_category=args.by_category, hlo_path=args.hlo)


if __name__ == "__main__":
    main()
