"""Model-size reporting — reference ``get_model_size`` + unit constants
(singlegpu.py:212-225)."""
from __future__ import annotations

import jax

# Reference unit constants (singlegpu.py:222-225): sizes are kept in *bits*.
Byte = 8
KiB = 1024 * Byte
MiB = 1024 * KiB
GiB = 1024 * MiB


def count_params(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def get_model_size(params, data_width: int = 32) -> int:
    """Model size in bits (reference semantics: #params * bits/param)."""
    return count_params(params) * data_width
