"""Convert between torch state_dicts and ddp_tpu pytrees.

Used by the parity tests (tests/test_models.py, tests/test_train_parity.py)
to load torch-initialised weights into the JAX models so forward/backward/
update numerics can be compared step-by-step against the reference math
(SURVEY.md section 4), and to export
checkpoints in the reference's flat ``backbone.conv0.weight``-style naming
(multigpu.py:110, key scheme from the add() helper at multigpu.py:45-47).

Layout conversions:
- conv kernels: torch OIHW  <->  ours HWIO   (transpose (2,3,1,0))
- linear weights: torch [out,in]  <->  ours [in,out]  (transpose)
- DeepNN's first linear additionally permutes its input axis because torch
  flattens NCHW and we flatten NHWC (see models/deepnn.py docstring).
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np


def _np(t) -> np.ndarray:
    # copy=True: torch tensors share memory with their .numpy() view, and on
    # the CPU backend jnp.asarray can be zero-copy over that view — without
    # the copy, torch's in-place buffer updates would mutate the JAX arrays.
    return np.array(t.detach().cpu().numpy() if hasattr(t, "detach") else t,
                    copy=True)


def conv_kernel_from_torch(w) -> jnp.ndarray:
    return jnp.asarray(_np(w).transpose(2, 3, 1, 0))  # OIHW -> HWIO


def conv_kernel_to_torch(k) -> np.ndarray:
    return np.asarray(k).transpose(3, 2, 0, 1)  # HWIO -> OIHW


def linear_weight_from_torch(w) -> jnp.ndarray:
    return jnp.asarray(_np(w).T)


def vgg_from_torch_state_dict(sd) -> Tuple[Dict, Dict]:
    """Reference-named VGG state_dict -> (params, batch_stats)."""
    backbone: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    i = 0
    while f"backbone.conv{i}.weight" in sd:
        backbone[f"conv{i}"] = {
            "kernel": conv_kernel_from_torch(sd[f"backbone.conv{i}.weight"])}
        backbone[f"bn{i}"] = {
            "scale": jnp.asarray(_np(sd[f"backbone.bn{i}.weight"])),
            "bias": jnp.asarray(_np(sd[f"backbone.bn{i}.bias"]))}
        stats[f"bn{i}"] = {
            "mean": jnp.asarray(_np(sd[f"backbone.bn{i}.running_mean"])),
            "var": jnp.asarray(_np(sd[f"backbone.bn{i}.running_var"]))}
        i += 1
    params = {
        "backbone": backbone,
        "classifier": {
            "weight": linear_weight_from_torch(sd["classifier.weight"]),
            "bias": jnp.asarray(_np(sd["classifier.bias"]))},
    }
    return params, stats


def vgg_to_torch_state_dict(params: Dict, batch_stats: Dict
                            ) -> Dict[str, np.ndarray]:
    """Export in the reference checkpoint key scheme (multigpu.py:110)."""
    out: Dict[str, np.ndarray] = {}
    backbone = params["backbone"]
    i = 0
    while f"conv{i}" in backbone:
        out[f"backbone.conv{i}.weight"] = conv_kernel_to_torch(
            backbone[f"conv{i}"]["kernel"])
        out[f"backbone.bn{i}.weight"] = np.asarray(backbone[f"bn{i}"]["scale"])
        out[f"backbone.bn{i}.bias"] = np.asarray(backbone[f"bn{i}"]["bias"])
        out[f"backbone.bn{i}.running_mean"] = np.asarray(
            batch_stats[f"bn{i}"]["mean"])
        out[f"backbone.bn{i}.running_var"] = np.asarray(
            batch_stats[f"bn{i}"]["var"])
        i += 1
    out["classifier.weight"] = np.asarray(params["classifier"]["weight"]).T
    out["classifier.bias"] = np.asarray(params["classifier"]["bias"])
    return out


def deepnn_from_torch_state_dict(sd) -> Tuple[Dict, Dict]:
    """DeepNN state_dict -> (params, {}).

    Maps by tensor rank + registration order rather than by name, so any
    ``nn.Sequential`` numbering works.  The first linear's input axis is
    permuted from torch's channel-major flatten to our NHWC flatten.
    """
    conv_ws = [v for k, v in sd.items() if _np(v).ndim == 4]
    conv_bs = [v for k, v in sd.items()
               if _np(v).ndim == 1 and "features" in k]
    lin_ws = [v for k, v in sd.items() if _np(v).ndim == 2]
    lin_bs = [v for k, v in sd.items()
              if _np(v).ndim == 1 and "classifier" in k]
    assert len(conv_ws) == 4 and len(lin_ws) == 2

    features = {
        f"conv{i}": {"kernel": conv_kernel_from_torch(conv_ws[i]),
                     "bias": jnp.asarray(_np(conv_bs[i]))}
        for i in range(4)
    }
    w0 = _np(lin_ws[0])                       # [512, 2048], input = (c,h,w)
    w0 = w0.reshape(512, 32, 8, 8).transpose(0, 2, 3, 1).reshape(512, 2048)
    params = {
        "features": features,
        "classifier": {
            "linear0": {"weight": jnp.asarray(w0.T),
                        "bias": jnp.asarray(_np(lin_bs[0]))},
            "linear1": {"weight": linear_weight_from_torch(lin_ws[1]),
                        "bias": jnp.asarray(_np(lin_bs[1]))},
        },
    }
    return params, {}


def deepnn_to_torch_state_dict(params: Dict) -> Dict[str, np.ndarray]:
    """Inverse of :func:`deepnn_from_torch_state_dict`, keyed for the
    reference module layout (``features`` Sequential with convs at 0/2/5/7,
    ``classifier`` with linears at 0/3 — singlegpu.py:18-44).  The first
    linear's input axis is permuted back from our NHWC flatten to torch's
    channel-major flatten."""
    out: Dict[str, np.ndarray] = {}
    feats = params["features"]
    for i, slot in enumerate((0, 2, 5, 7)):
        out[f"features.{slot}.weight"] = conv_kernel_to_torch(
            feats[f"conv{i}"]["kernel"])
        out[f"features.{slot}.bias"] = np.asarray(feats[f"conv{i}"]["bias"])
    w0 = np.asarray(params["classifier"]["linear0"]["weight"]).T  # [512,2048]
    out["classifier.0.weight"] = (
        w0.reshape(512, 8, 8, 32).transpose(0, 3, 1, 2).reshape(512, 2048))
    out["classifier.0.bias"] = np.asarray(
        params["classifier"]["linear0"]["bias"])
    out["classifier.3.weight"] = np.asarray(
        params["classifier"]["linear1"]["weight"]).T
    out["classifier.3.bias"] = np.asarray(
        params["classifier"]["linear1"]["bias"])
    return out


def _bn_from_torch(sd, prefix: str) -> Tuple[Dict, Dict]:
    return ({"scale": jnp.asarray(_np(sd[f"{prefix}.weight"])),
             "bias": jnp.asarray(_np(sd[f"{prefix}.bias"]))},
            {"mean": jnp.asarray(_np(sd[f"{prefix}.running_mean"])),
             "var": jnp.asarray(_np(sd[f"{prefix}.running_var"]))})


def resnet18_from_torch_state_dict(sd) -> Tuple[Dict, Dict]:
    """torchvision.models.resnet18 state_dict -> (params, batch_stats)."""
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    params["conv1"] = {"kernel": conv_kernel_from_torch(sd["conv1.weight"])}
    params["bn1"], stats["bn1"] = _bn_from_torch(sd, "bn1")
    for si in range(1, 5):
        for bi in range(2):
            tp = f"layer{si}.{bi}"
            name = f"layer{si}.block{bi}"
            blk: Dict[str, Any] = {}
            bst: Dict[str, Any] = {}
            blk["conv1"] = {"kernel": conv_kernel_from_torch(
                sd[f"{tp}.conv1.weight"])}
            blk["bn1"], bst["bn1"] = _bn_from_torch(sd, f"{tp}.bn1")
            blk["conv2"] = {"kernel": conv_kernel_from_torch(
                sd[f"{tp}.conv2.weight"])}
            blk["bn2"], bst["bn2"] = _bn_from_torch(sd, f"{tp}.bn2")
            if f"{tp}.downsample.0.weight" in sd:
                ds_bn, ds_st = _bn_from_torch(sd, f"{tp}.downsample.1")
                blk["downsample"] = {
                    "conv": {"kernel": conv_kernel_from_torch(
                        sd[f"{tp}.downsample.0.weight"])},
                    "bn": ds_bn}
                bst["downsample_bn"] = ds_st
            params[name] = blk
            stats[name] = bst
    params["fc"] = {"weight": linear_weight_from_torch(sd["fc.weight"]),
                    "bias": jnp.asarray(_np(sd["fc.bias"]))}
    return params, stats


def _bn_to_torch(out: Dict[str, np.ndarray], prefix: str,
                 p: Dict, s: Dict) -> None:
    out[f"{prefix}.weight"] = np.asarray(p["scale"])
    out[f"{prefix}.bias"] = np.asarray(p["bias"])
    out[f"{prefix}.running_mean"] = np.asarray(s["mean"])
    out[f"{prefix}.running_var"] = np.asarray(s["var"])


def resnet18_to_torch_state_dict(params: Dict, batch_stats: Dict
                                 ) -> Dict[str, np.ndarray]:
    """Inverse of :func:`resnet18_from_torch_state_dict` — torchvision
    ``resnet18`` naming, so the export loads strictly into the stock
    torchvision model."""
    out: Dict[str, np.ndarray] = {}
    out["conv1.weight"] = conv_kernel_to_torch(params["conv1"]["kernel"])
    _bn_to_torch(out, "bn1", params["bn1"], batch_stats["bn1"])
    for si in range(1, 5):
        for bi in range(2):
            tp = f"layer{si}.{bi}"
            blk = params[f"layer{si}.block{bi}"]
            bst = batch_stats[f"layer{si}.block{bi}"]
            out[f"{tp}.conv1.weight"] = conv_kernel_to_torch(
                blk["conv1"]["kernel"])
            _bn_to_torch(out, f"{tp}.bn1", blk["bn1"], bst["bn1"])
            out[f"{tp}.conv2.weight"] = conv_kernel_to_torch(
                blk["conv2"]["kernel"])
            _bn_to_torch(out, f"{tp}.bn2", blk["bn2"], bst["bn2"])
            if "downsample" in blk:
                out[f"{tp}.downsample.0.weight"] = conv_kernel_to_torch(
                    blk["downsample"]["conv"]["kernel"])
                _bn_to_torch(out, f"{tp}.downsample.1",
                             blk["downsample"]["bn"], bst["downsample_bn"])
    out["fc.weight"] = np.asarray(params["fc"]["weight"]).T
    out["fc.bias"] = np.asarray(params["fc"]["bias"])
    return out
