"""Cross-replica SDC drift audit — bit-level parameter fingerprints
compared across the ``data`` axis with one tiny collective.

Data-parallel training's core invariant is that every replica applies the
IDENTICAL update to IDENTICAL parameters (the lockstep contract DDP relies
on at multigpu.py:97, and the replicated-weight structure the framework's
``P()`` param sharding encodes).  That makes silent data corruption —
a flipped HBM bit, a miscompiled kernel on one chip, a torn DMA —
*detectable by construction*: replicas must agree bit-for-bit, so any
disagreement is a fault, full stop.  No tolerance window, no float
epsilon.

The audit folds each replica's parameter pytree into a per-leaf 32-bit
fingerprint (a multiplicative hash over the raw bit patterns — NOT a
float sum, which could cancel a corruption or differ benignly in
reduction order) and compares against replica 0's fingerprints with two
``psum``s over ``data``.  Payload per audit: ``2 * n_leaves * 4`` bytes
per device pair — a few hundred bytes for the bundled models, priced and
budgeted like every other collective (``analysis/costmodel.py``; the
``drift_audit@dp8`` registry entry).  The full parameter gather it
replaces would be the entire model.

uint32 throughout: ``jnp.uint64`` needs the x64 flag the framework never
enables, and modular uint32 arithmetic is exactly what a checksum wants.

Divergence handling (``DriftAuditor``): a named ``drift_detected`` event
with the offending leaf paths and per-replica mismatch counts, then the
configured action — ``abort`` (:class:`DriftDetectedError`) or
``restore`` (reload the newest verifiable snapshot through the trainer's
existing :class:`~ddp_tpu.resilience.guard.RestoreFromLastGood` path,
sharing the guard's restore budget so persistent corruption cannot
restore-loop forever).
"""
from __future__ import annotations

import sys
from typing import List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.mesh import DATA_AXIS

DRIFT_ACTIONS = ("abort", "restore")

# Knuth's multiplicative constant — any odd constant with good bit mixing
# works; the hash only needs "a single flipped input bit changes the sum
# with overwhelming probability", not cryptographic strength.
_HASH_MULT = 2654435761


class DriftDetectedError(RuntimeError):
    """Replicas disagree bit-for-bit and the action said stop."""


def _leaf_fingerprint(x: jax.Array) -> jax.Array:
    """uint32 checksum of one leaf's raw bit pattern.

    32-bit dtypes are bitcast (bit-exact sensitivity: any flipped bit
    changes the fingerprint); other widths are cast to float32 first —
    still deterministic and replica-comparable, just quantized.  The
    per-element position is mixed in so two swapped values don't cancel.
    """
    flat = x.ravel()
    if flat.dtype == jnp.uint32 or flat.dtype == jnp.int32:
        bits = flat.astype(jnp.uint32) if flat.dtype == jnp.int32 \
            else flat
    elif flat.dtype.itemsize == 4:
        bits = lax.bitcast_convert_type(flat, jnp.uint32)
    else:
        bits = lax.bitcast_convert_type(flat.astype(jnp.float32),
                                        jnp.uint32)
    pos = lax.iota(jnp.uint32, bits.shape[0])
    h = (bits ^ (pos * jnp.uint32(0x9E3779B9))) * jnp.uint32(_HASH_MULT)
    h = h ^ (h >> 15)
    return jnp.sum(h, dtype=jnp.uint32)


def leaf_paths(params) -> List[str]:
    """Dot-joined key paths of ``params``' leaves, in flatten order —
    the names a ``drift_detected`` event reports."""
    flat, _ = jax.tree_util.tree_flatten_with_path(params)
    return [jax.tree_util.keystr(kp) for kp, _ in flat]


def make_drift_audit(mesh):
    """Build the jitted audit program: ``fn(params) -> (counts, fps)``.

    ``counts``: ``[L]`` uint32, replicated — for each leaf, how many
    replicas disagree with replica 0's fingerprint (0 everywhere ⇔ the
    lockstep invariant holds).  ``fps``: ``[R, L]`` uint32 sharded over
    ``data`` — the per-replica fingerprint matrix, for naming WHICH
    replica diverged in the event.  Params are NOT donated (the audit
    must never invalidate the live training state) and the only
    collectives are two ``psum``s over ``data`` — the shape the jaxpr
    auditor's generic invariants (axis whitelist, no gathers) verify for
    the registered ``drift_audit`` program.
    """
    def body(params):
        leaves = jax.tree_util.tree_leaves(params)
        fps = jnp.stack([_leaf_fingerprint(x) for x in leaves])  # [L]
        rid = lax.axis_index(DATA_AXIS)
        # Replica 0's row, broadcast to everyone: mask-and-sum is one
        # psum (no pbroadcast/ppermute — both are banned by the audit).
        fp0 = lax.psum(jnp.where(rid == 0, fps, jnp.zeros_like(fps)),
                       DATA_AXIS)
        mism = (fps != fp0).astype(jnp.uint32)
        counts = lax.psum(mism, DATA_AXIS)
        return counts, fps[None, :]

    # check_vma=False: the params are replicated, so the VMA tracker
    # would rewrite the mask-and-psum into pbroadcast+psum2 (primitives
    # the auditor bans).  The collectives here are explicit and total —
    # the same unchecked regime train/zero.py and the TP wiring use.
    mapped = jax.shard_map(body, mesh=mesh, in_specs=(P(),),
                           out_specs=(P(), P(DATA_AXIS, None)),
                           check_vma=False)
    return jax.jit(mapped)


class DriftAuditor:
    """Every-K-steps audit driver for the trainer's streaming loop.

    Synchronous by design: an audit step host-reads the ``[L]`` counts
    vector (a few hundred bytes) and decides before the next dispatch —
    corruption must not get K more steps of spreading through checkpoint
    writes while the verdict floats in the async stream.  The ms this
    costs every K steps is what ``bench.py --guard_overhead`` prices
    (<1% ms/step at K=50 on the bench box, BENCH_r10.json).
    """

    def __init__(self, mesh, params_like, *, every: int,
                 action: str = "abort", registry=None):
        if action not in DRIFT_ACTIONS:
            raise ValueError(
                f"drift_action must be one of {DRIFT_ACTIONS}, got "
                f"{action!r}")
        self.every = int(every)
        self.action = action
        self.paths = leaf_paths(params_like)
        self._fn = make_drift_audit(mesh)
        self.last_audit_step: int = -1  # watchdog stall-context surface
        self.detections = 0
        self.audits = 0
        if registry is not None:
            # Function-backed: this object stays the source of truth.
            registry.counter(
                "ddp_drift_audits_total",
                "Cross-replica SDC audits run").set_function(
                    lambda: float(self.audits))
            registry.counter(
                "ddp_drift_detections_total",
                "Audits that found cross-replica parameter drift"
            ).set_function(lambda: float(self.detections))

    def due(self, step: int) -> bool:
        return self.every > 0 and step > 0 and step % self.every == 0

    def audit(self, params, step: int, *, metrics=None, tracer=None,
              guard=None) -> None:
        """Run one audit at global ``step``; raise per the action on
        divergence.  ``guard`` (the trainer's StepHealthGuard) supplies
        the shared restore budget for ``action='restore'``."""
        self.last_audit_step = int(step)
        self.audits += 1
        counts, fps = self._fn(params)
        counts = np.asarray(jax.device_get(counts))
        if not counts.any():
            return
        self.detections += 1
        bad = np.flatnonzero(counts)
        bad_paths = [self.paths[i] for i in bad[:16]]
        fps_host = np.asarray(jax.device_get(fps))  # [R, L]
        bad_replicas = sorted({
            int(r) for i in bad
            for r in np.flatnonzero(fps_host[:, i] != fps_host[0, i])})
        msg = (f"cross-replica parameter drift at global step {step}: "
               f"{len(bad)}/{counts.size} leaves diverge "
               f"(e.g. {bad_paths[:4]}), replicas {bad_replicas[:8]} "
               "disagree with replica 0 — silent data corruption on at "
               "least one replica")
        print(f"WARNING: {msg}", file=sys.stderr)
        sys.stderr.flush()
        if metrics is not None:
            metrics.log_event(
                "drift_detected", step=int(step), action=self.action,
                leaves=bad_paths, replicas=bad_replicas[:32],
                n_leaves_diverged=int(len(bad)))
            metrics.fsync()  # the verdict must survive an abort
        if self.action == "restore":
            from .guard import RestoreFromLastGood
            if guard is not None:
                if guard.restores >= guard.max_restores:
                    raise DriftDetectedError(
                        f"{msg}; restore budget exhausted "
                        f"({guard.restores}/{guard.max_restores})")
                guard.restores += 1
                guard.last_decision = f"drift_restore@step={int(step)}"
            print("WARNING: --drift_action restore: reloading the last "
                  "verified checkpoint", file=sys.stderr)
            sys.stderr.flush()
            raise RestoreFromLastGood(msg)
        raise DriftDetectedError(
            f"{msg}; --drift_action abort (pass --drift_action restore "
            "to roll back to the last verified checkpoint instead)")
