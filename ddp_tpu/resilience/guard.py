"""Step health guard — the ``--on_nan {abort,skip,restore}`` policy.

Detection rides the trainer's existing deferred-loss flush: every epoch's
per-step losses already cross device->host as one stacked transfer
(``Trainer._flush_losses``), so checking them costs ZERO extra D2H reads —
the reference (which never reads the loss at all, SURVEY.md §5) could not
have this for free.  Detection is therefore *post-hoc*: the update that
produced a non-finite loss has already been applied, and on non-save epochs
it may surface one epoch late (the flush is deferred by design).  What the
policies mean under that model:

``abort``   (default) raise :class:`NonFiniteLossError` — fail fast, and
            because the trainer flushes/checks an epoch's losses *before*
            checkpointing it, the newest checkpoint on disk is always one
            whose losses were verified finite.
``skip``    log and keep training (the reference's implicit behavior, made
            explicit); NaNs may persist in the parameters.
``restore`` reload the newest verifiable checkpoint (lineage fall-back
            included) and continue from there with a re-seeded step RNG —
            the re-seed changes the augmentation/dropout stream so a
            numerics-driven divergence doesn't deterministically replay.
            Bounded by ``max_restores``; exhausting it raises.
"""
from __future__ import annotations

import sys

import numpy as np

POLICIES = ("abort", "skip", "restore")


class NonFiniteLossError(RuntimeError):
    """Training produced a non-finite loss and the policy said stop."""


class RestoreFromLastGood(Exception):
    """Internal control-flow signal: ``Trainer.train`` catches this and
    reloads the newest verifiable checkpoint (``on_nan=restore``)."""


class StepHealthGuard:
    def __init__(self, policy: str = "abort", max_restores: int = 8):
        if policy not in POLICIES:
            raise ValueError(
                f"on_nan policy must be one of {POLICIES}, got {policy!r}")
        self.policy = policy
        self.max_restores = int(max_restores)
        self.restores = 0  # also the RNG re-seed counter (trainer folds it)

    def check(self, losses: np.ndarray, *, epoch: int,
              start_step: int) -> None:
        """Apply the policy to one flushed epoch's loss vector.  Raises
        per policy; returns normally when all losses are finite (or under
        ``skip``)."""
        finite = np.isfinite(losses)
        if finite.all():
            return
        bad = np.flatnonzero(~finite)
        steps = [int(start_step + i) for i in bad[:8]]
        msg = (f"non-finite loss at epoch {epoch}, global step(s) {steps}"
               f"{' (+more)' if len(bad) > 8 else ''} "
               f"[{len(bad)}/{losses.size} steps affected]")
        if self.policy == "skip":
            print(f"WARNING: {msg}; --on_nan skip: continuing (parameters "
                  "may carry NaNs)", file=sys.stderr)
            sys.stderr.flush()
            return
        if self.policy == "restore":
            if self.restores >= self.max_restores:
                raise NonFiniteLossError(
                    f"{msg}; restore budget exhausted "
                    f"({self.restores}/{self.max_restores} restores used)")
            self.restores += 1
            print(f"WARNING: {msg}; --on_nan restore: reloading the last "
                  f"good checkpoint (restore {self.restores}/"
                  f"{self.max_restores})", file=sys.stderr)
            sys.stderr.flush()
            raise RestoreFromLastGood(msg)
        raise NonFiniteLossError(
            f"{msg}; --on_nan abort (pass --on_nan skip|restore to "
            "continue instead)")
