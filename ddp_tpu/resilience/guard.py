"""Step health guard — loss-stream anomaly policy, ``--on_nan`` included.

Detection rides the trainer's existing deferred-loss flush: every epoch's
per-step losses already cross device->host as one stacked transfer
(``Trainer._flush_losses``), so checking them costs ZERO extra D2H reads —
the reference (which never reads the loss at all, SURVEY.md §5) could not
have this for free.  Detection is therefore *post-hoc*: the update that
produced a bad loss has already been applied, and on non-save epochs it
may surface one epoch late (the flush is deferred by design).

Two detectors share the one decision path:

**Non-finite** (``--on_nan {abort,skip,restore}``, the original policy —
the flag survives as an alias for the corresponding guard actions):

``abort``   (default) raise :class:`NonFiniteLossError` — fail fast, and
            because the trainer flushes/checks an epoch's losses *before*
            checkpointing it, the newest checkpoint on disk is always one
            whose losses were verified finite.
``skip``    log and keep training (the reference's implicit behavior, made
            explicit); NaNs may persist in the parameters.
``restore`` reload the newest verifiable checkpoint (lineage fall-back
            included) and continue from there with a re-seeded step RNG —
            the re-seed changes the augmentation/dropout stream so a
            numerics-driven divergence doesn't deterministically replay.
            Bounded by ``max_restores``; exhausting it raises.

**Spike** (round 12, ``--guard_spike_factor``; 0 = off, the default —
tier-1 behavior is bit-identical with it off): a rolling median/MAD
window over the finite losses; a step whose loss exceeds
``median * spike_factor + 3 * MAD`` (with at least ``_MIN_WINDOW``
history) is anomalous.  Actions (``--guard_action``):

``skip``        log the spike, keep training (and keep the spike OUT of
                the window so one outlier doesn't inflate the baseline).
``lr_backoff``  halve the learning rate going forward (the trainer
                rebuilds its jitted step with the scaled schedule via
                the ``on_lr_backoff`` hook) — the instability response
                that keeps the trajectory instead of rewinding it.
``rollback``    restore the last verified snapshot, re-seed, and SKIP the
                poisoned batch window on replay (the raised
                :class:`RestoreFromLastGood` names the bad steps;
                the trainer maps them to ``(epoch, batch)`` positions
                and drops them from the resumed epoch) — the poisoned-
                shard response: re-ingesting the same bad data would
                just spike again.  Shares the non-finite restore budget.
``abort``       raise :class:`LossSpikeError` — fail fast.

The guard is *series-agnostic*: :meth:`check_series` applies the same
window/threshold machinery to any named per-step statistic — the loss is
wired today; a step variant that emits a global grad-norm feeds it
through the identical path with ``name="grad_norm"``.

Every decision lands as a ``guard_decision`` metrics event and a counter,
and ``last_decision`` holds a one-line summary the watchdog's stall
context prints — a hung rollback is diagnosable from the stall dump.
"""
from __future__ import annotations

import sys
from collections import Counter, deque
from typing import Dict, List, Optional

import numpy as np

POLICIES = ("abort", "skip", "restore")
SPIKE_ACTIONS = ("abort", "skip", "lr_backoff", "rollback")

_MIN_WINDOW = 8  # spike verdicts need this much history to be robust
_LR_BACKOFF_FACTOR = 0.5


class NonFiniteLossError(RuntimeError):
    """Training produced a non-finite loss and the policy said stop."""


class LossSpikeError(RuntimeError):
    """The loss spiked past the guard's threshold and the action said
    stop."""


class RestoreFromLastGood(Exception):
    """Internal control-flow signal: ``Trainer.train`` catches this and
    reloads the newest verifiable checkpoint (``on_nan=restore``, the
    guard's ``rollback`` action, and ``--drift_action restore``).

    ``skip_steps``/``skip_epoch`` (spike-rollback only): the global steps
    whose batches poisoned the run — the trainer maps them to epoch-local
    batch positions and skips them on replay.
    """

    def __init__(self, msg: str, *, skip_steps: Optional[List[int]] = None,
                 skip_epoch: Optional[int] = None):
        super().__init__(msg)
        self.skip_steps = skip_steps or []
        self.skip_epoch = skip_epoch


class StepHealthGuard:
    def __init__(self, policy: str = "abort", max_restores: int = 8, *,
                 window: int = 64, spike_factor: float = 0.0,
                 spike_action: str = "rollback", metrics=None,
                 registry=None):
        if policy not in POLICIES:
            raise ValueError(
                f"on_nan policy must be one of {POLICIES}, got {policy!r}")
        if spike_action not in SPIKE_ACTIONS:
            raise ValueError(
                f"guard_action must be one of {SPIKE_ACTIONS}, got "
                f"{spike_action!r}")
        if spike_factor < 0:
            raise ValueError(
                f"guard_spike_factor must be >= 0 (0 disables spike "
                f"detection), got {spike_factor}")
        self.policy = policy
        self.max_restores = int(max_restores)
        self.restores = 0  # also the RNG re-seed counter (trainer folds it)
        self.spike_factor = float(spike_factor)
        self.spike_action = spike_action
        self.metrics = metrics
        self.last_decision = "none"  # watchdog stall-context surface
        self.decisions: Counter = Counter()
        # Mirror every decision into the run's metrics registry as a
        # labelled family; ``decisions`` stays the in-process truth
        # (test-pinned API), the registry is the scrape surface.
        self._reg_decisions = (
            registry.counter("ddp_guard_decisions_total",
                             "Step-health guard decisions by kind",
                             ("decision",))
            if registry is not None else None)
        self.lr_scale = 1.0
        # Trainer hook: called with the new cumulative LR scale when the
        # lr_backoff action fires (the trainer rebuilds its jitted step
        # with the scaled schedule).  None = action degrades to a logged
        # skip (embedders without the hook must not crash).
        self.on_lr_backoff = None
        self._windows: Dict[str, deque] = {}
        self._maxlen = max(int(window), _MIN_WINDOW)

    # -- decision bookkeeping ---------------------------------------------

    def _decide(self, decision: str, *, step: int, **fields) -> None:
        self.decisions[decision] += 1
        if self._reg_decisions is not None:
            self._reg_decisions.labels(decision=decision).inc()
        self.last_decision = f"{decision}@step={int(step)}"
        if self.metrics is not None:
            self.metrics.log_event("guard_decision", decision=decision,
                                   step=int(step), **fields)

    # -- non-finite policy (the original --on_nan path) -------------------

    def check(self, losses: np.ndarray, *, epoch: int,
              start_step: int) -> None:
        """Apply the policy to one flushed epoch's loss vector.  Raises
        per policy; returns normally when all losses are healthy (or
        under ``skip``).  Non-finite first (it dominates: a NaN is also
        an outlier), then the spike detector over the finite entries."""
        losses = np.asarray(losses)
        finite = np.isfinite(losses)
        if not finite.all():
            self._check_nonfinite(losses, finite, epoch=epoch,
                                  start_step=start_step)
        if self.spike_factor > 0:
            self.check_series("loss", losses[finite],
                              np.flatnonzero(finite) + start_step,
                              epoch=epoch)

    def _check_nonfinite(self, losses, finite, *, epoch: int,
                         start_step: int) -> None:
        bad = np.flatnonzero(~finite)
        steps = [int(start_step + i) for i in bad[:8]]
        msg = (f"non-finite loss at epoch {epoch}, global step(s) {steps}"
               f"{' (+more)' if len(bad) > 8 else ''} "
               f"[{len(bad)}/{losses.size} steps affected]")
        if self.policy == "skip":
            self._decide("nonfinite_skip", step=steps[0], epoch=epoch)
            print(f"WARNING: {msg}; --on_nan skip: continuing (parameters "
                  "may carry NaNs)", file=sys.stderr)
            sys.stderr.flush()
            return
        if self.policy == "restore":
            if self.restores >= self.max_restores:
                self._decide("nonfinite_abort", step=steps[0], epoch=epoch,
                             reason="restore budget exhausted")
                raise NonFiniteLossError(
                    f"{msg}; restore budget exhausted "
                    f"({self.restores}/{self.max_restores} restores used)")
            self.restores += 1
            self._decide("nonfinite_restore", step=steps[0], epoch=epoch,
                         restores=self.restores)
            print(f"WARNING: {msg}; --on_nan restore: reloading the last "
                  f"good checkpoint (restore {self.restores}/"
                  f"{self.max_restores})", file=sys.stderr)
            sys.stderr.flush()
            raise RestoreFromLastGood(msg)
        self._decide("nonfinite_abort", step=steps[0], epoch=epoch)
        raise NonFiniteLossError(
            f"{msg}; --on_nan abort (pass --on_nan skip|restore to "
            "continue instead)")

    # -- spike detector (any per-step series; the loss is wired) ----------

    def check_series(self, name: str, values, steps, *,
                     epoch: int) -> None:
        """Feed one flushed stretch of a named per-step statistic through
        the rolling median/MAD spike detector.  ``values[i]`` was
        observed at global step ``steps[i]``.  May raise per the spike
        action; healthy values extend the window."""
        if self.spike_factor <= 0:
            return
        win = self._windows.setdefault(name, deque(maxlen=self._maxlen))
        spike_steps: List[int] = []
        spike_vals: List[float] = []
        for v, s in zip(np.asarray(values, np.float64),
                        np.asarray(steps)):
            v = float(v)
            if len(win) >= _MIN_WINDOW:
                med = float(np.median(win))
                mad = float(np.median(np.abs(np.asarray(win) - med)))
                if v > med * self.spike_factor + 3.0 * mad:
                    # Anomalous: record, keep it OUT of the window (one
                    # outlier must not inflate the baseline).
                    spike_steps.append(int(s))
                    spike_vals.append(v)
                    continue
            win.append(v)
        if spike_steps:
            self._on_spike(name, spike_steps, spike_vals, epoch=epoch)

    def _on_spike(self, name: str, steps: List[int], values: List[float],
                  *, epoch: int) -> None:
        msg = (f"{name} spike at epoch {epoch}, global step(s) "
               f"{steps[:8]}{' (+more)' if len(steps) > 8 else ''}: "
               f"value(s) {[round(v, 4) for v in values[:4]]} exceed "
               f"median * {self.spike_factor} + 3*MAD over the last "
               f"{self._maxlen}-step window")
        action = self.spike_action
        if action == "lr_backoff" and self.on_lr_backoff is None:
            action = "skip"  # no trainer hook: degrade loudly below
        if action == "skip":
            self._decide("spike_skip", step=steps[0], epoch=epoch,
                         series=name, n=len(steps))
            print(f"WARNING: {msg}; --guard_action skip: continuing",
                  file=sys.stderr)
            sys.stderr.flush()
            return
        if action == "lr_backoff":
            self.lr_scale *= _LR_BACKOFF_FACTOR
            self._decide("spike_lr_backoff", step=steps[0], epoch=epoch,
                         series=name, lr_scale=self.lr_scale)
            print(f"WARNING: {msg}; --guard_action lr_backoff: scaling "
                  f"the LR schedule by {_LR_BACKOFF_FACTOR} (cumulative "
                  f"scale {self.lr_scale})", file=sys.stderr)
            sys.stderr.flush()
            self.on_lr_backoff(self.lr_scale)
            return
        if action == "rollback":
            if self.restores >= self.max_restores:
                self._decide("spike_abort", step=steps[0], epoch=epoch,
                             series=name,
                             reason="restore budget exhausted")
                raise LossSpikeError(
                    f"{msg}; restore budget exhausted "
                    f"({self.restores}/{self.max_restores} restores used)")
            self.restores += 1
            self._decide("spike_rollback", step=steps[0], epoch=epoch,
                         series=name, restores=self.restores,
                         skip_steps=steps[:32])
            print(f"WARNING: {msg}; --guard_action rollback: reloading "
                  "the last verified checkpoint and skipping the "
                  f"poisoned batch window (restore {self.restores}/"
                  f"{self.max_restores})", file=sys.stderr)
            sys.stderr.flush()
            raise RestoreFromLastGood(msg, skip_steps=steps,
                                      skip_epoch=epoch)
        self._decide("spike_abort", step=steps[0], epoch=epoch,
                     series=name)
        raise LossSpikeError(
            f"{msg}; --guard_action abort (pass --guard_action "
            "skip|lr_backoff|rollback to continue instead)")
