"""Run supervisor — the restart wrapper the exit-status contract was
written for (resilience/__init__ and preemption.py:40 both call exit 75
"the restart wrapper's cue"; this module IS that wrapper).

``python -m ddp_tpu.supervise -- multigpu.py 10 1 --snapshot ...`` launches
the training run as a child process and closes the recovery loop no
operator should sit in:

- exit 75 (preempted): the emergency checkpoint is already on disk —
  relaunch immediately with ``--resume``, no backoff (preemption is the
  scheduler's doing, not the run's).
- exit 124 (watchdog): the run stalled — relaunch with ``--resume`` under
  exponential backoff plus jitter (a wedged host often needs time to be
  replaced, and a thundering herd of restarts is how fleets melt).
- other nonzero: a classified crash — relaunch under the same backoff,
  but only while the failure ledger calls the death TRANSIENT.

Elastic restarts: before each relaunch the supervisor probes the live
device count and shrinks ``--mesh_shape`` to the largest surviving
``(d, m)`` — or, for a pipelined ``(d, m, s)`` run, the largest
``(d, m, s')`` with the STAGE axis giving way first, since the canonical
checkpoint restores onto any stage count and the partitioner re-cuts the
model into the surviving stages at relaunch — that the checkpoint
reshards onto (``load_for_mesh`` makes any shape restorable), then grows
back to the full mesh at the next relaunch once devices return.  Growth
only ever happens at a relaunch boundary — a running child's mesh is
immutable.

The failure ledger tails the child's metrics JSONL between launches and
keeps, per death, the exit code, the mesh it ran on, and the last
guard/drift event it recorded.  The same ``drift_detected``/``spike_*``
event at the same global step twice is not bad luck — it is a poisoned
step that will kill every future attempt identically, so the supervisor
stops burning restart budget and exits with a named diagnosis.

Supervisor exit codes (continuing the child contract):
  0    child completed (possibly after restarts)
  86   restart budget exhausted — ledger printed, newest verifiable
       checkpoint still on disk for a manual relaunch
  87   deterministic failure diagnosed (same failure signature at the
       same step twice) — crash-looping would spend budget re-proving it
"""
from __future__ import annotations

import argparse
import json
import os
import random
import subprocess
import sys
import time
from typing import Callable, List, Optional, Sequence, Tuple

SUPERVISOR_BUDGET_EXIT_STATUS = 86
SUPERVISOR_DETERMINISTIC_EXIT_STATUS = 87

# Test/CI seam: when set, the device probe trusts this count instead of
# spawning a JAX interpreter (a multi-second import on the CPU tier).
PROBE_ENV = "DDP_TPU_SUPERVISE_DEVICES"

# Set in every child's environment so cli.py's preemption message can say
# "the supervisor relaunches automatically" instead of telling a human to
# type --resume.
SUPERVISED_ENV = "DDP_TPU_SUPERVISED"


# -- pure helpers (unit-tested directly) -----------------------------------


def classify_exit(returncode: int) -> str:
    """``preempted`` (75) / ``stalled`` (124) / ``crash`` (anything else
    nonzero, including signal deaths reported as negative returncodes)."""
    from .preemption import EMERGENCY_CHECKPOINT_EXIT_STATUS
    from .watchdog import WATCHDOG_EXIT_STATUS
    if returncode == EMERGENCY_CHECKPOINT_EXIT_STATUS:
        return "preempted"
    if returncode == WATCHDOG_EXIT_STATUS:
        return "stalled"
    return "crash"


def backoff_delay(restart_no: int, *, base: float, cap: float,
                  jitter: float, rng: random.Random) -> float:
    """``min(base * 2**restart_no, cap)`` spread by ``±jitter`` (fractional)
    — the standard decorrelation so a rack of supervisors whose children
    died together does not relaunch them together."""
    nominal = min(base * (2.0 ** restart_no), cap)
    spread = 1.0 + jitter * (2.0 * rng.random() - 1.0)
    return max(0.0, nominal * spread)


def shrink_mesh(full: Tuple[int, ...], ndev: int) -> Tuple[int, ...]:
    """The largest surviving mesh under ``full`` that fits on ``ndev``
    devices; same arity out as in.

    2-D ``(D, M)``: the model axis is load-bearing (the checkpoint's
    layer shards assume M-way TP unless resharded), so shrink the DATA
    axis first and only split M when even one M-wide replica no longer
    fits — then the largest divisor of M that does.

    3-D ``(D, M, S)``: the STAGE axis shrinks first — losing a host
    kills a whole stage plane, the canonical checkpoint restores onto
    any stage count, and the partitioner simply re-cuts the model into
    the surviving ``s'`` stages at relaunch (``s'=1`` collapses to the
    plain 2-D mesh), so stages are the cheapest axis to give up.  Only
    when not even one (D, M) plane survives does the 2-D policy above
    take over (with s=1)."""
    dims = tuple(int(v) for v in full)
    ndev = max(1, int(ndev))
    if len(dims) == 3:
        d, m, s = dims
        if d * m * s <= ndev:
            return (d, m, s)
        if d * m <= ndev:
            return (d, m, max(1, ndev // (d * m)))
        d2, m2 = shrink_mesh((d, m), ndev)
        return (d2, m2, 1)
    d, m = dims
    if d * m <= ndev:
        return (d, m)
    if m <= ndev:
        return (max(1, ndev // m), m)
    # Not even one full model replica fits: largest divisor of M <= ndev.
    for cand in range(ndev, 0, -1):
        if m % cand == 0:
            return (1, cand)
    return (1, 1)


def _get_flag(argv: Sequence[str], name: str) -> Optional[str]:
    for i, a in enumerate(argv):
        if a == name and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith(name + "="):
            return a.split("=", 1)[1]
    return None


def _set_flag(argv: Sequence[str], name: str, value: str) -> List[str]:
    out = list(argv)
    for i, a in enumerate(out):
        if a == name and i + 1 < len(out):
            out[i + 1] = value
            return out
        if a.startswith(name + "="):
            out[i] = f"{name}={value}"
            return out
    out.extend([name, value])
    return out


def _ensure_resume(argv: Sequence[str]) -> List[str]:
    out = list(argv)
    if "--resume" not in out:
        out.append("--resume")
    return out


# -- failure ledger --------------------------------------------------------


def _iter_new_events(path: Optional[str], offset: int):
    """Parse the ``event`` records appended to the metrics JSONL since
    ``offset``; returns ``(events, new_offset)``.  Only complete lines are
    consumed — a torn trailing line is left for the next read."""
    if not path:
        return [], offset
    try:
        size = os.path.getsize(path)
    except OSError:
        return [], offset
    if size < offset:  # replaced/truncated by a fresh run
        offset = 0
    events = []
    try:
        with open(path, "r") as f:
            f.seek(offset)
            chunk = f.read()
    except OSError:
        return [], offset
    end = chunk.rfind("\n")
    if end < 0:
        return [], offset
    for line in chunk[:end].splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(rec, dict) and "event" in rec:
            events.append(rec)
    return events, offset + end + 1


def _failure_signature(events) -> Optional[Tuple[str, int]]:
    """The deterministic-failure fingerprint of one death: the LAST
    drift/guard anomaly event, keyed ``(what, step)``.  ``None`` when the
    death left no such event (nothing to match a recurrence against)."""
    for rec in reversed(events):
        kind = rec.get("event")
        if kind == "drift_detected" and "step" in rec:
            return ("drift_detected", int(rec["step"]))
        if kind == "guard_decision" and "step" in rec:
            decision = str(rec.get("decision", ""))
            if decision.startswith(("spike_", "nonfinite_")):
                return (decision, int(rec["step"]))
    return None


class FailureLedger:
    """Per-death forensic record: exit code, classified reason, the mesh
    the attempt ran on, the metrics events it appended, and the failure
    signature — the thing the transient-vs-deterministic call is made
    on.  Printed whenever the supervisor gives up."""

    def __init__(self, metrics_path: Optional[str] = None):
        self.metrics_path = metrics_path
        self.deaths: List[dict] = []
        self._offset = 0
        self._sig_counts: dict = {}
        # Flight-recorder linkage: the child dumps postmortem.json next
        # to the metrics JSONL on every abnormal exit (obs/blackbox.py).
        # Remember the bundle's identity at construction so a stale file
        # left by a PREVIOUS run is never attributed to this run's first
        # death — only a bundle that changed since last look counts.
        self._pm_seen = self._postmortem_stat()

    def _postmortem_path(self) -> Optional[str]:
        if not self.metrics_path:
            return None
        from ..obs.blackbox import POSTMORTEM_BASENAME  # stdlib-only
        return os.path.join(
            os.path.dirname(os.path.abspath(self.metrics_path)),
            POSTMORTEM_BASENAME)

    def _postmortem_stat(self) -> Optional[Tuple[float, int]]:
        path = self._postmortem_path()
        if not path:
            return None
        try:
            st = os.stat(path)
        except OSError:
            return None
        return (st.st_mtime, st.st_size)

    def _link_postmortem(self) -> Optional[dict]:
        """The just-dead child's flight-recorder bundle, schema-checked —
        or ``None`` when there is no FRESH bundle (a SIGKILLed child
        cannot dump; an unchanged file belongs to an earlier death)."""
        stat = self._postmortem_stat()
        if stat is None or stat == self._pm_seen:
            return None
        self._pm_seen = stat
        path = self._postmortem_path()
        from ..obs.blackbox import validate_postmortem  # stdlib-only
        try:
            with open(path) as f:  # type: ignore[arg-type]
                doc = json.load(f)
            validate_postmortem(doc)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            return {"path": path, "valid": False, "error": str(e)}
        return {"path": path, "valid": True, "reason": doc["reason"],
                "exit_status": doc["exit_status"]}

    def record_death(self, *, exit_code: int, reason: str,
                     mesh: Optional[str], wall_s: float) -> dict:
        events, self._offset = _iter_new_events(self.metrics_path,
                                                self._offset)
        sig = _failure_signature(events)
        count = 0
        if sig is not None:
            count = self._sig_counts.get(sig, 0) + 1
            self._sig_counts[sig] = count
        entry = {
            "death": len(self.deaths) + 1,
            "exit_code": int(exit_code),
            "reason": reason,
            "mesh": mesh,
            "wall_s": round(float(wall_s), 3),
            "events": len(events),
            "last_event": events[-1] if events else None,
            "signature": sig,
            "signature_count": count,
            "postmortem": self._link_postmortem(),
        }
        self.deaths.append(entry)
        return entry

    @staticmethod
    def is_deterministic(entry: dict) -> bool:
        """A crash whose signature has now been seen twice — spec'd as
        exactly-2 so one recurrence is enough and budget stops burning."""
        return (entry["reason"] == "crash"
                and entry["signature"] is not None
                and entry["signature_count"] >= 2)

    def format(self) -> str:
        lines = ["failure ledger "
                 f"({self.metrics_path or 'no metrics stream'}):"]
        if not self.deaths:
            lines.append("  (no deaths recorded)")
        for d in self.deaths:
            last = d["last_event"]
            last_txt = "-"
            if last is not None:
                step = last.get("step")
                last_txt = str(last.get("event"))
                if last.get("decision"):
                    last_txt += f":{last['decision']}"
                if step is not None:
                    last_txt += f"@step={step}"
            sig_txt = "-"
            if d["signature"] is not None:
                sig_txt = (f"{d['signature'][0]}@step={d['signature'][1]} "
                           f"(x{d['signature_count']})")
            pm = d.get("postmortem")
            pm_txt = "-"
            if pm is not None:
                pm_txt = (pm["reason"] if pm.get("valid")
                          else f"INVALID({pm.get('error', '?')})")
            lines.append(
                f"  death {d['death']}: exit {d['exit_code']} "
                f"({d['reason']}) mesh={d['mesh'] or '-'} "
                f"wall={d['wall_s']:.1f}s last_event={last_txt} "
                f"signature={sig_txt} postmortem={pm_txt}")
        return "\n".join(lines)


# -- device probe ----------------------------------------------------------


def probe_device_count(env: Optional[dict] = None,
                       timeout: float = 120.0) -> Optional[int]:
    """The live device count, from :data:`PROBE_ENV` when set (tests, CI)
    or a throwaway interpreter otherwise (the supervisor itself must not
    import jax — initializing a TPU runtime in the wrapper would hold the
    very devices the child needs).  ``None`` when the probe fails: the
    caller falls back to the full mesh and lets the child's own device
    check report the shortage."""
    env = dict(env if env is not None else os.environ)
    override = env.get(PROBE_ENV)
    if override:
        try:
            return max(1, int(override))
        except ValueError:
            return None
    try:
        out = subprocess.run(
            [sys.executable, "-c", "import jax; print(jax.device_count())"],
            env=env, capture_output=True, text=True, timeout=timeout)
        return int(out.stdout.strip().splitlines()[-1])
    except (subprocess.SubprocessError, OSError, ValueError, IndexError):
        return None


# -- the supervisor --------------------------------------------------------


def _default_launcher(argv: Sequence[str], env: dict) -> int:
    return subprocess.call(list(argv), env=env)


class Supervisor:
    """Launch ``child_argv``, classify its deaths, and relaunch with
    ``--resume`` under a bounded budget.  Every collaborator with a side
    effect (process launch, device probe, sleep, clock) is injectable so
    the edge-case tests run in milliseconds without subprocesses."""

    def __init__(self, child_argv: Sequence[str], *,
                 max_restarts: int = 5,
                 backoff_base: float = 1.0,
                 backoff_max: float = 60.0,
                 jitter: float = 0.25,
                 seed: Optional[int] = None,
                 keep_fault_env: bool = False,
                 prom_path: Optional[str] = None,
                 env: Optional[dict] = None,
                 launcher: Optional[Callable] = None,
                 device_probe: Optional[Callable] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic,
                 registry=None):
        self.child_argv = list(child_argv)
        self.max_restarts = int(max_restarts)
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.jitter = float(jitter)
        self.keep_fault_env = bool(keep_fault_env)
        self._rng = random.Random(seed)
        self._base_env = dict(env if env is not None else os.environ)
        self._launcher = launcher or _default_launcher
        self._device_probe = device_probe or probe_device_count
        self._sleep = sleep
        self._clock = clock
        # Full-mesh topology to grow back to, parsed once from the ORIGINAL
        # argv (later relaunches rewrite the flags in place).
        mesh = _get_flag(self.child_argv, "--mesh_shape")
        self._full_mesh: Optional[Tuple[int, ...]] = None
        if mesh:
            try:
                dims = tuple(int(x) for x in mesh.split(","))
                if len(dims) in (2, 3):
                    self._full_mesh = dims
            except ValueError:
                pass
        ndev = _get_flag(self.child_argv, "--num_devices")
        self._full_num_devices = (int(ndev)
                                  if ndev and ndev.isdigit() else None)
        metrics_path = _get_flag(self.child_argv, "--metrics_path")
        self.ledger = FailureLedger(metrics_path)
        self.prom_path = prom_path or (
            metrics_path + ".supervisor.prom" if metrics_path else None)
        if registry is None:
            from ..obs.registry import MetricsRegistry
            registry = MetricsRegistry()
        self.registry = registry
        from ..obs.registry import SECONDS_BUCKETS
        self._restarts_total = registry.counter(
            "ddp_supervisor_restarts_total",
            "Child relaunches by classified death reason", ("reason",))
        self._recovery_seconds = registry.histogram(
            "ddp_supervisor_recovery_seconds",
            "Child death to relaunch, seconds (backoff + probe included)",
            buckets=SECONDS_BUCKETS)
        self.restarts_used = 0

    # -- per-launch plumbing ----------------------------------------------

    def _child_env(self, first_launch: bool) -> dict:
        env = dict(self._base_env)
        env[SUPERVISED_ENV] = "1"
        if not first_launch and not self.keep_fault_env:
            # A step/epoch-triggered DDP_TPU_FAULT would re-fire on the
            # resumed run (the injectors count from the RESUMED host step,
            # already past the trigger) and preempt it forever — injected
            # faults are one drill each unless the campaign says otherwise.
            env.pop("DDP_TPU_FAULT", None)
        return env

    def _relaunch_argv(self, argv: Sequence[str]) -> List[str]:
        argv = _ensure_resume(argv)
        if self._full_mesh is None and self._full_num_devices is None:
            return argv  # no topology flags to manage
        ndev = self._device_probe(self._child_env(first_launch=False))
        if self._full_mesh is not None:
            full_n = 1
            for v in self._full_mesh:
                full_n *= v
            new = shrink_mesh(self._full_mesh,
                              full_n if ndev is None else ndev)
            if new != self._full_mesh:
                note = ""
                if len(new) == 3 and new[2] != self._full_mesh[2]:
                    note = (f" (stage plane lost: the partitioner re-cuts "
                            f"{self._full_mesh[2]} -> {new[2]} stage(s) "
                            f"from the canonical checkpoint)")
                print(f"[supervise] {ndev} device(s) live: shrinking mesh "
                      f"{','.join(map(str, self._full_mesh))} -> "
                      f"{','.join(map(str, new))} for this relaunch{note}",
                      file=sys.stderr)
            argv = _set_flag(argv, "--mesh_shape",
                             ",".join(map(str, new)))
        else:
            want = self._full_num_devices
            n = want if ndev is None else min(want, ndev)
            argv = _set_flag(argv, "--num_devices", str(max(1, n)))
        return argv

    def _write_prom(self) -> None:
        if not self.prom_path:
            return
        try:
            with open(self.prom_path, "w") as f:
                f.write(self.registry.exposition())
        except OSError as e:
            print(f"[supervise] WARNING: cannot write scrape file "
                  f"{self.prom_path!r}: {e}", file=sys.stderr)

    def _write_diagnosis(self, entry: dict) -> Optional[str]:
        """Exit-87 repro artifact: ``diagnosis.json`` next to the ledger's
        metrics stream.  A DETERMINISTIC verdict means a specific step
        poisons the run every time — this file pins everything needed to
        reproduce it after the fact: the failure signature (what + step +
        occurrences), the checkpoint the relaunches restored from (head
        ref incl. ``data_state`` and mirror status), the mirror URI, and
        the last guard/drift event of every death."""
        base = (os.path.dirname(os.path.abspath(self.ledger.metrics_path))
                if self.ledger.metrics_path else os.getcwd())
        path = os.path.join(base, "diagnosis.json")
        sig = entry.get("signature") or (None, None)
        snapshot = _get_flag(self.child_argv, "--snapshot_path")
        ckpt: Optional[dict] = None
        if snapshot:
            head = None
            try:
                from .lineage import read_manifest
                m = read_manifest(snapshot)
                if m is not None and isinstance(m.get("head"), dict):
                    head = m["head"]
            except Exception:  # noqa: BLE001 — forensics must not crash
                head = None
            ckpt = {"path": snapshot, "head": head}
        doc = {
            "schema": "supervisor_diagnosis/1",
            "verdict": "deterministic",
            "signature": {"what": sig[0], "step": sig[1],
                          "occurrences": entry.get("signature_count", 0)},
            "exit_code": entry.get("exit_code"),
            "mesh": entry.get("mesh"),
            "checkpoint": ckpt,
            "mirror": _get_flag(self.child_argv, "--mirror"),
            # The dying attempt's flight-recorder bundle (fresh-file
            # check in FailureLedger._link_postmortem): the autopsy for
            # `python -m ddp_tpu.obs --postmortem <path>`.
            "postmortem": entry.get("postmortem"),
            "last_events": [d.get("last_event")
                            for d in self.ledger.deaths],
            "deaths": self.ledger.deaths,
            "child_argv": list(self.child_argv),
        }
        try:
            tmp = path + f".tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1)
            os.replace(tmp, path)
        except OSError as e:
            print(f"[supervise] WARNING: cannot write diagnosis "
                  f"artifact {path!r}: {e}", file=sys.stderr)
            return None
        print(f"[supervise] diagnosis artifact written to {path}",
              file=sys.stderr)
        return path

    # -- the loop ----------------------------------------------------------

    def run(self) -> int:
        argv = list(self.child_argv)
        first = True
        backoff_no = 0  # escalates on stall/crash only, never preemption
        while True:
            mesh = (_get_flag(argv, "--mesh_shape")
                    or _get_flag(argv, "--num_devices"))
            print(f"[supervise] launching (attempt "
                  f"{self.restarts_used + 1}/"
                  f"{self.max_restarts + 1}): {' '.join(argv)}",
                  file=sys.stderr)
            sys.stderr.flush()
            t0 = self._clock()
            rc = self._launcher(argv, self._child_env(first))
            wall = self._clock() - t0
            if rc == 0:
                print(f"[supervise] child completed after "
                      f"{self.restarts_used} restart(s)", file=sys.stderr)
                self._write_prom()
                return 0
            reason = classify_exit(rc)
            entry = self.ledger.record_death(
                exit_code=rc, reason=reason, mesh=mesh, wall_s=wall)
            print(f"[supervise] child died: exit {rc} ({reason})",
                  file=sys.stderr)
            if FailureLedger.is_deterministic(entry):
                sig = entry["signature"]
                print(f"[supervise] DETERMINISTIC failure: "
                      f"{sig[0]} at step {sig[1]} recurred "
                      f"({entry['signature_count']} occurrences) — a "
                      "poisoned step, not bad luck; refusing to burn the "
                      "remaining restart budget", file=sys.stderr)
                print(self.ledger.format(), file=sys.stderr)
                self._write_diagnosis(entry)
                self._write_prom()
                return SUPERVISOR_DETERMINISTIC_EXIT_STATUS
            if self.restarts_used >= self.max_restarts:
                print(f"[supervise] restart budget exhausted "
                      f"({self.max_restarts} restart(s) used); giving up — "
                      "the newest verifiable checkpoint is still on disk "
                      "for a manual relaunch", file=sys.stderr)
                print(self.ledger.format(), file=sys.stderr)
                self._write_prom()
                return SUPERVISOR_BUDGET_EXIT_STATUS
            t_dead = self._clock()
            if reason == "preempted":
                delay = 0.0  # checkpoint already on disk; relaunch now
            else:
                delay = backoff_delay(backoff_no, base=self.backoff_base,
                                      cap=self.backoff_max,
                                      jitter=self.jitter, rng=self._rng)
                backoff_no += 1
            if delay > 0:
                print(f"[supervise] backing off {delay:.2f}s before "
                      "relaunch", file=sys.stderr)
                self._sleep(delay)
            argv = self._relaunch_argv(argv)
            self.restarts_used += 1
            self._restarts_total.labels(reason=reason).inc()
            # Death-to-relaunch recovery time: the wall clock covers the
            # backoff sleep and the device probe; under an injected
            # (instant) sleep the clock never moves, so the nominal delay
            # is the floor.
            self._recovery_seconds.observe(
                max(delay, self._clock() - t_dead))
            first = False


# -- CLI -------------------------------------------------------------------


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m ddp_tpu.supervise",
        description="Restart wrapper for ddp_tpu training runs: relaunch "
                    "with --resume on preemption (75) / stall (124) / "
                    "transient crash, under a bounded backoff budget, "
                    "with elastic mesh shrink-and-grow-back.",
        epilog="Everything after `--` is the child command; a leading "
               "*.py token is run under this interpreter.")
    p.add_argument("--max_restarts", type=int, default=5,
                   help="Restart budget (default 5); exhaustion exits "
                        f"{SUPERVISOR_BUDGET_EXIT_STATUS}")
    p.add_argument("--backoff_base", type=float, default=1.0,
                   help="First stall/crash backoff in seconds (default 1); "
                        "doubles per restart. Preemption never backs off.")
    p.add_argument("--backoff_max", type=float, default=60.0,
                   help="Backoff cap in seconds (default 60)")
    p.add_argument("--jitter", type=float, default=0.25,
                   help="Fractional backoff jitter (default 0.25)")
    p.add_argument("--seed", type=int, default=None,
                   help="Jitter RNG seed (reproducible drills)")
    p.add_argument("--prom", default=None, metavar="PATH",
                   help="Supervisor metrics scrape file (default: "
                        "<child --metrics_path>.supervisor.prom)")
    p.add_argument("--keep_fault_env", action="store_true",
                   help="Keep DDP_TPU_FAULT in relaunch environments "
                        "(default: stripped after the first launch so a "
                        "step-triggered fault is one drill, not a loop)")
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        own, child = argv[:split], argv[split + 1:]
    else:
        own, child = argv, []
    args = build_parser().parse_args(own)
    if not child:
        print("usage: python -m ddp_tpu.supervise [options] -- "
              "<child command>", file=sys.stderr)
        return 2
    if child[0].endswith(".py") or child[0] == "-m":
        child = [sys.executable] + child
    sup = Supervisor(child, max_restarts=args.max_restarts,
                     backoff_base=args.backoff_base,
                     backoff_max=args.backoff_max, jitter=args.jitter,
                     seed=args.seed, prom_path=args.prom,
                     keep_fault_env=args.keep_fault_env)
    return sup.run()


if __name__ == "__main__":
    raise SystemExit(main())
