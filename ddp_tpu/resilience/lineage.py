"""Checkpoint lineage: retained rotating snapshots + SHA-256 manifest.

The reference overwrites one fixed ``checkpoint.pt`` in place
(multigpu.py:111) and has no load path; our ``save_checkpoint`` already
writes atomically, so a crash mid-save never tears the head — but external
damage (a preempted copy, a truncated upload, filesystem rot) can, and
before this module a torn head made ``--resume`` fatal with nothing to fall
back to.

Layout (all siblings of the head path ``P``):
  ``P``                    the head — always the newest checkpoint
  ``P.ep<NNNNNNNN>``       rotated snapshots of former heads (hard links
                           made *before* each overwrite, so the old inode
                           survives ``os.replace``), newest ``keep - 1``
  ``P.manifest.json``      per-file epoch/step/sha256/size records,
                           written atomically after each head write

Single-writer discipline: every mutator here runs inside the trainer's one
async checkpoint writer thread (rank 0; ``Trainer._join_pending_save``
guarantees at most one in flight), which is what makes
preserve -> write -> commit -> trim safe without locking, and why rotation
can never delete a file the saver is still writing — the in-flight write is
always a ``*.tmp`` name this module never touches, and trimming happens in
the same thread after the write has landed.
"""
from __future__ import annotations

import glob
import json
import os
import re
import shutil
import sys
import tempfile
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..train.checkpoint import (Checkpoint, CheckpointError, load_checkpoint,
                                sha256_of_file)

MANIFEST_SUFFIX = ".manifest.json"
MANIFEST_FORMAT = 1


def _entry_shards(entry) -> List[str]:
    """A manifest entry's shard-file names (empty for gathered v1 heads
    and malformed entries)."""
    if not isinstance(entry, dict):
        return []
    return [str(s) for s in entry.get("shards", []) if s]


def lineage_name(path: str, epoch: int) -> str:
    """Rotated-snapshot name for the head state of ``epoch``."""
    return f"{path}.ep{int(epoch):08d}"


def _log(msg: str) -> None:
    print(msg, file=sys.stderr)
    sys.stderr.flush()


def _fsync_dir(d: str) -> None:
    """Durable-rename helper: fsync a directory, tolerating platforms
    (and filesystems) where directories cannot be fsynced."""
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def read_manifest(path: str) -> Optional[Dict[str, Any]]:
    """The head path's manifest, or None when absent/unparseable (a torn
    manifest is logged and treated as missing — the files themselves are
    still tried, so a damaged 1 KB JSON can never block a restore)."""
    mpath = path + MANIFEST_SUFFIX
    try:
        with open(mpath) as f:
            m = json.load(f)
    except FileNotFoundError:
        return None
    except (OSError, ValueError) as e:
        _log(f"WARNING: checkpoint manifest {mpath!r} is unreadable "
             f"({type(e).__name__}: {e}); proceeding without sha "
             "verification")
        return None
    return m if isinstance(m, dict) else None


class CheckpointLineage:
    """Rank-0 retention bookkeeping around one head checkpoint path."""

    def __init__(self, path: str, keep: int = 1):
        if keep < 1:
            raise ValueError(f"keep_checkpoints must be >= 1, got {keep}")
        self.path = path
        self.keep = int(keep)
        self.manifest_path = path + MANIFEST_SUFFIX
        # Tier hook: when a mirror uploader is attached (store.py), commit
        # stamps each entry's mirror status ("pending"/"mirrored") via this
        # epoch -> status callable.  Set once before the writer thread
        # starts and only CALLED from it, so manifest writes stay on the
        # single writer.  None = no mirror tier (status keys absent; old
        # manifests and mirror-less runs are byte-identical to before).
        self.mirror_state = None

    # -- write side (single writer thread) --------------------------------

    def preserve_head(self) -> None:
        """Hard-link the CURRENT head to its epoch-numbered lineage name
        *before* the next save overwrites it — ``os.replace`` drops the old
        inode's last name otherwise.  No-op with ``keep == 1``, with no head
        yet, or when the head is unreadable (a torn head is not worth
        preserving)."""
        if self.keep < 2 or not os.path.exists(self.path):
            return
        epoch = self._head_epoch()
        if epoch is None:
            return
        dst = lineage_name(self.path, epoch)
        if os.path.exists(dst):
            # A resumed run re-commits epochs: the head is the newest
            # authority for this epoch's state, so REPLACE the old name —
            # keeping it could leave a stale (even torn) file squatting on
            # the epoch slot and crowd the good state out of retention.
            try:
                os.unlink(dst)
            except OSError:
                return
        try:
            os.link(self.path, dst)
        except OSError:
            try:  # filesystems without hard links (some network mounts)
                shutil.copy2(self.path, dst)
            except OSError as e:
                _log(f"WARNING: could not preserve outgoing checkpoint "
                     f"{self.path!r} as {dst!r} ({e}); retention shrinks "
                     "by one this round")

    def _head_epoch(self) -> Optional[int]:
        # Read the epoch from the FILE, not the manifest: the answer then
        # doubles as a tear check (a torn head fails the npz read, returns
        # None, and is not preserved — garbage must not take an epoch
        # slot), and it is right even when the manifest is stale/absent.
        try:
            with np.load(self.path) as z:
                return int(z["meta/epoch"])
        except Exception:
            return None

    def commit(self, *, epoch: int, step: int, sha256: str,
               shards: Optional[List[str]] = None,
               data_state: Optional[Dict[str, Any]] = None) -> None:
        """Record the just-written head and trim retention to ``keep``
        states (the head plus ``keep - 1`` rotated snapshots).

        ``shards`` is the sharded (v2) format's pointer to the head's
        shard set (train/ckpt_shard.py): the epoch-qualified shard file
        names the head index references.  Each manifest entry carries its
        own shard list, and trimming unlinks exactly the shard files that
        dropped out of the manifest — never one a surviving entry (or the
        new head) still references, and structurally never an in-flight
        ``*.tmp`` write."""
        m = read_manifest(self.path) or {}
        retained: List[Dict[str, Any]] = [
            e for e in m.get("retained", []) if isinstance(e, dict)]
        prev_head = m.get("head")
        if isinstance(prev_head, dict) and self.keep >= 2 and \
                "epoch" in prev_head:
            fname = os.path.basename(
                lineage_name(self.path, int(prev_head["epoch"])))
            if os.path.exists(self._resolve(fname)):
                retained.insert(0, {**prev_head, "file": fname})
        # Dedupe by file name (a resume re-commits epochs), newest first.
        seen: set = set()
        retained = [e for e in retained
                    if e.get("file") not in seen
                    and not seen.add(e.get("file"))]
        # Shard files referenced BEFORE this commit (old head + every
        # retained entry, dropped ones included)...
        old_shards = set(_entry_shards(prev_head))
        for e in retained:
            old_shards |= set(_entry_shards(e))
        for dropped in retained[max(self.keep - 1, 0):]:
            self._unlink_rotated(dropped.get("file"))
        retained = retained[:max(self.keep - 1, 0)]
        head: Dict[str, Any] = {"file": os.path.basename(self.path),
                                "epoch": int(epoch), "step": int(step),
                                "sha256": sha256,
                                "size": os.path.getsize(self.path)}
        if shards:
            head["shards"] = [os.path.basename(s) for s in shards]
        if data_state is not None:
            # Mirrored from the checkpoint's own meta/data_state_json so
            # operators can read the resume position (epoch, iterator
            # offset, seed, rng folds) from the 1 KB manifest without
            # opening the npz.  The checkpoint file stays authoritative.
            head["data_state"] = data_state
        # ...minus the ones still referenced AFTER it = the set to trim.
        new_shards = set(_entry_shards(head))
        for e in retained:
            new_shards |= set(_entry_shards(e))
        for fname in sorted(old_shards - new_shards):
            self._unlink_shard(fname)
        if self.mirror_state is not None:
            head["mirror"] = self.mirror_state(int(epoch))
            for e in retained:
                if e.get("mirror") != "mirrored" and "epoch" in e:
                    e["mirror"] = self.mirror_state(int(e["epoch"]))
        manifest = {
            "format": MANIFEST_FORMAT,
            "head": head,
            "retained": retained,
        }
        d = os.path.dirname(os.path.abspath(self.manifest_path))
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            # Crash-atomic: fsync the bytes BEFORE the rename publishes
            # them (or power loss can promote an empty manifest over a
            # good one), and fsync the directory AFTER so the rename
            # itself is durable — rename ordering alone is a filesystem
            # implementation detail, not a guarantee.
            with os.fdopen(fd, "w") as f:
                json.dump(manifest, f, indent=1)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self.manifest_path)
            _fsync_dir(d)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def _resolve(self, fname: str) -> str:
        return os.path.join(os.path.dirname(os.path.abspath(self.path)),
                            fname)

    def _unlink_rotated(self, fname) -> None:
        """Delete a dropped rotation target — only ever a ``P.ep*`` sibling
        this module created; the head and any in-flight ``*.tmp`` write are
        structurally not candidates."""
        if not fname or not str(fname).startswith(
                os.path.basename(self.path) + ".ep"):
            return
        try:
            os.unlink(self._resolve(str(fname)))
        except OSError:
            pass  # already gone — retention is best-effort

    def _unlink_shard(self, fname) -> None:
        """Delete one dropped shard file (+ its multi-host ``.sha256``
        sidecar) — only the epoch-qualified ``P.ep*.shard*`` names the
        sharded saver created and the manifest stopped referencing."""
        name = str(fname or "")
        if not (name.startswith(os.path.basename(self.path) + ".ep")
                and ".shard" in name):
            return
        for victim in (name, name + ".sha256"):
            try:
                os.unlink(self._resolve(victim))
            except OSError:
                pass  # already gone — retention is best-effort


# -- read side (every rank, at resume / on_nan-restore time) --------------


def _candidates(path: str) -> List[Tuple[str, Optional[str]]]:
    """(file, expected_sha) restore candidates, newest first: the head,
    then the manifest's retained snapshots; without a manifest, a directory
    scan of the ``P.ep*`` naming (newest epoch first)."""
    m = read_manifest(path)
    out: List[Tuple[str, Optional[str]]] = []
    head_sha = None
    if m is not None and isinstance(m.get("head"), dict):
        head_sha = m["head"].get("sha256")
    if os.path.exists(path):
        out.append((path, head_sha))
    if m is not None:
        for e in m.get("retained", []):
            if not isinstance(e, dict) or not e.get("file"):
                continue
            fp = os.path.join(os.path.dirname(os.path.abspath(path)),
                              str(e["file"]))
            if os.path.exists(fp):
                out.append((fp, e.get("sha256")))
            else:
                _log(f"WARNING: checkpoint manifest lists {fp!r} but the "
                     "file is gone; skipping it as a restore candidate")
    else:
        # Manifest-less scan: rotated heads are exactly ``P.ep<digits>`` —
        # the sharded format's ``P.ep*.shard*`` data files live in the
        # same namespace and are NOT restore candidates themselves (their
        # epoch's index is).
        rotated = sorted(
            (fp for fp in glob.glob(glob.escape(path) + ".ep*")
             if re.fullmatch(r"\.ep\d+", fp[len(path):])),
            reverse=True)
        out.extend((fp, None) for fp in rotated)
    return out


def _resolve_head(path: str) -> str:
    """Accept either a head checkpoint path or a *directory* holding one.

    The serve engine is pointed at "where training checkpoints land",
    which operationally is a directory at least as often as a file.  A
    directory resolves to the head its manifest names; without a manifest
    the reference's fixed default ``checkpoint.pt`` (multigpu.py:111) is
    assumed.  Ambiguity (several ``*.manifest.json`` heads in one
    directory) is an error, not a guess — serving the wrong model must
    not be a silent outcome.
    """
    if not os.path.isdir(path):
        return path
    manifests = sorted(glob.glob(os.path.join(glob.escape(path),
                                              "*" + MANIFEST_SUFFIX)))
    if len(manifests) > 1:
        raise CheckpointError(
            f"checkpoint directory {path!r} holds {len(manifests)} lineage "
            f"manifests ({[os.path.basename(m) for m in manifests]}); pass "
            "the head checkpoint path explicitly")
    if manifests:
        return manifests[0][:-len(MANIFEST_SUFFIX)]
    return os.path.join(path, "checkpoint.pt")


def _restore_from_mirror(path: str, loader, store,
                         tried: List[Tuple[str, str]]
                         ) -> Optional[Tuple[Checkpoint, str]]:
    """Tier-2 fallback of :func:`latest_verifiable`: download verifiable
    mirror objects (head first, then retained, newest first) back into
    the local checkpoint directory — recreating it when the whole local
    disk is gone — and load them with the SAME loader/fallback semantics
    as the local walk.  Both formats restore: a gathered v1 head is one
    object; a sharded v2 entry downloads its index plus every shard file
    the mirror manifest lists.  Failures append to ``tried`` (the raise
    in the caller names every candidate, both tiers)."""
    base = os.path.basename(path)
    d = os.path.dirname(os.path.abspath(path))
    mname = base + MANIFEST_SUFFIX
    try:
        if store.stat(mname) is None:
            return None  # nothing ever mirrored — not an error
        rm = json.loads(store.get_bytes(mname).decode())
    except Exception as e:  # noqa: BLE001 — any store/parse damage
        tried.append((f"<mirror>/{mname}",
                      f"mirror manifest unreadable ({e})"))
        _log(f"WARNING: mirror manifest {mname!r} in {store.describe()} "
             f"is unreadable ({e}); no mirror fallback")
        return None
    if not isinstance(rm, dict):
        tried.append((f"<mirror>/{mname}", "mirror manifest malformed"))
        return None
    entries = [rm.get("head")] + list(rm.get("retained") or [])
    for e in entries:
        if not isinstance(e, dict) or not e.get("file"):
            continue
        fname = str(e["file"])
        local = os.path.join(d, fname)
        try:
            os.makedirs(d, exist_ok=True)
            store.get(fname, local)
            for s in _entry_shards(e):
                store.get(s, os.path.join(d, s))
        except Exception as ex:  # noqa: BLE001 — skip to older object
            tried.append((f"<mirror>/{fname}", str(ex)))
            _log(f"WARNING: mirror object {fname!r} is not restorable "
                 f"({ex}); falling back to the next mirrored snapshot")
            continue
        expected = e.get("sha256")
        if expected:
            try:
                actual = sha256_of_file(local)
            except OSError as ex:
                tried.append((f"<mirror>/{fname}", f"unreadable ({ex})"))
                continue
            if actual != expected:
                _log(f"WARNING: downloaded mirror object {fname!r} "
                     "sha256 mismatch vs mirror manifest; attempting "
                     "restore anyway")
        try:
            ck = loader(local)
        except (FileNotFoundError, CheckpointError) as ex:
            tried.append((f"<mirror>/{fname}", str(ex)))
            _log(f"WARNING: mirror object {fname!r} downloaded but does "
                 f"not restore ({ex}); falling back")
            continue
        _log(f"WARNING: restored checkpoint from MIRROR object {fname!r} "
             f"(epoch {ck.epoch}) via {store.describe()} — no local "
             f"candidate under {path!r} was verifiable")
        return ck, local
    return None


def latest_verifiable(
        path: Optional[str],
        loader=None, store=None) -> Optional[Tuple[Checkpoint, str]]:
    """Restore the newest verifiable checkpoint under ``path`` — the ONE
    manifest-walking selection both the trainer's resume and the serve
    engine's model load go through (a head checkpoint path, or a
    directory resolved by :func:`_resolve_head`).

    Tries the head first, then each retained snapshot newest-first.  A
    candidate whose manifest sha256 mismatches is logged and still
    *attempted* (a stale manifest — e.g. a preemption between the head
    write and the manifest write — must not discard a good head); a
    candidate the loader rejects (torn/foreign file, torn or missing
    SHARD of a v2 sharded set) is logged and skipped.  Falling back past
    the head is a recoverable, loudly-logged event — the behavior today's
    single-file resume cannot offer.

    ``loader`` maps a candidate file to a :class:`Checkpoint` — default
    ``load_checkpoint`` (host arrays, both formats).  The trainer and the
    serve engine pass ``ckpt_shard.load_for_mesh`` bound to their live
    mesh instead, so a sharded snapshot redistributes straight onto the
    surviving topology (elastic resume) with the SAME walk and fallback
    semantics: a loader must raise :class:`CheckpointError` for a
    candidate that cannot restore.

    ``store`` (a ``resilience.store.CheckpointStore``) adds the second
    durability tier: when every LOCAL candidate fails — or the local
    directory is gone entirely — the walk falls back to verifiable
    mirror objects via :func:`_restore_from_mirror`, downloading them
    back into place so the run continues exactly as a local restore
    would.  Local candidates always win when verifiable (they are never
    older than the mirror, which only uploads committed states).

    Returns ``(checkpoint, file_used)``; ``None`` when no candidate exists
    at all (fresh training); raises :class:`CheckpointError` naming every
    candidate tried (both tiers) when candidates exist but none restores.
    """
    if not path:
        return None
    if loader is None:
        loader = load_checkpoint
    path = _resolve_head(path)
    cands = _candidates(path)
    tried: List[Tuple[str, str]] = []
    for fp, expected_sha in cands:
        if expected_sha:
            try:
                actual = sha256_of_file(fp)
            except OSError as e:
                tried.append((fp, f"unreadable ({e})"))
                continue
            if actual != expected_sha:
                _log(f"WARNING: checkpoint {fp!r} sha256 mismatch vs "
                     "manifest (stale manifest or file damage); attempting "
                     "restore anyway")
        try:
            ck = loader(fp)
        except FileNotFoundError:
            tried.append((fp, "vanished before it could be read"))
            continue
        except CheckpointError as e:
            tried.append((fp, str(e)))
            _log(f"WARNING: checkpoint {fp!r} is not restorable ({e}); "
                 "falling back to the next retained snapshot")
            continue
        if fp != path:
            _log(f"WARNING: restored FALLBACK checkpoint {fp!r} "
                 f"(epoch {ck.epoch}) — the head {path!r} was torn or "
                 "missing")
        return ck, fp
    if store is not None:
        got = _restore_from_mirror(path, loader, store, tried)
        if got is not None:
            return got
    if not cands and not tried:
        return None
    raise CheckpointError(
        f"no verifiable checkpoint under {path!r}; candidates tried: "
        + "; ".join(f"{fp!r}: {why}" for fp, why in tried))


def head_fingerprint(path: Optional[str]):
    """Cheap publish-change detector for checkpoint watchers (the serve
    fleet's hot-swap poller): a hashable token that changes whenever a
    new head lands under ``path``, WITHOUT reading checkpoint bytes.

    Reads only the ~1 KB manifest (epoch/step/sha of the head) when one
    exists; a manifest-less head degrades to its stat signature.  Returns
    ``None`` when nothing resolvable exists yet — callers poll again.
    A fingerprint change is a *hint* to run the full (expensive, sha-
    verified) :func:`latest_verifiable` walk, never a load decision by
    itself: a torn head changes the fingerprint too, and the walk is
    what falls back / skips it.
    """
    if not path:
        return None
    try:
        head = _resolve_head(path)
    except CheckpointError:
        return None
    m = read_manifest(head)
    if m is not None and isinstance(m.get("head"), dict):
        h = m["head"]
        return ("manifest", h.get("epoch"), h.get("step"), h.get("sha256"))
    try:
        st = os.stat(head)
    except OSError:
        return None
    return ("stat", st.st_mtime_ns, st.st_size, None)


# Historical name (rounds 5-7); the trainer and serve engine both call
# latest_verifiable now, but external embedders may hold this spelling.
load_latest_verifiable = latest_verifiable
