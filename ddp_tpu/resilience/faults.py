"""Test-only fault injection — the failure modes the resilience layer
exists for, made reproducible on a laptop CPU mesh.

Nothing here runs in production paths: the injectors monkeypatch a single
``Trainer`` instance (no global state), and the one production touchpoint —
``cli.install_env_faults`` — is a no-op unless the :data:`FAULT_ENV`
variable is set, which only the drills in ``tests/test_resilience.py`` do.

Faults:
  ``tear_file``         truncate a checkpoint (external-damage model; the
                        atomic saver itself never produces a torn file)
  ``poison_loss``       replace the recorded loss at global step k with
                        NaN, once — drives the ``--on_nan`` policies
  ``sigterm_at_epoch``  deliver SIGTERM to this process at the end of
                        epoch k — the epoch-boundary preemption drill
  ``sigterm_at_step``   deliver SIGTERM right before global step k — the
                        mid-epoch preemption drill (data_state resume)
  ``flip_param_bit``    flip one bit of a parameter on ONE replica before
                        step k — the SDC the drift audit exists for
  ``poison_batch``      scale the batch at step k by 1e4 — the loss-spike
                        anomaly the rolling guard bounds
  ``torn_data_state``   tear a checkpoint's data_state record — resume
                        must degrade to the epoch boundary, warned once
  ``stall_at_epoch``    put one rank to sleep at the end of epoch k — the
                        hung-peer scenario the watchdog bounds
  ``fail_ckpt_write``   the async checkpoint write of epoch k dies on the
                        WRITER THREAD (a full disk / lost mount) — drives
                        the deferred ``trainer._save_error`` surfacing at
                        the next join, with the lineage left un-torn
  ``fail_put``          the next n mirror uploads fail at the store — the
                        flaky remote the uploader's backoff absorbs
  ``slow_put``          every mirror upload stalls ms at the store — the
                        hung remote the per-op deadline bounds (training
                        keeps stepping; mirror lag grows visibly)
  ``torn_remote_object``  the next mirror upload lands truncated under a
                        full-length sha — restore must detect + fall back
  ``wipe_local_ckpt``   delete every local lineage file after epoch k has
                        mirrored — total local-disk loss, mirror-only copy

Serve-side faults (the fleet chaos drills — tests/test_fleet.py and the
CI fleet smoke):
  ``crash_replica_at_request_n``  one replica dies permanently at its
                        n-th request: submits fail fast AND health
                        probes fail, so the router retries the request
                        elsewhere and then ejects the replica
  ``slow_forward_ms``   every request on one replica takes ms longer —
                        the straggler/overload scenario the deadline
                        budget and least-loaded routing bound
  ``torn_publish``      truncate the newest published head right before
                        the fleet's hot-swap watcher loads it, once —
                        drives the named ``swap_skipped`` path

Env surface for subprocess drills (``DDP_TPU_FAULT``): semicolon-separated
specs ``kind@key=val,key=val`` — e.g.
``sigterm@epoch=1``, ``sigterm@step=12``, ``poison@step=5``,
``flip_param_bit@step=6,replica=1``, ``poison_batch@step=9,scale=1e4``,
``stall@epoch=0,rank=1,secs=600``, ``fail_ckpt_write@epoch=1``,
``fail_put@n=2``, ``slow_put@ms=500``, ``torn_remote_object@``,
``wipe_local_ckpt@epoch=1``.  Serve processes
(``python -m ddp_tpu.serve --fleet N``) parse the same variable through
:func:`install_serve_faults` with the serve vocabulary:
``crash_replica@requests=25,replica=0``, ``slow_forward@ms=200,replica=1``,
``torn_publish@``.
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Optional

import jax
import numpy as np

FAULT_ENV = "DDP_TPU_FAULT"


def tear_file(path: str, keep_fraction: float = 0.5) -> None:
    """Truncate ``path`` to ``keep_fraction`` of its bytes (at least one
    byte shorter) — the torn-by-external-damage checkpoint."""
    size = os.path.getsize(path)
    keep = min(int(size * keep_fraction), size - 1)
    with open(path, "r+b") as f:
        f.truncate(max(keep, 0))


def poison_loss(trainer, step: int, value: float = float("nan")) -> None:
    """Replace the loss recorded at global step ``step`` with ``value``,
    ONCE (a latch: after an ``--on_nan restore`` rewinds the step counter
    past ``step``, the fault does not re-fire — a real transient).  Hooks
    the deferred-flush boundary, so the guard sees the poison exactly where
    it would see a real divergence."""
    orig = trainer._flush_losses
    fired = [False]

    def wrapped(epoch, start_step, stacked):
        if not fired[0] and stacked is not None:
            n = int(stacked.shape[0])
            if start_step <= step < start_step + n:
                arr = np.array(jax.device_get(stacked), dtype=np.float64)
                arr[step - start_step] = value
                stacked = arr
                fired[0] = True
        return orig(epoch, start_step, stacked)

    trainer._flush_losses = wrapped


def _after_epoch(trainer, fn) -> None:
    orig = trainer._run_epoch

    def wrapped(epoch, *a, **kw):
        orig(epoch, *a, **kw)
        fn(epoch)

    trainer._run_epoch = wrapped


def _before_step(trainer, fn) -> None:
    """Wrap ``trainer.train_step`` so ``fn(global_step)`` runs before each
    dispatch — the step-granular injection point (the counter is the
    host-side global step, resume-aware via ``trainer._host_step``)."""
    orig = trainer.train_step
    count = [None]

    def wrapped(state, batch, rng):
        if count[0] is None:
            count[0] = int(trainer._host_step)
        fn(count[0])
        out = orig(state, batch, rng)
        count[0] += 1
        return out

    trainer.train_step = wrapped


def sigterm_at_step(trainer, step: int) -> None:
    """Deliver SIGTERM to this process right before global step ``step``
    dispatches — a preemption notice landing mid-epoch; the step-boundary
    guard must take a mid-epoch emergency checkpoint whose ``data_state``
    resumes bit-for-bit."""
    fired = [False]

    def fire(s):
        if not fired[0] and s >= step:
            fired[0] = True
            print(f"[fault] delivering SIGTERM before step {s}",
                  file=sys.stderr)
            sys.stderr.flush()
            os.kill(os.getpid(), signal.SIGTERM)

    _before_step(trainer, fire)


def flip_param_bit(trainer, step: int, replica: int = 1,
                   bit: int = 28) -> None:
    """Flip one bit of the first parameter leaf on ONE replica's copy,
    right before global step ``step`` dispatches — the silent-data-
    corruption model (an HBM upset on a single chip).  The corruption is
    real divergence: replicas apply identical updates to now-different
    values, so it persists until the drift audit's bit-level fingerprint
    compare names the leaf.

    The default bit 28 is a float32 EXPONENT bit: even on a 0.0 leaf the
    flip yields a small *normal* number (2^-95), so the divergence
    survives arithmetic.  A low mantissa bit on a zero leaf would make a
    denormal that the first update's flush-to-zero multiply erases —
    self-healing corruption the drill must not model."""
    orig = trainer.train_step
    count = [None]
    fired = [False]

    # Corrupts the state ARGUMENT of the wrapped dispatch, not
    # trainer.state: the loop binds the argument before the wrapper runs,
    # so a trainer.state assignment here would be overwritten by this very
    # dispatch's output and the corruption would never enter the run.
    def wrapped(state, batch, rng):
        if count[0] is None:
            count[0] = int(trainer._host_step)
        if not fired[0] and count[0] >= step:
            fired[0] = True
            leaves, treedef = jax.tree_util.tree_flatten(state.params)
            from .drift import leaf_paths
            path = leaf_paths(state.params)[0]
            x = leaves[0]
            clean = np.asarray(jax.device_get(x))
            corrupt = clean.copy()
            if corrupt.dtype.itemsize == 4:
                corrupt.view(np.uint32).reshape(-1)[0] ^= \
                    np.uint32(1 << (bit % 32))
            else:
                corrupt.view(np.uint8).reshape(-1)[0] ^= \
                    np.uint8(1 << (bit % 8))
            devs = list(trainer.mesh.devices.flat)
            r = replica % len(devs)
            bufs = [jax.device_put(corrupt if i == r else clean, d)
                    for i, d in enumerate(devs)]
            leaves[0] = jax.make_array_from_single_device_arrays(
                x.shape, x.sharding, bufs)
            state = state._replace(
                params=jax.tree_util.tree_unflatten(treedef, leaves))
            print(f"[fault] flipped bit {bit} of param leaf {path!r} on "
                  f"replica {r} before step {count[0]}", file=sys.stderr)
            sys.stderr.flush()
        out = orig(state, batch, rng)
        count[0] += 1
        return out

    trainer.train_step = wrapped


def poison_batch(trainer, step: int, scale: float = 1e4) -> None:
    """Scale the batch dispatched at global step ``step`` by ``scale``,
    once — a corrupted input shard.  The float-scaled images bypass the
    uint8/255 normalisation, so the step's loss spikes by orders of
    magnitude: the rolling median/MAD guard's target."""
    orig = trainer.train_step
    count = [None]
    fired = [False]

    def wrapped(state, batch, rng):
        if count[0] is None:
            count[0] = int(trainer._host_step)
        if not fired[0] and count[0] >= step:
            fired[0] = True
            batch = dict(batch)
            batch["image"] = (batch["image"].astype(np.float32)
                              * np.float32(scale))
            print(f"[fault] poisoned batch at step {count[0]} "
                  f"(x{scale:g})", file=sys.stderr)
            sys.stderr.flush()
        out = orig(state, batch, rng)
        count[0] += 1
        return out

    trainer.train_step = wrapped


def torn_data_state(path: str) -> None:
    """Replace a gathered checkpoint's ``data_state`` record with torn
    bytes (the file is rewritten, so the lineage manifest's sha no longer
    matches — the warn-but-attempt restore path).  The loader must treat
    the unparseable record as absent: epoch-boundary resume with a
    warning, never an error."""
    from ..train.checkpoint import write_npz_hashed
    with np.load(path) as z:
        flat = {k: z[k] for k in z.files}
    flat["meta/data_state_json"] = np.frombuffer(b'{"torn', np.uint8)
    write_npz_hashed(path, flat)
    print(f"[fault] tore the data_state record of {path!r}",
          file=sys.stderr)
    sys.stderr.flush()


def fail_ckpt_write(trainer, epoch: int) -> None:
    """The async checkpoint write of epoch ``epoch`` dies on the WRITER
    THREAD, once — the full-disk / lost-NFS-mount model.  The injection
    point is ``lineage.preserve_head()``, the write closure's FIRST call:
    the head file is never opened, so the previous snapshot (and the
    whole lineage) stays byte-identical — the "un-torn" half of the
    drill.  The error lands in ``trainer._save_error`` and must surface
    at the next ``_join_pending_save`` boundary (a silently-lost
    checkpoint must not look saved).

    Rank-0 only (the preserve/commit bookkeeping is rank-0-gated in the
    write closure) — which is every CPU drill in the suite.  The target
    epoch rides a FIFO handed from the main-thread save call to the
    writer thread: joins serialize the writers, so the order matches."""
    import collections
    if trainer.lineage is None:
        raise ValueError("fail_ckpt_write needs a trainer with a "
                         "snapshot path (no lineage, no writer thread)")
    orig_inner = trainer._save_checkpoint_inner
    orig_preserve = trainer.lineage.preserve_head
    pending = collections.deque()
    fired = [False]

    def inner(ep, data_state=None):
        pending.append(int(ep))
        return orig_inner(ep, data_state)

    def preserve():
        ep = pending.popleft() if pending else None
        if not fired[0] and ep == int(epoch):
            fired[0] = True
            print(f"[fault] failing the checkpoint write of epoch {ep} "
                  "on the writer thread", file=sys.stderr)
            sys.stderr.flush()
            raise OSError(28, "injected checkpoint write failure "
                              f"at epoch {ep}")
        return orig_preserve()

    trainer._save_checkpoint_inner = inner
    trainer.lineage.preserve_head = preserve


def sigterm_at_epoch(trainer, epoch: int) -> None:
    """Deliver SIGTERM to this process right after epoch ``epoch`` runs —
    before the trainer's save gate and preemption check, like a real
    preemption notice landing mid-run."""

    def fire(e):
        if e == epoch:
            print(f"[fault] delivering SIGTERM after epoch {e}",
                  file=sys.stderr)
            os.kill(os.getpid(), signal.SIGTERM)

    _after_epoch(trainer, fire)


def stall_at_epoch(trainer, epoch: int, seconds: float,
                   rank: Optional[int] = None) -> None:
    """Sleep ``seconds`` after epoch ``epoch`` on ``rank`` (all ranks when
    None) — a wedged host; its peers block in their next collective."""

    def fire(e):
        if e == epoch and (rank is None or jax.process_index() == rank):
            print(f"[fault] rank {jax.process_index()} stalling "
                  f"{seconds:.0f}s after epoch {e}", file=sys.stderr)
            sys.stderr.flush()
            time.sleep(seconds)

    _after_epoch(trainer, fire)


def crash_replica_at_request_n(replica, n: int) -> None:
    """Replica ``replica`` dies permanently at its ``n``-th submit: the
    latched ``crashed`` flag makes every later submit AND health probe
    fail, so the router both retries the victim request elsewhere and
    (after ``eject_after`` probes) ejects the replica from rotation —
    the closest in-process model of a killed serve process."""
    orig = replica.submit
    lock = threading.Lock()
    count = [0]

    def wrapped(images, timeout=None, req=None):
        with lock:
            count[0] += 1
            c = count[0]
        if c >= n:
            if not replica.crashed:
                print(f"[fault] replica {replica.replica_id} crashing at "
                      f"request {c}", file=sys.stderr)
                sys.stderr.flush()
            replica.crashed = True
        return orig(images, timeout=timeout, req=req)

    replica.submit = wrapped


def slow_forward_ms(replica, ms: float) -> None:
    """Every submit on ``replica`` takes ``ms`` extra — a straggling
    replica the least-loaded routing should steer around and the
    per-request deadline budget must bound."""
    orig = replica.submit
    delay_s = float(ms) / 1e3

    def wrapped(images, timeout=None, req=None):
        time.sleep(delay_s)
        return orig(images, timeout=timeout, req=req)

    replica.submit = wrapped


def torn_publish(fleet) -> None:
    """Truncate the resolved head file right before the fleet's NEXT
    snapshot load (once) — the watcher's full lineage walk must then
    skip the publish with a named ``swap_skipped`` event and keep
    serving the current snapshot."""
    orig = fleet._load_snapshot
    fired = [False]

    def wrapped():
        if not fired[0]:
            fired[0] = True
            from .lineage import _resolve_head
            head = _resolve_head(fleet.snapshot_path)
            if os.path.exists(head):
                print(f"[fault] tearing published head {head!r} before "
                      "the watcher loads it", file=sys.stderr)
                sys.stderr.flush()
                tear_file(head)
        return orig()

    fleet._load_snapshot = wrapped


def _known_kwargs(kind: str, part: str, kv: dict, allowed) -> None:
    """Strict kwarg validation for the mirror-era fault forms: a typo'd
    key must fail the drill loudly at install time, not silently arm
    nothing (matching the unknown-kind contract below)."""
    unknown = sorted(set(kv) - set(allowed))
    if unknown:
        raise ValueError(
            f"unknown kwarg(s) {unknown} for {FAULT_ENV} fault {kind!r} "
            f"in {part!r} (allowed: {sorted(allowed)})")


def _mirror_store_of(trainer):
    """The trainer's DirStore mirror backend, for fault injection — the
    flaky-remote faults are meaningless (and a drill wiring error)
    without ``--mirror``."""
    store = getattr(trainer, "_mirror_store", None)
    if store is None or not hasattr(store, "inject_fail_puts"):
        raise ValueError(
            f"{FAULT_ENV} mirror fault needs a trainer running with "
            "--mirror over a DirStore backend (no store to inject into)")
    return store


def fail_put(trainer, n) -> None:
    """The next ``n`` mirror uploads fail at the store — a flaky remote;
    the uploader's bounded backoff retries must absorb it (or degrade to
    visible mirror lag), never the training loop."""
    n = int(n)
    if n < 1:
        raise ValueError(f"fail_put: n must be >= 1, got {n}")
    _mirror_store_of(trainer).inject_fail_puts(n)
    print(f"[fault] next {n} mirror put(s) will fail", file=sys.stderr)
    sys.stderr.flush()


def slow_put(trainer, ms) -> None:
    """Every mirror upload stalls ``ms`` milliseconds at the store — the
    hung-remote model; the per-op deadline times it out and training
    must keep stepping while ``mirror_lag_epochs`` grows."""
    ms = float(ms)
    if ms < 0:
        raise ValueError(f"slow_put: ms must be >= 0, got {ms:g}")
    _mirror_store_of(trainer).inject_slow_put(ms / 1e3)
    print(f"[fault] mirror puts slowed by {ms:g} ms", file=sys.stderr)
    sys.stderr.flush()


def torn_remote_object(trainer) -> None:
    """The next mirror upload lands TRUNCATED while the store records the
    full-length sha — the lie a torn network upload tells.  The mirror
    restore walk must detect the mismatch at get time and fall back to
    the next mirrored object."""
    _mirror_store_of(trainer).inject_torn_next_put()
    print("[fault] next mirror put will land torn", file=sys.stderr)
    sys.stderr.flush()


def wipe_local_ckpt(trainer, epoch) -> None:
    """Delete EVERY local checkpoint lineage file (head, manifest,
    rotated snapshots, shard files) after epoch ``epoch``'s checkpoint
    has committed and mirrored — total local-disk loss with the mirror
    as the only surviving copy.  Fires at the start of the next epoch
    (so the wiped epoch's save + mirror upload have landed); later saves
    recreate the head, and a relaunch restores from the mirror."""
    epoch = int(epoch)
    if epoch < 0:
        raise ValueError(f"wipe_local_ckpt: epoch must be >= 0, "
                         f"got {epoch}")
    path = getattr(trainer, "snapshot_path", None)
    if not path:
        raise ValueError("wipe_local_ckpt needs a trainer with a "
                         "snapshot path (nothing local to wipe)")
    orig = trainer._run_epoch
    fired = [False]

    def wrapped(ep, *a, **kw):
        if not fired[0] and ep > epoch:
            fired[0] = True
            trainer._join_pending_save()
            drain = getattr(trainer, "_mirror_drain", None)
            if drain is not None:
                drain(60.0)
            d = os.path.dirname(os.path.abspath(path))
            base = os.path.basename(path)
            victims = [f for f in os.listdir(d)
                       if f == base or f.startswith(base + ".")]
            for v in victims:
                try:
                    os.unlink(os.path.join(d, v))
                except OSError:
                    pass
            print(f"[fault] wiped {len(victims)} local checkpoint "
                  f"file(s) under {d!r} after epoch {epoch} — the "
                  "mirror is the only copy now", file=sys.stderr)
            sys.stderr.flush()
        return orig(ep, *a, **kw)

    trainer._run_epoch = wrapped


def install_serve_faults(fleet) -> None:
    """Apply :data:`FAULT_ENV` serve-fault specs to ``fleet`` (the serve
    process's counterpart of :func:`install_env_faults`; no-op when the
    variable is unset).  Specs use the serve vocabulary only — a serve
    process given a trainer spec is a drill wiring error and fails
    loudly."""
    spec = os.environ.get(FAULT_ENV)
    if not spec:
        return
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, argstr = part.partition("@")
        kv = dict(a.split("=", 1) for a in argstr.split(",") if a)
        if kind == "crash_replica":
            idx = int(kv.get("replica", "0"))
            crash_replica_at_request_n(fleet.replicas[idx],
                                       int(kv["requests"]))
        elif kind == "slow_forward":
            idx = int(kv.get("replica", "0"))
            slow_forward_ms(fleet.replicas[idx], float(kv["ms"]))
        elif kind == "torn_publish":
            torn_publish(fleet)
        else:
            raise ValueError(f"unknown {FAULT_ENV} serve fault kind "
                             f"{kind!r} in {part!r}")


def install_env_faults(trainer) -> None:
    """Apply :data:`FAULT_ENV` fault specs to ``trainer`` (no-op when the
    variable is unset — the only line of this module production code
    reaches)."""
    spec = os.environ.get(FAULT_ENV)
    if not spec:
        return
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, argstr = part.partition("@")
        kv = dict(a.split("=", 1) for a in argstr.split(",") if a)
        if kind == "sigterm":
            # epoch= (the original boundary drill) or step= (mid-epoch).
            if "step" in kv:
                sigterm_at_step(trainer, int(kv["step"]))
            else:
                sigterm_at_epoch(trainer, int(kv["epoch"]))
        elif kind == "flip_param_bit":
            flip_param_bit(trainer, int(kv["step"]),
                           replica=int(kv.get("replica", "1")),
                           bit=int(kv.get("bit", "28")))
        elif kind == "poison_batch":
            poison_batch(trainer, int(kv["step"]),
                         scale=float(kv.get("scale", "1e4")))
        elif kind == "poison":
            poison_loss(trainer, int(kv["step"]),
                        float(kv.get("value", "nan")))
        elif kind == "stall":
            stall_at_epoch(trainer, int(kv["epoch"]),
                           float(kv.get("secs", "3600")),
                           rank=int(kv["rank"]) if "rank" in kv else None)
        elif kind == "fail_ckpt_write":
            fail_ckpt_write(trainer, int(kv["epoch"]))
        elif kind == "fail_put":
            _known_kwargs(kind, part, kv, ("n",))
            fail_put(trainer, kv.get("n", "1"))
        elif kind == "slow_put":
            _known_kwargs(kind, part, kv, ("ms",))
            slow_put(trainer, kv["ms"])
        elif kind == "torn_remote_object":
            _known_kwargs(kind, part, kv, ())
            torn_remote_object(trainer)
        elif kind == "wipe_local_ckpt":
            _known_kwargs(kind, part, kv, ("epoch",))
            wipe_local_ckpt(trainer, kv["epoch"])
        else:
            raise ValueError(f"unknown {FAULT_ENV} fault kind {kind!r} "
                             f"in {part!r}")
