"""Test-only fault injection — the failure modes the resilience layer
exists for, made reproducible on a laptop CPU mesh.

Nothing here runs in production paths: the injectors monkeypatch a single
``Trainer`` instance (no global state), and the one production touchpoint —
``cli.install_env_faults`` — is a no-op unless the :data:`FAULT_ENV`
variable is set, which only the drills in ``tests/test_resilience.py`` do.

Faults:
  ``tear_file``         truncate a checkpoint (external-damage model; the
                        atomic saver itself never produces a torn file)
  ``poison_loss``       replace the recorded loss at global step k with
                        NaN, once — drives the ``--on_nan`` policies
  ``sigterm_at_epoch``  deliver SIGTERM to this process at the end of
                        epoch k — the preemption drill
  ``stall_at_epoch``    put one rank to sleep at the end of epoch k — the
                        hung-peer scenario the watchdog bounds

Serve-side faults (the fleet chaos drills — tests/test_fleet.py and the
CI fleet smoke):
  ``crash_replica_at_request_n``  one replica dies permanently at its
                        n-th request: submits fail fast AND health
                        probes fail, so the router retries the request
                        elsewhere and then ejects the replica
  ``slow_forward_ms``   every request on one replica takes ms longer —
                        the straggler/overload scenario the deadline
                        budget and least-loaded routing bound
  ``torn_publish``      truncate the newest published head right before
                        the fleet's hot-swap watcher loads it, once —
                        drives the named ``swap_skipped`` path

Env surface for subprocess drills (``DDP_TPU_FAULT``): semicolon-separated
specs ``kind@key=val,key=val`` — e.g.
``sigterm@epoch=1``, ``poison@step=5``,
``stall@epoch=0,rank=1,secs=600``.  Serve processes
(``python -m ddp_tpu.serve --fleet N``) parse the same variable through
:func:`install_serve_faults` with the serve vocabulary:
``crash_replica@requests=25,replica=0``, ``slow_forward@ms=200,replica=1``,
``torn_publish@``.
"""
from __future__ import annotations

import os
import signal
import sys
import threading
import time
from typing import Optional

import jax
import numpy as np

FAULT_ENV = "DDP_TPU_FAULT"


def tear_file(path: str, keep_fraction: float = 0.5) -> None:
    """Truncate ``path`` to ``keep_fraction`` of its bytes (at least one
    byte shorter) — the torn-by-external-damage checkpoint."""
    size = os.path.getsize(path)
    keep = min(int(size * keep_fraction), size - 1)
    with open(path, "r+b") as f:
        f.truncate(max(keep, 0))


def poison_loss(trainer, step: int, value: float = float("nan")) -> None:
    """Replace the loss recorded at global step ``step`` with ``value``,
    ONCE (a latch: after an ``--on_nan restore`` rewinds the step counter
    past ``step``, the fault does not re-fire — a real transient).  Hooks
    the deferred-flush boundary, so the guard sees the poison exactly where
    it would see a real divergence."""
    orig = trainer._flush_losses
    fired = [False]

    def wrapped(epoch, start_step, stacked):
        if not fired[0] and stacked is not None:
            n = int(stacked.shape[0])
            if start_step <= step < start_step + n:
                arr = np.array(jax.device_get(stacked), dtype=np.float64)
                arr[step - start_step] = value
                stacked = arr
                fired[0] = True
        return orig(epoch, start_step, stacked)

    trainer._flush_losses = wrapped


def _after_epoch(trainer, fn) -> None:
    orig = trainer._run_epoch

    def wrapped(epoch):
        orig(epoch)
        fn(epoch)

    trainer._run_epoch = wrapped


def sigterm_at_epoch(trainer, epoch: int) -> None:
    """Deliver SIGTERM to this process right after epoch ``epoch`` runs —
    before the trainer's save gate and preemption check, like a real
    preemption notice landing mid-run."""

    def fire(e):
        if e == epoch:
            print(f"[fault] delivering SIGTERM after epoch {e}",
                  file=sys.stderr)
            os.kill(os.getpid(), signal.SIGTERM)

    _after_epoch(trainer, fire)


def stall_at_epoch(trainer, epoch: int, seconds: float,
                   rank: Optional[int] = None) -> None:
    """Sleep ``seconds`` after epoch ``epoch`` on ``rank`` (all ranks when
    None) — a wedged host; its peers block in their next collective."""

    def fire(e):
        if e == epoch and (rank is None or jax.process_index() == rank):
            print(f"[fault] rank {jax.process_index()} stalling "
                  f"{seconds:.0f}s after epoch {e}", file=sys.stderr)
            sys.stderr.flush()
            time.sleep(seconds)

    _after_epoch(trainer, fire)


def crash_replica_at_request_n(replica, n: int) -> None:
    """Replica ``replica`` dies permanently at its ``n``-th submit: the
    latched ``crashed`` flag makes every later submit AND health probe
    fail, so the router both retries the victim request elsewhere and
    (after ``eject_after`` probes) ejects the replica from rotation —
    the closest in-process model of a killed serve process."""
    orig = replica.submit
    lock = threading.Lock()
    count = [0]

    def wrapped(images, timeout=None):
        with lock:
            count[0] += 1
            c = count[0]
        if c >= n:
            if not replica.crashed:
                print(f"[fault] replica {replica.replica_id} crashing at "
                      f"request {c}", file=sys.stderr)
                sys.stderr.flush()
            replica.crashed = True
        return orig(images, timeout=timeout)

    replica.submit = wrapped


def slow_forward_ms(replica, ms: float) -> None:
    """Every submit on ``replica`` takes ``ms`` extra — a straggling
    replica the least-loaded routing should steer around and the
    per-request deadline budget must bound."""
    orig = replica.submit
    delay_s = float(ms) / 1e3

    def wrapped(images, timeout=None):
        time.sleep(delay_s)
        return orig(images, timeout=timeout)

    replica.submit = wrapped


def torn_publish(fleet) -> None:
    """Truncate the resolved head file right before the fleet's NEXT
    snapshot load (once) — the watcher's full lineage walk must then
    skip the publish with a named ``swap_skipped`` event and keep
    serving the current snapshot."""
    orig = fleet._load_snapshot
    fired = [False]

    def wrapped():
        if not fired[0]:
            fired[0] = True
            from .lineage import _resolve_head
            head = _resolve_head(fleet.snapshot_path)
            if os.path.exists(head):
                print(f"[fault] tearing published head {head!r} before "
                      "the watcher loads it", file=sys.stderr)
                sys.stderr.flush()
                tear_file(head)
        return orig()

    fleet._load_snapshot = wrapped


def install_serve_faults(fleet) -> None:
    """Apply :data:`FAULT_ENV` serve-fault specs to ``fleet`` (the serve
    process's counterpart of :func:`install_env_faults`; no-op when the
    variable is unset).  Specs use the serve vocabulary only — a serve
    process given a trainer spec is a drill wiring error and fails
    loudly."""
    spec = os.environ.get(FAULT_ENV)
    if not spec:
        return
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, argstr = part.partition("@")
        kv = dict(a.split("=", 1) for a in argstr.split(",") if a)
        if kind == "crash_replica":
            idx = int(kv.get("replica", "0"))
            crash_replica_at_request_n(fleet.replicas[idx],
                                       int(kv["requests"]))
        elif kind == "slow_forward":
            idx = int(kv.get("replica", "0"))
            slow_forward_ms(fleet.replicas[idx], float(kv["ms"]))
        elif kind == "torn_publish":
            torn_publish(fleet)
        else:
            raise ValueError(f"unknown {FAULT_ENV} serve fault kind "
                             f"{kind!r} in {part!r}")


def install_env_faults(trainer) -> None:
    """Apply :data:`FAULT_ENV` fault specs to ``trainer`` (no-op when the
    variable is unset — the only line of this module production code
    reaches)."""
    spec = os.environ.get(FAULT_ENV)
    if not spec:
        return
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        kind, _, argstr = part.partition("@")
        kv = dict(a.split("=", 1) for a in argstr.split(",") if a)
        if kind == "sigterm":
            sigterm_at_epoch(trainer, int(kv["epoch"]))
        elif kind == "poison":
            poison_loss(trainer, int(kv["step"]),
                        float(kv.get("value", "nan")))
        elif kind == "stall":
            stall_at_epoch(trainer, int(kv["epoch"]),
                           float(kv.get("secs", "3600")),
                           rank=int(kv["rank"]) if "rank" in kv else None)
        else:
            raise ValueError(f"unknown {FAULT_ENV} fault kind {kind!r} "
                             f"in {part!r}")
