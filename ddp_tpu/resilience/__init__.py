"""Resilience subsystem — surviving the failures real TPU pods throw.

The reference has no failure story at all: a SIGTERM mid-epoch loses
everything since the last ``save_every`` boundary (multigpu.py:117-119), a
torn ``checkpoint.pt`` kills ``--resume`` outright, a diverged loss trains
NaNs to completion in silence, and a stuck peer rides the full 300 s
graceful-shutdown timeout.  This package turns "resume exists" into "runs
survive":

- ``lineage``    retained rotating snapshots with a per-file SHA-256
                 manifest, and resume fall-back to the newest *verifiable*
                 checkpoint when the head is torn.
- ``preemption`` SIGTERM/SIGINT -> one coordinated emergency checkpoint at
                 the next epoch boundary on all hosts, then a clean exit
                 with :data:`EMERGENCY_CHECKPOINT_EXIT_STATUS`.
- ``guard``      per-step loss health policy (``--on_nan
                 {abort,skip,restore}``) folded into the trainer's existing
                 deferred-loss flush — zero extra device->host transfers.
- ``watchdog``   heartbeat thread bounding epoch/step wall time; on expiry
                 it calls the non-blocking ``dist.abort()`` and hard-exits
                 with :data:`WATCHDOG_EXIT_STATUS` instead of hanging peers.
- ``faults``     test-only fault injection (tear a checkpoint, poison the
                 loss at step k, SIGTERM at epoch k, stall a host) driving
                 ``tests/test_resilience.py`` and the CLI drills.

Exit-status contract (a restart wrapper keys off these):
  0    normal completion
  75   (EX_TEMPFAIL) preempted; emergency checkpoint on disk — relaunch
       with ``--resume``
  124  watchdog expired: no step/epoch progress within ``--watchdog_secs``
  else a real failure; inspect before relaunching
"""
from .guard import NonFiniteLossError, StepHealthGuard
from .lineage import (CheckpointLineage, latest_verifiable,
                      load_latest_verifiable)
from .preemption import (EMERGENCY_CHECKPOINT_EXIT_STATUS, PreemptionGuard,
                         PreemptionInterrupt)
from .watchdog import WATCHDOG_EXIT_STATUS, Watchdog

__all__ = [
    "CheckpointLineage", "EMERGENCY_CHECKPOINT_EXIT_STATUS",
    "NonFiniteLossError", "PreemptionGuard", "PreemptionInterrupt",
    "StepHealthGuard", "WATCHDOG_EXIT_STATUS", "Watchdog",
    "latest_verifiable", "load_latest_verifiable",
]
