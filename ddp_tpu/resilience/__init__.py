"""Resilience subsystem — surviving the failures real TPU pods throw.

The reference has no failure story at all: a SIGTERM mid-epoch loses
everything since the last ``save_every`` boundary (multigpu.py:117-119), a
torn ``checkpoint.pt`` kills ``--resume`` outright, a diverged loss trains
NaNs to completion in silence, and a stuck peer rides the full 300 s
graceful-shutdown timeout.  This package turns "resume exists" into "runs
survive":

- ``lineage``    retained rotating snapshots with a per-file SHA-256
                 manifest, and resume fall-back to the newest *verifiable*
                 checkpoint when the head is torn.
- ``preemption`` SIGTERM/SIGINT -> one coordinated emergency checkpoint at
                 the next epoch boundary on all hosts, then a clean exit
                 with :data:`EMERGENCY_CHECKPOINT_EXIT_STATUS`.
- ``guard``      per-step loss health policy (``--on_nan
                 {abort,skip,restore}``) folded into the trainer's existing
                 deferred-loss flush — zero extra device->host transfers.
- ``watchdog``   heartbeat thread bounding epoch/step wall time; on expiry
                 it calls the non-blocking ``dist.abort()`` and hard-exits
                 with :data:`WATCHDOG_EXIT_STATUS` instead of hanging peers.
- ``faults``     test-only fault injection (tear a checkpoint, poison the
                 loss at step k, SIGTERM at epoch k, stall a host) driving
                 ``tests/test_resilience.py`` and the CLI drills.
- ``supervisor`` THE restart wrapper the exit codes below cue: launches
                 the run as a child, relaunches with ``--resume`` on
                 preemption/stall/transient crash under a backoff budget,
                 shrinks the mesh to the surviving devices, and stops with
                 a named diagnosis when a failure recurs deterministically.

Exit-status contract (``python -m ddp_tpu.supervise`` keys off these):
  0    normal completion
  75   (EX_TEMPFAIL) preempted; emergency checkpoint on disk — relaunch
       with ``--resume``
  124  watchdog expired: no step/epoch progress within ``--watchdog_secs``
  else a real failure; inspect before relaunching

The supervisor's OWN exits continue the table:
  86   restart budget exhausted (failure ledger printed; newest verifiable
       checkpoint still on disk for a manual relaunch)
  87   deterministic failure diagnosed — the same drift/guard signature at
       the same step twice; relaunching would re-prove it, not fix it
"""
from .guard import NonFiniteLossError, StepHealthGuard
from .lineage import (CheckpointLineage, latest_verifiable,
                      load_latest_verifiable)
from .preemption import (EMERGENCY_CHECKPOINT_EXIT_STATUS, PreemptionGuard,
                         PreemptionInterrupt)
from .supervisor import (SUPERVISOR_BUDGET_EXIT_STATUS,
                         SUPERVISOR_DETERMINISTIC_EXIT_STATUS, Supervisor)
from .watchdog import WATCHDOG_EXIT_STATUS, Watchdog

__all__ = [
    "CheckpointLineage", "EMERGENCY_CHECKPOINT_EXIT_STATUS",
    "NonFiniteLossError", "PreemptionGuard", "PreemptionInterrupt",
    "SUPERVISOR_BUDGET_EXIT_STATUS",
    "SUPERVISOR_DETERMINISTIC_EXIT_STATUS", "StepHealthGuard",
    "Supervisor", "WATCHDOG_EXIT_STATUS", "Watchdog",
    "latest_verifiable", "load_latest_verifiable",
]
