"""Durable checkpoint tiering: pluggable object store + async mirror.

Every recovery path the framework has — supervisor elastic relaunch,
mid-epoch ``--resume``, drift-restore, hot-swap publish — bottoms out in
``lineage.latest_verifiable`` over ONE local directory.  On a real TPU
pod that directory does not survive the faults that matter most:
preemption reclaims the VM *and its disk*.  This module adds the second
failure domain:

- :class:`CheckpointStore` — the put/get/list/delete/stat protocol with
  object-level sha-256 verification.  The protocol is the deliverable;
  a GCS/S3 backend is a ~40-line paste of :class:`DirStore` over the
  blob client (RUNBOOK §18 has the sketch).
- :class:`LocalStore` — a plain directory viewed through the store
  interface (integrity computed on read; the local tier already has the
  lineage manifest for end-to-end shas).
- :class:`DirStore` — a second directory standing in for a remote
  object store: atomic object visibility (tmp + rename), a ``.meta.json``
  integrity sidecar per object (the stand-in for blob metadata/etag),
  per-op deadlines, and built-in fault hooks (``fail_put`` /
  ``slow_put`` / ``torn_remote_object`` — driven by resilience/faults.py)
  so the retry/degradation story is tested honestly.
- :class:`MirrorUploader` — the background thread that uploads each
  checkpoint AFTER its lineage commit, off the critical path: bounded
  jittered exponential-backoff retries (same ``backoff_delay`` math as
  the supervisor), per-op timeouts, and graceful degradation — a flaky
  or stalled remote NEVER blocks or fails training, it only grows the
  ``ddp_mirror_lag_epochs`` gauge (surfaced in the ``.prom`` file and
  the watchdog stall context).

Threading: all REMOTE mutations — uploads, the remote manifest write,
remote trim/GC — happen on the uploader's one worker thread (the remote
twin of the lineage module's single-writer discipline).  Trim therefore
structurally cannot race an upload, and is belt-and-braces guarded by
the ``_in_flight`` set anyway; the newest mirrored head is always in the
keep-set, so it is never deleted.  Cross-thread state is guarded by
``_lock`` and annotated for the lockset lint (analysis/lockset.py).
"""
from __future__ import annotations

import hashlib
import json
import os
import queue
import random
import shutil
import sys
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .lineage import MANIFEST_SUFFIX, lineage_name
from .supervisor import backoff_delay

_CHUNK = 1 << 20


def _log(msg: str) -> None:
    print(msg, file=sys.stderr)
    sys.stderr.flush()


class StoreError(Exception):
    """An object-store operation failed (I/O error, integrity mismatch,
    injected fault).  Retryable by policy; never propagates into the
    training loop."""


class StoreTimeout(StoreError):
    """A store operation exceeded its per-op deadline."""


def _check_deadline(deadline: Optional[float], what: str) -> None:
    if deadline is not None and time.monotonic() > deadline:
        raise StoreTimeout(f"{what} exceeded its per-op deadline")


class CheckpointStore:
    """The pluggable durability-tier protocol.

    Objects are flat names (checkpoint lineage file basenames); every
    transfer returns the sha-256 of the bytes moved so callers get
    end-to-end integrity without a second disk pass.  ``get`` MUST verify
    the object against the store's own integrity record when one exists
    and raise :class:`StoreError` on mismatch — a torn remote object is
    a skip-to-next-candidate event, never a silent bad restore.  All
    methods raise :class:`StoreError` (or :class:`StoreTimeout`) on
    failure; ``deadline`` is an absolute ``time.monotonic()`` cutoff.
    """

    def put(self, local_path: str, name: str, *,
            deadline: Optional[float] = None) -> str:
        """Upload ``local_path`` as object ``name``; returns its sha256."""
        raise NotImplementedError

    def put_bytes(self, name: str, data: bytes, *,
                  deadline: Optional[float] = None) -> str:
        """Upload a small blob (the mirror manifest) as ``name``."""
        raise NotImplementedError

    def get(self, name: str, local_path: str, *,
            deadline: Optional[float] = None) -> str:
        """Download + verify object ``name`` to ``local_path`` (atomic:
        the file appears only after verification); returns its sha256."""
        raise NotImplementedError

    def get_bytes(self, name: str, *,
                  deadline: Optional[float] = None) -> bytes:
        """Download + verify a small object into memory."""
        raise NotImplementedError

    def list(self) -> List[str]:
        """Names of every object in the store."""
        raise NotImplementedError

    def delete(self, name: str) -> None:
        """Remove one object (idempotent — absent is not an error)."""
        raise NotImplementedError

    def stat(self, name: str) -> Optional[Dict[str, Any]]:
        """``{"size": int, "sha256": str|None}`` or None when absent."""
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


def _copy_hashed(src_path: str, out, deadline: Optional[float],
                 what: str) -> Tuple[str, int]:
    """Stream-copy ``src_path`` into the open binary file ``out``,
    hashing while copying (one disk pass) and checking the deadline
    between chunks; returns ``(sha256, size)``."""
    h = hashlib.sha256()
    total = 0
    with open(src_path, "rb") as src:
        while True:
            _check_deadline(deadline, what)
            chunk = src.read(_CHUNK)
            if not chunk:
                break
            h.update(chunk)
            total += len(chunk)
            out.write(chunk)
    return h.hexdigest(), total


class LocalStore(CheckpointStore):
    """A plain directory as a store — the tier-0 backend.

    No sidecar metadata: the local tier's integrity record is the
    lineage manifest itself, so ``stat``/``get`` compute the sha from
    the bytes (callers compare against the manifest)."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)

    def describe(self) -> str:
        return f"LocalStore({self.root!r})"

    def _obj(self, name: str) -> str:
        if not name or os.sep in name or name.startswith("."):
            raise StoreError(f"invalid object name {name!r}")
        return os.path.join(self.root, name)

    def put(self, local_path, name, *, deadline=None):
        dst = self._obj(name)
        os.makedirs(self.root, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as out:
                sha, _ = _copy_hashed(local_path, out, deadline,
                                      f"put {name!r}")
            os.replace(tmp, dst)
        except StoreError:
            _unlink_quiet(tmp)
            raise
        except OSError as e:
            _unlink_quiet(tmp)
            raise StoreError(f"put {name!r} failed: {e}") from e
        return sha

    def put_bytes(self, name, data, *, deadline=None):
        dst = self._obj(name)
        os.makedirs(self.root, exist_ok=True)
        _check_deadline(deadline, f"put {name!r}")
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as out:
                out.write(data)
            os.replace(tmp, dst)
        except OSError as e:
            _unlink_quiet(tmp)
            raise StoreError(f"put {name!r} failed: {e}") from e
        return hashlib.sha256(data).hexdigest()

    def get(self, name, local_path, *, deadline=None):
        src = self._obj(name)
        if not os.path.exists(src):
            raise StoreError(f"no object {name!r} in {self.describe()}")
        d = os.path.dirname(os.path.abspath(local_path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as out:
                sha, _ = _copy_hashed(src, out, deadline, f"get {name!r}")
            os.replace(tmp, local_path)
        except StoreError:
            _unlink_quiet(tmp)
            raise
        except OSError as e:
            _unlink_quiet(tmp)
            raise StoreError(f"get {name!r} failed: {e}") from e
        return sha

    def get_bytes(self, name, *, deadline=None):
        src = self._obj(name)
        _check_deadline(deadline, f"get {name!r}")
        try:
            with open(src, "rb") as f:
                return f.read()
        except OSError as e:
            raise StoreError(f"get {name!r} failed: {e}") from e

    def list(self):
        try:
            return sorted(n for n in os.listdir(self.root)
                          if not n.endswith(".tmp"))
        except OSError:
            return []

    def delete(self, name):
        _unlink_quiet(self._obj(name))

    def stat(self, name):
        try:
            st = os.stat(self._obj(name))
        except OSError:
            return None
        return {"size": int(st.st_size), "sha256": None}


def _unlink_quiet(path: str) -> None:
    try:
        os.unlink(path)
    except OSError:
        pass


class DirStore(CheckpointStore):
    """A directory standing in for a remote object store.

    Object semantics a blob store would give us, reproduced on a
    filesystem so the whole durability protocol is testable in CI:

    - atomic visibility — an object appears only after its bytes are
      complete (tmp + rename), and its ``<name>.meta.json`` integrity
      sidecar (the stand-in for blob metadata/etag) is written LAST, so
      a reader never sees a verifiable-looking half-object;
    - ``get`` verifies the sha256 recorded at put time and raises
      :class:`StoreError` on mismatch — a torn upload is detected at
      restore time, not trusted;
    - ``delete`` removes the sidecar FIRST, so a concurrent reader sees
      "absent", never "present but unverifiable".

    Fault hooks (installed via ``DDP_TPU_FAULT`` — resilience/faults.py):
    ``inject_fail_puts(n)`` fails the next n puts, ``inject_slow_put(s)``
    stalls every put (the per-op deadline then times it out),
    ``inject_torn_next_put()`` truncates the next object's bytes while
    recording the full-length sha — the lie a torn network upload tells.
    """

    META_SUFFIX = ".meta.json"

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._lock = threading.Lock()
        # analysis: shared-under(_lock)
        self._fail_puts_remaining = 0
        # analysis: shared-under(_lock)
        self._slow_put_s = 0.0
        # analysis: shared-under(_lock)
        self._torn_next_put = False

    def describe(self) -> str:
        return f"DirStore({self.root!r})"

    # -- fault hooks (main thread) ----------------------------------------

    def inject_fail_puts(self, n: int) -> None:
        with self._lock:
            self._fail_puts_remaining = int(n)

    def inject_slow_put(self, seconds: float) -> None:
        with self._lock:
            self._slow_put_s = float(seconds)

    def inject_torn_next_put(self) -> None:
        with self._lock:
            self._torn_next_put = True

    def _take_put_faults(self) -> Tuple[bool, float, bool]:
        with self._lock:
            fail = self._fail_puts_remaining > 0
            if fail:
                self._fail_puts_remaining -= 1
            slow = self._slow_put_s
            torn = self._torn_next_put
            if torn:
                self._torn_next_put = False
        return fail, slow, torn

    # -- object ops --------------------------------------------------------

    def _obj(self, name: str) -> str:
        if not name or os.sep in name or name.startswith("."):
            raise StoreError(f"invalid object name {name!r}")
        return os.path.join(self.root, name)

    def _meta_path(self, name: str) -> str:
        return self._obj(name) + self.META_SUFFIX

    def _apply_put_faults(self, name: str,
                          deadline: Optional[float]) -> bool:
        """Honor injected put faults; returns the torn flag."""
        fail, slow, torn = self._take_put_faults()
        if slow:
            end = time.monotonic() + slow
            while time.monotonic() < end:
                _check_deadline(deadline, f"put {name!r} (slow remote)")
                time.sleep(min(0.05, end - time.monotonic()))
        if fail:
            raise StoreError(f"injected put failure for {name!r}")
        return torn

    def put(self, local_path, name, *, deadline=None):
        torn = self._apply_put_faults(name, deadline)
        dst = self._obj(name)
        os.makedirs(self.root, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as out:
                sha, size = _copy_hashed(local_path, out, deadline,
                                         f"put {name!r}")
                if torn:
                    # The torn-upload lie: half the bytes land, the
                    # integrity record below still claims the full sha.
                    out.truncate(max(0, size // 2))
            os.replace(tmp, dst)
        except StoreError:
            _unlink_quiet(tmp)
            raise
        except OSError as e:
            _unlink_quiet(tmp)
            raise StoreError(f"put {name!r} failed: {e}") from e
        self._write_meta(name, sha, size)
        return sha

    def put_bytes(self, name, data, *, deadline=None):
        torn = self._apply_put_faults(name, deadline)
        dst = self._obj(name)
        os.makedirs(self.root, exist_ok=True)
        body = data[: len(data) // 2] if torn else data
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as out:
                out.write(body)
            os.replace(tmp, dst)
        except OSError as e:
            _unlink_quiet(tmp)
            raise StoreError(f"put {name!r} failed: {e}") from e
        sha = hashlib.sha256(data).hexdigest()
        self._write_meta(name, sha, len(data))
        return sha

    def _write_meta(self, name: str, sha: str, size: int) -> None:
        meta = {"sha256": sha, "size": int(size)}
        fd, tmp = tempfile.mkstemp(dir=self.root, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(meta, f)
            os.replace(tmp, self._meta_path(name))
        except OSError as e:
            _unlink_quiet(tmp)
            raise StoreError(f"meta write for {name!r} failed: {e}") from e

    def get(self, name, local_path, *, deadline=None):
        meta = self.stat(name)
        if meta is None:
            raise StoreError(f"no object {name!r} in {self.describe()}")
        d = os.path.dirname(os.path.abspath(local_path))
        os.makedirs(d, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as out:
                sha, _ = _copy_hashed(self._obj(name), out, deadline,
                                      f"get {name!r}")
            expected = meta.get("sha256")
            if expected and sha != expected:
                _unlink_quiet(tmp)
                raise StoreError(
                    f"object {name!r} failed sha-256 verification (torn "
                    f"upload or remote rot): bytes hash {sha[:12]}…, "
                    f"store records {expected[:12]}…")
            os.replace(tmp, local_path)
        except StoreError:
            _unlink_quiet(tmp)
            raise
        except OSError as e:
            _unlink_quiet(tmp)
            raise StoreError(f"get {name!r} failed: {e}") from e
        return sha

    def get_bytes(self, name, *, deadline=None):
        meta = self.stat(name)
        if meta is None:
            raise StoreError(f"no object {name!r} in {self.describe()}")
        _check_deadline(deadline, f"get {name!r}")
        try:
            with open(self._obj(name), "rb") as f:
                data = f.read()
        except OSError as e:
            raise StoreError(f"get {name!r} failed: {e}") from e
        expected = meta.get("sha256")
        if expected and hashlib.sha256(data).hexdigest() != expected:
            raise StoreError(
                f"object {name!r} failed sha-256 verification (torn "
                "upload or remote rot)")
        return data

    def list(self):
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(n for n in names
                      if not n.endswith(self.META_SUFFIX)
                      and not n.endswith(".tmp"))

    def delete(self, name):
        # Sidecar first: a concurrent reader sees "absent" (stat None),
        # never "present but unverifiable".
        _unlink_quiet(self._meta_path(name))
        _unlink_quiet(self._obj(name))

    def stat(self, name):
        try:
            with open(self._meta_path(name)) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return None
        if not os.path.exists(self._obj(name)):
            return None
        return {"size": int(meta.get("size", 0)),
                "sha256": meta.get("sha256")}


def open_store(uri) -> CheckpointStore:
    """Resolve a ``--mirror`` URI to a backend.

    A plain path or ``dir://PATH`` is the :class:`DirStore` remote
    stand-in; ``local://PATH`` is the thin :class:`LocalStore`.  Cloud
    schemes name the paste point deliberately: the protocol above is the
    deliverable, a real blob backend is ~40 lines over its client SDK
    (RUNBOOK §18)."""
    if isinstance(uri, CheckpointStore):
        return uri
    uri = str(uri)
    for scheme in ("gs://", "s3://", "az://"):
        if uri.startswith(scheme):
            raise StoreError(
                f"no {scheme.rstrip('/:')} backend is bundled — subclass "
                "CheckpointStore over the blob client (put/get/list/"
                "delete/stat + sha-256 metadata; see DirStore and "
                "RUNBOOK §18 for the shape) and pass it to the Trainer")
    if uri.startswith("dir://"):
        return DirStore(uri[len("dir://"):])
    if uri.startswith("local://"):
        return LocalStore(uri[len("local://"):])
    return DirStore(uri)


class RetryPolicy:
    """Bounded jittered exponential backoff for store ops — the same
    decorrelation math as the supervisor's relaunch backoff
    (``supervisor.backoff_delay``): attempt ``k`` waits
    ``min(base * 2**k, cap) * (1 ± jitter)``; after ``retries`` failed
    retries the op is abandoned (the caller degrades, never crashes)."""

    def __init__(self, *, retries: int = 4, base: float = 0.25,
                 cap: float = 4.0, jitter: float = 0.25):
        if retries < 0:
            raise ValueError(f"retries must be >= 0, got {retries}")
        if base < 0 or cap < 0 or not 0 <= jitter <= 1:
            raise ValueError(
                f"invalid backoff (base={base}, cap={cap}, jitter={jitter})")
        self.retries = int(retries)
        self.base = float(base)
        self.cap = float(cap)
        self.jitter = float(jitter)

    def delay(self, attempt: int, rng: random.Random) -> float:
        return backoff_delay(attempt, base=self.base, cap=self.cap,
                             jitter=self.jitter, rng=rng)


MIRROR_MANIFEST_FORMAT = 1


class MirrorUploader:
    """Asynchronous checkpoint mirroring, strictly off the critical path.

    The trainer's writer thread calls :meth:`enqueue` right after each
    ``lineage.commit`` (so only durable, sha-recorded states are ever
    mirrored); this class's ONE worker thread does everything remote:
    upload the head snapshot (+ v2 shard files and sidecars), publish the
    remote mirror manifest, then trim remote objects that fell out of
    retention.  ``enqueue`` never blocks and upload failure never
    propagates — the remote tier degrades to visible
    ``ddp_mirror_lag_epochs``, never a blocked or failed step.

    The head is snapshotted by hard link at enqueue time (the live head
    path is overwritten by the NEXT save while an upload may still be in
    queue); each upload's returned sha is compared against the lineage
    commit's sha so a changed-under-us file is detected and treated as
    superseded, not mirrored wrong.
    """

    def __init__(self, store, path: str, *, keep: int = 1, registry=None,
                 tracer=None, policy: Optional[RetryPolicy] = None,
                 op_timeout: float = 30.0,
                 rng: Optional[random.Random] = None):
        self.store = open_store(store)
        self.path = os.path.abspath(path)
        self.base = os.path.basename(path)
        self.keep = max(1, int(keep))
        self.policy = policy if policy is not None else RetryPolicy()
        self.op_timeout = float(op_timeout)
        self._rng = rng if rng is not None else random.Random(0x5EED)
        if tracer is None:
            from ..obs.tracer import get_tracer
            tracer = get_tracer()
        self.tracer = tracer
        self._m_seconds = self._m_retries = self._m_failures = None
        if registry is not None:
            from ..obs.registry import SECONDS_BUCKETS
            self._m_seconds = registry.histogram(
                "ddp_ckpt_upload_seconds",
                "Wall time of one mirrored checkpoint upload (all files)",
                buckets=SECONDS_BUCKETS)
            self._m_retries = registry.counter(
                "ddp_ckpt_upload_retries_total",
                "Mirror upload attempts retried after a store error or "
                "per-op timeout")
            self._m_failures = registry.counter(
                "ddp_ckpt_upload_failures_total",
                "Mirror uploads abandoned after the retry budget — the "
                "checkpoint stays local-only and mirror lag grows")
            registry.gauge(
                "ddp_mirror_lag_epochs",
                "Committed checkpoint epochs not yet durably mirrored "
                "(0 = mirror current)").set_function(
                    lambda: float(self.lag_epochs()))
        self._q: "queue.Queue" = queue.Queue()
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()
        # analysis: shared-under(_lock)
        self._pending = {}       # epoch -> True, committed-not-yet-mirrored
        # analysis: shared-under(_lock)
        self._mirrored = []      # mirror manifest entries, newest first
        # analysis: shared-under(_lock)
        self._in_flight = set()  # remote names being uploaded right now
        # analysis: shared-under(_lock)
        self._outstanding = 0    # queued-or-running jobs (drain watches it)
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="ckpt-mirror")
        self._thread.start()

    # -- writer-thread side ------------------------------------------------

    def enqueue(self, *, epoch: int, step: int, sha256: str,
                shards: Sequence[str] = (),
                data_state: Optional[Dict[str, Any]] = None) -> None:
        """Queue one freshly-committed checkpoint for mirroring.  Called
        on the trainer's checkpoint writer thread right after
        ``lineage.commit``; never blocks, never raises into the save."""
        epoch = int(epoch)
        d = os.path.dirname(self.path)
        remote_head = os.path.basename(lineage_name(self.path, epoch))
        snap = os.path.join(d, remote_head + ".mirror")
        try:
            if os.path.exists(snap):
                os.unlink(snap)
            try:
                os.link(self.path, snap)
            except OSError:  # filesystems without hard links
                shutil.copy2(self.path, snap)
        except OSError as e:
            _log(f"WARNING: mirror: could not snapshot head for epoch "
                 f"{epoch} ({e}); this epoch stays local-only")
            return
        files = [(snap, remote_head, sha256, True)]
        for s in shards or ():
            name = os.path.basename(str(s))
            files.append((os.path.join(d, name), name, None, False))
            sidecar = os.path.join(d, name + ".sha256")
            if os.path.exists(sidecar):
                files.append((sidecar, name + ".sha256", None, False))
        entry: Dict[str, Any] = {"file": remote_head, "epoch": epoch,
                                 "step": int(step), "sha256": sha256}
        if shards:
            entry["shards"] = [os.path.basename(str(s)) for s in shards]
        if data_state is not None:
            entry["data_state"] = data_state
        with self._lock:
            self._pending[epoch] = True
            self._outstanding += 1
        self._q.put({"epoch": epoch, "files": files, "entry": entry})

    def state_of_epoch(self, epoch: int) -> str:
        """Lineage manifests stamp this per entry: ``"mirrored"`` once
        the epoch's objects + remote manifest landed, else ``"pending"``."""
        with self._lock:
            if any(e.get("epoch") == int(epoch) for e in self._mirrored):
                return "mirrored"
        return "pending"

    def lag_epochs(self) -> int:
        """Committed-but-not-yet-mirrored epochs (the ``/healthz`` number:
        0 = the mirror is current; growth = remote falling behind)."""
        with self._lock:
            return len(self._pending)

    def mirrored_entries(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._mirrored]

    def drain(self, timeout: float) -> bool:
        """Best-effort wait for the queue to empty (emergency-checkpoint
        exits give the mirror a bounded head start); True when idle."""
        deadline = time.monotonic() + max(0.0, float(timeout))
        while True:
            with self._lock:
                idle = self._outstanding == 0
            if idle:
                return True
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.02)

    def close(self, timeout: float = 5.0) -> None:
        """Drain (bounded), then stop the worker.  Safe to call twice."""
        self.drain(timeout)
        self._stop_evt.set()
        self._q.put(None)
        self._thread.join(timeout=max(1.0, timeout))

    # -- uploader-thread side ----------------------------------------------

    def _run(self) -> None:
        while True:
            job = self._q.get()
            if job is None:
                return
            try:
                self._process(job)
            except BaseException as e:  # the uploader must never die loud
                _log(f"WARNING: mirror uploader error for epoch "
                     f"{job['epoch']}: {type(e).__name__}: {e}")
            finally:
                with self._lock:
                    self._outstanding -= 1

    def _process(self, job: Dict[str, Any]) -> None:
        epoch, entry = job["epoch"], job["entry"]
        t0 = time.monotonic()
        outcome = "ok"
        for local, remote, sha, is_snap in job["files"]:
            got = self._put_with_retry(local, remote,
                                       step=entry["step"],
                                       expected_sha=sha)
            if is_snap:
                _unlink_quiet(local)
            if got != "ok":
                outcome = got
                break
        if outcome == "superseded":
            # The local bytes rotated away / changed before upload — a
            # newer committed epoch is (or will be) in the queue; this
            # epoch no longer needs durability of its own.
            with self._lock:
                self._pending.pop(epoch, None)
            return
        if outcome != "ok":
            if self._m_failures is not None:
                self._m_failures.inc()
            _log(f"WARNING: mirror: epoch {epoch} NOT mirrored (upload "
                 "abandoned after retries); training continues, "
                 "mirror lag grows until a newer epoch lands")
            return
        if self._m_seconds is not None:
            self._m_seconds.observe(time.monotonic() - t0)
        with self._lock:
            self._mirrored = [e for e in self._mirrored
                              if e.get("epoch") != epoch]
            self._mirrored.insert(0, dict(entry))
            self._mirrored.sort(key=lambda e: -int(e.get("epoch", -1)))
            self._mirrored = self._mirrored[: self.keep]
            # Anything at or below the epoch just mirrored is covered:
            # the mirror head is now at least this new.
            self._pending = {ep: True for ep in self._pending
                             if ep > epoch}
            manifest = {
                "format": MIRROR_MANIFEST_FORMAT,
                "mirror": True,
                "head": dict(self._mirrored[0]),
                "retained": [dict(e) for e in self._mirrored[1:]],
            }
        self._publish_manifest(manifest, step=entry["step"])
        self._trim_remote()

    def _publish_manifest(self, manifest: Dict[str, Any],
                          *, step: int) -> None:
        name = self.base + MANIFEST_SUFFIX
        blob = json.dumps(manifest, indent=1).encode()
        got = self._op_with_retry(
            lambda deadline: self.store.put_bytes(name, blob,
                                                  deadline=deadline),
            name, step=step)
        if got != "ok":
            if self._m_failures is not None:
                self._m_failures.inc()
            _log("WARNING: mirror: remote manifest publish failed; the "
                 "mirror head is stale until the next successful commit")

    def _trim_remote(self) -> None:
        """GC remote objects that fell out of retention.  Runs on the
        same thread as every upload (no concurrent put to race), and is
        still guarded: never deletes an in-flight name, and the newest
        mirrored head's file set is always in the keep-set."""
        with self._lock:
            keep_names = {self.base + MANIFEST_SUFFIX}
            for e in self._mirrored:
                keep_names.add(str(e.get("file")))
                for s in e.get("shards", []) or []:
                    keep_names.add(str(s))
                    keep_names.add(str(s) + ".sha256")
            in_flight = set(self._in_flight)
        try:
            names = self.store.list()
        except StoreError:
            return
        for name in names:
            if name in keep_names or name in in_flight:
                continue
            try:
                self.store.delete(name)
            except StoreError:
                pass  # retention is best-effort, next trim retries

    def _put_with_retry(self, local: str, remote: str, *, step: int,
                        expected_sha: Optional[str]) -> str:
        """Upload one file with bounded retries; ``"ok"`` /
        ``"superseded"`` (local bytes gone or changed) / ``"failed"``."""
        def op(deadline):
            return self.store.put(local, remote, deadline=deadline)
        return self._op_with_retry(op, remote, step=step,
                                   expected_sha=expected_sha)

    def _op_with_retry(self, op, remote: str, *, step: int,
                       expected_sha: Optional[str] = None) -> str:
        for attempt in range(self.policy.retries + 1):
            deadline = time.monotonic() + self.op_timeout
            with self._lock:
                self._in_flight.add(remote)
            try:
                with self.tracer.span("ckpt_upload", step=int(step),
                                      overlap=True):
                    sha = op(deadline)
                if expected_sha is not None and sha != expected_sha:
                    _log(f"WARNING: mirror: {remote!r} changed under the "
                         "uploader (rotation outpaced the mirror); "
                         "treating as superseded")
                    try:
                        self.store.delete(remote)
                    except StoreError:
                        pass  # next trim collects the mismatched object
                    return "superseded"
                return "ok"
            except FileNotFoundError:
                _log(f"mirror: local source for {remote!r} rotated away "
                     "before upload; superseded")
                return "superseded"
            except (StoreError, OSError) as e:
                if attempt >= self.policy.retries:
                    _log(f"WARNING: mirror upload of {remote!r} abandoned "
                         f"after {attempt + 1} attempt(s) "
                         f"({type(e).__name__}: {e})")
                    return "failed"
                delay = self.policy.delay(attempt, self._rng)
                if self._m_retries is not None:
                    self._m_retries.inc()
                _log(f"WARNING: mirror upload of {remote!r} attempt "
                     f"{attempt + 1} failed ({type(e).__name__}: {e}); "
                     f"retrying in {delay:.2f}s")
                if self._stop_evt.wait(delay):
                    return "failed"
            finally:
                with self._lock:
                    self._in_flight.discard(remote)
        return "failed"
