"""Watchdog — bound epoch/step wall time instead of hanging with the peers.

The failure this exists for: one host stalls (hung collective, wedged data
source, a peer that died without tearing down the rendezvous) and every
other host blocks inside an XLA collective waiting for it — on the graceful
path that ride lasts the full 300 s shutdown timeout (measured,
parallel/dist.py:94).  The watchdog is a daemon thread fed heartbeats from
the trainer's epoch/step loop; when no beat arrives within ``timeout_s`` it
prints a diagnostic, calls the NON-BLOCKING ``dist.abort()`` (dropping the
coordination-service state so peers fail fast instead of timing out), and
hard-exits with :data:`WATCHDOG_EXIT_STATUS`.  ``os._exit`` rather than an
exception on purpose: the main thread is typically blocked inside a C++
collective and will never see a Python exception — the same hard-kill
discipline NCCL watchdogs use.

The thread holds no GIL dependency on the main thread's progress (blocking
JAX calls release the GIL), so it fires even while the main thread is stuck
in device code.
"""
from __future__ import annotations

import os
import sys
import threading
import time
from typing import Callable, Optional

# 124 — the conventional "timed out" status (GNU timeout(1)); distinct from
# the preemption path's 75 so a restart wrapper can tell "resume me" from
# "something is wedged, investigate".
WATCHDOG_EXIT_STATUS = 124


class Watchdog:
    def __init__(self, timeout_s: float, *, tag: str = "train",
                 on_expire: Optional[Callable[[], None]] = None,
                 context: Optional[Callable[[], str]] = None,
                 exit_status: int = WATCHDOG_EXIT_STATUS,
                 registry=None):
        if timeout_s <= 0:
            raise ValueError(f"watchdog timeout must be > 0, got {timeout_s}")
        self.timeout_s = float(timeout_s)
        self.tag = tag
        self.on_expire = on_expire
        # ``context()`` -> str is printed with the stall diagnostic —
        # cli.py wires the span tracer's last-completed-span summary here
        # (obs/tracer.py::describe_last), so a wedged run names WHAT each
        # host finished last.  Per-host by design: collectives are down
        # during the exact stalls this fires on, so no cross-host gather
        # is possible — each host's stderr carries its own tail.
        self.context = context
        self.exit_status = int(exit_status)
        # Monotonic heartbeat float: torn reads are impossible (CPython
        # float store is atomic) and a stale read only delays expiry by
        # one poll interval — a lock on the per-step beat() would buy
        # nothing but contention.
        # analysis: unlocked-ok(atomic float; staleness bounded by poll)
        self._last = time.monotonic()
        # Same single-writer argument as _last: beat() is the trainer
        # thread only, expirations the watchdog thread only (and the
        # process exits right after).
        # analysis: unlocked-ok(single-writer int; scrape-only readers)
        self.beats = 0
        # analysis: unlocked-ok(single-writer int; scrape-only readers)
        self.expirations = 0
        if registry is not None:
            registry.counter(
                "ddp_watchdog_beats_total",
                "Progress heartbeats received").set_function(
                    lambda: float(self.beats))
            registry.counter(
                "ddp_watchdog_expirations_total",
                "Watchdog expiries (stall -> hard exit)").set_function(
                    lambda: float(self.expirations))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._exit = os._exit  # monkeypatch seam for in-process tests

    def beat(self) -> None:
        """Record progress; cheap enough for per-step calls."""
        self._last = time.monotonic()
        self.beats += 1

    def last_beat_age(self) -> float:
        """Seconds since the last heartbeat — the /healthz liveness
        number (obs/inspect.py): an age approaching ``timeout_s`` is a
        stall in progress, visible before the expiry fires."""
        return time.monotonic() - self._last

    def start(self) -> "Watchdog":
        if self._thread is not None:
            return self
        self.beat()
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name=f"watchdog-{self.tag}", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2.0)

    def __enter__(self) -> "Watchdog":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    def _run(self) -> None:
        poll = min(1.0, self.timeout_s / 4.0)
        while not self._stop.wait(poll):
            idle = time.monotonic() - self._last
            if idle > self.timeout_s:
                self._expire(idle)
                return

    def _expire(self, idle: float) -> None:
        self.expirations += 1
        print(f"WATCHDOG [{self.tag}]: no progress for {idle:.1f}s "
              f"(limit {self.timeout_s:.1f}s); aborting the coordination "
              f"service and hard-exiting {self.exit_status} so peers fail "
              "fast instead of riding the 300 s shutdown timeout",
              file=sys.stderr)
        if self.context is not None:
            try:
                detail = self.context()
            except Exception as e:
                detail = f"<context hook failed: {e!r}>"
            if detail:
                print(f"WATCHDOG [{self.tag}]: last completed spans on "
                      f"this host: {detail}", file=sys.stderr)
        sys.stderr.flush()
        try:
            if self.on_expire is not None:
                self.on_expire()
        except Exception as e:
            print(f"WATCHDOG [{self.tag}]: on_expire hook failed: {e!r}",
                  file=sys.stderr)
        try:
            from ..parallel import dist
            dist.abort()  # non-graceful: never blocks (dist.py)
        finally:
            self._exit(self.exit_status)
