"""Preemption handling: SIGTERM/SIGINT -> one coordinated emergency
checkpoint at the next *step* boundary, then a clean distinct-status exit.

Why the step boundary is safe (round 12; it used to be the epoch
boundary): the checkpoint payload now carries a ``data_state`` record —
epoch, iterator offset, sampler seed, RNG fold count — and the prefetch
engine fast-forwards to an arbitrary batch index, so a mid-epoch snapshot
resumes onto the *identical* trajectory an uninterrupted run takes (the
bit-for-bit property the mid-epoch drill in tests/test_resilience.py
pins).  Batch content is a pure function of ``(seed, epoch, k)`` and the
step RNG a pure function of the restored step counter, so neither loses
the partial epoch's updates nor double-trains its batches.  On the
``--resident`` path the whole epoch is one dispatch, so there the epoch
boundary IS the step boundary and the stop decision stays per-epoch.

Multi-host coordination: the local signal flag is OR-reduced across
processes with a tiny jitted collective over the training mesh (the same
asymmetric-topology-safe pattern as ``mesh.process_min_mib``), so every
host agrees on the stop epoch and runs the (collective) checkpoint
canonicalisation + save in lockstep.  When ``jax.distributed`` created a
preemption sync manager (it does so at initialize), its
``reached_sync_point`` signal is polled too — that is how cloud preemption
notices delivered below Python (the TPU pod metadata path) join the same
epoch-boundary decision.

Second-signal escape hatch: the first SIGTERM/SIGINT arms the graceful
path and *restores the previous handler*, so a second signal kills the
process immediately — an operator's Ctrl-C Ctrl-C still works.
"""
from __future__ import annotations

import signal
import sys
import threading
from typing import Optional

import jax
import numpy as np

# EX_TEMPFAIL: "temporary failure, retry" — the restart wrapper's cue that
# an emergency checkpoint is on disk and a ``--resume`` relaunch will
# continue the run.  Distinct from 0 (done), 1 (real failure), and the
# watchdog's 124 (no progress).  The relaunch does NOT need the same
# topology: restore redistributes either checkpoint format onto whatever
# mesh the relaunch builds (train/ckpt_shard.py), so a preemption that
# SHRINKS the pod — the common cloud case: some hosts never come back —
# is survivable by resuming with the surviving ``--mesh_shape`` (elastic
# resume; RUNBOOK §11).
EMERGENCY_CHECKPOINT_EXIT_STATUS = 75


class PreemptionInterrupt(BaseException):
    """Raised by ``Trainer.train`` after the emergency checkpoint landed.

    A ``BaseException`` (like ``KeyboardInterrupt``): this is not a program
    error and must not be swallowed by ``except Exception`` recovery
    paths.  ``cli.run`` converts it into
    ``SystemExit(EMERGENCY_CHECKPOINT_EXIT_STATUS)``.
    """

    def __init__(self, epoch: int, path: Optional[str]):
        self.epoch = epoch
        self.path = path
        super().__init__(
            f"preempted: emergency checkpoint at epoch {epoch}"
            + (f" in {path!r} (any mesh shape can --resume it)" if path
               else " (checkpointing disabled)"))


class PreemptionGuard:
    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self._signals = tuple(signals)
        self._noticed = threading.Event()
        self._prev: dict = {}
        self._installed = False

    def install(self) -> "PreemptionGuard":
        """Install the handlers (main thread only — ``signal.signal``
        raises elsewhere; callers off the main thread just skip graceful
        preemption)."""
        if self._installed:
            return self
        for sig in self._signals:
            self._prev[sig] = signal.signal(sig, self._handler)
        self._installed = True
        return self

    def uninstall(self) -> None:
        if not self._installed:
            return
        for sig, prev in self._prev.items():
            try:
                # None means "installed from C" (signal.getsignal contract)
                # — we cannot re-install that from Python; default is the
                # closest safe restoration.
                signal.signal(sig, prev if prev is not None
                              else signal.SIG_DFL)
            except (ValueError, OSError):
                pass
        self._prev.clear()
        self._installed = False

    def _handler(self, signum, frame) -> None:
        self._noticed.set()
        print(f"preemption notice ({signal.Signals(signum).name}): will "
              "take an emergency checkpoint at the next step boundary and "
              "exit with status "
              f"{EMERGENCY_CHECKPOINT_EXIT_STATUS}; signal again to die "
              "immediately", file=sys.stderr)
        sys.stderr.flush()
        # Re-arm the pre-existing behavior so a second signal is immediate.
        prev = self._prev.get(signum)
        try:
            signal.signal(signum, prev if prev is not None
                          else signal.SIG_DFL)
        except (ValueError, OSError):
            pass

    def noticed(self) -> bool:
        """This process's local flag (signal seen, not yet coordinated)."""
        return self._noticed.is_set()

    def should_stop(self, epoch: int, mesh) -> bool:
        """Coordinated stop decision at the ``epoch`` boundary (the
        ``--resident`` path, where the epoch IS the dispatch unit).

        Multi-host this is a COLLECTIVE — every process must call it at
        every epoch boundary, in the same order relative to the trainer's
        other collectives, whether or not it saw a signal locally.
        The ``if _process_any(mesh, local):`` shape below — a collective
        in the *test* position, never under a host-local branch — is the
        pattern the divergence lint (``analysis/divergence.py``)
        sanctions: decide collectively, then branch.
        """
        return self._should_stop_at(int(epoch), mesh)

    def should_stop_step(self, step: int, mesh) -> bool:
        """Coordinated stop decision at a global *step* boundary — the
        streaming loop's per-step check.  Same collective discipline as
        :meth:`should_stop`, with the global optimizer step as the one
        sync-id space (monotonic across epochs, identical on every
        process), so a notice delivered to any host stops every host at
        the same step.  Single-process (every test topology and the
        virtual-replica CPU meshes) this is a host-local Event check
        plus one non-blocking manager poll — no device work on the
        common no-signal step.  Multi-host it is a per-step collective,
        unconditionally: the OR-reduce must run on every process or none
        (divergence-lint discipline — a host-local branch around a
        collective is the deadlock it lints against).
        """
        return self._should_stop_at(int(step), mesh)

    def _should_stop_at(self, sync_id: int, mesh) -> bool:
        from ..parallel import dist
        local = self._noticed.is_set()
        mgr = dist.preemption_sync_manager()
        if mgr is not None:
            try:
                # Non-blocking; returns True on every process at the same
                # (coordinated) counter once any task got a notice through
                # the runtime's own channel.
                local = bool(mgr.reached_sync_point(sync_id)) or local
            except Exception:
                pass  # manager torn down mid-run: the flag path stands
        if jax.process_count() == 1:
            return local
        if _process_any(mesh, local):
            self._noticed.set()  # a peer was preempted: we stop too
            return True
        return False


def _process_any(mesh, flag: bool) -> bool:
    """OR of a per-process bool over the mesh's processes — the same
    device-collective pattern as ``mesh.process_min_mib`` (asymmetric-
    topology-safe, no ``process_allgather`` reshape assumptions)."""
    import jax.numpy as jnp

    from ..parallel.mesh import (assemble_from_local, batch_sharding,
                                 local_replica_ids, replicated_sharding)
    vals = assemble_from_local(
        batch_sharding(mesh),
        np.full(len(local_replica_ids(mesh)), 1 if flag else 0, np.int32),
        0)
    return bool(int(jax.jit(
        jnp.max, out_shardings=replicated_sharding(mesh))(vals)))
