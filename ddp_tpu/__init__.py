"""ddp_tpu — TPU-native re-implementation of
``UnchartedWhispers/Distributed-Data-Parallel-Experiment``.

The reference repo is a pair of near-identical PyTorch scripts
(``singlegpu.py`` / ``multigpu.py``, see /root/repo/SURVEY.md) whose only
difference is the data-parallel plumbing (NCCL process group + DDP wrapper +
DistributedSampler).  On TPU that whole diff collapses into the size of a
``jax.sharding.Mesh``: the single-chip and multi-chip paths here are the same
jitted ``train_step``, executed over a mesh of 1 or N devices.

Package layout
--------------
- ``ops/``      low-level NN ops (conv, batch-norm, pooling, linear, losses)
                with PyTorch-default-parity initialisation.
- ``models/``   VGG (reference singlegpu.py:47-82), DeepNN (singlegpu.py:18-44),
                ResNet-18 (BASELINE.json config #3).
- ``optim/``    SGD with the PyTorch momentum/weight-decay convention
                (reference singlegpu.py:135-140) and the triangular LR
                schedule (singlegpu.py:142-149).
- ``data/``     CIFAR-10 pipeline, torch-``DistributedSampler``-exact sharding
                (multigpu.py:147-154), vectorised augmentation, prefetch.
- ``parallel/`` device mesh + shard_map data parallelism (the TPU-native
                replacement for DDP/NCCL, multigpu.py:24-33, 89).
- ``train/``    Trainer engine (singlegpu.py:85-128), evaluation
                (singlegpu.py:184-209), checkpoint save/restore.
- ``serve/``    inference serving: dynamic micro-batcher over bucketed
                AOT-warmed eval forwards, stdlib HTTP front end
                (``python -m ddp_tpu.serve``; no reference analogue).
- ``utils/``    model-size reporting (singlegpu.py:212-225), torch interop
                for parity tests, metrics logging.
"""

__version__ = "0.1.0"

# jax-version compatibility shims (utils/compat.py): on jax 0.4.x runtimes
# this installs ``jax.shard_map``/``jax.lax.pcast`` aliases over the
# experimental-namespace ancestors so the jax>=0.9-targeted call sites run
# unchanged; a no-op on jax>=0.9.  Must happen at package import, before
# any step builder references the new names.
from .utils import compat as _compat  # noqa: E402,F401
