"""Alternative 2x2/2 max-pool aimed at the pool-backward residue.

BASELINE.md's phase split charges ~1.4-1.8 ms/step to "pool backward":
the autodiff VJP of ``lax.reduce_window`` max is ``select-and-scatter``,
a windowed scan op.  For the VGG case (window == stride == 2, no
padding, even spatial dims) the same pooling is expressible as a
reshape + axis max, whose backward is pure elementwise work (equality
mask + broadcast) that XLA can fuse — IF the tie-breaking is made to
match.  Plain ``jnp.max`` autodiff splits the cotangent EVENLY among
tied window elements; ``select_and_scatter`` (and torch's maxpool)
route it to the FIRST maximal element in row-major window order — and
ties are common here because post-ReLU activations carry exact zeros.
``max_pool_reshape`` therefore pins first-tie semantics with a custom
VJP (cumulative-count-of-ties == 1 mask), making it numerically
identical to :func:`~ddp_tpu.ops.layers.max_pool` forward AND backward.

Measure with ``python -m ddp_tpu.ops.pool_candidates`` (marginal-cost
chains, same differencing methodology as ``conv_probe``); one JSON line
per (impl, shape).  The result — win or negative — belongs next to the
conv-candidate table in BASELINE.md.
"""
from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp
from jax import lax

# Shared timing methodology — chain lengths, noise threshold, and the
# best-of core come from the conv probe so the two cannot drift.
from .conv_probe import N_LONG, N_SHORT, NOISE_S_PER_CALL, best_of

# (H=W, C) at batch 512 — every "M" site in VGG.ARCH (models/vgg.py:23).
VGG_POOL_SHAPES = [(32, 128), (16, 256), (8, 512), (4, 512)]


@jax.custom_vjp
def max_pool_reshape(x: jax.Array) -> jax.Array:
    """2x2 stride-2 max pool of NHWC ``x`` (even H and W) as reshape+max
    with a pure-elementwise first-tie backward — the CANDIDATE.  Wins
    the isolated chains 1.6x but loses the composed step by 20% (its
    window-view transposes force activation relayouts that fight the
    conv layouts), so the shipped ``max_pool`` stays on
    ``reduce_window`` (layers.py)."""
    n, h, w, c = x.shape
    return x.reshape(n, h // 2, 2, w // 2, 2, c).max(axis=(2, 4))


def _window_view(x):
    """[N,H,W,C] -> [N,H/2,W/2,4,C] with window index in ROW-MAJOR order
    ((dy,dx) = (0,0),(0,1),(1,0),(1,1)) — the order select_and_scatter
    (and torch) break ties in."""
    n, h, w, c = x.shape
    return (x.reshape(n, h // 2, 2, w // 2, 2, c)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(n, h // 2, w // 2, 4, c))


def _fwd(x):
    y = max_pool_reshape(x)
    return y, (x, y)


def _bwd(res, dy):
    x, y = res
    n, h, w, c = x.shape
    eq = (_window_view(x) == y[:, :, :, None, :])
    # First maximal element per window: the tie where the running count
    # of ties is exactly 1.  Pure elementwise + a length-4 cumsum — no
    # windowed scatter anywhere in the backward.
    first = eq & (jnp.cumsum(eq, axis=3) == 1)
    dxw = jnp.where(first, dy[:, :, :, None, :], 0).astype(x.dtype)
    dx = (dxw.reshape(n, h // 2, w // 2, 2, 2, c)
          .transpose(0, 1, 3, 2, 4, 5)
          .reshape(n, h, w, c))
    return (dx,)


max_pool_reshape.defvjp(_fwd, _bwd)


def _reduce_window_pool(x):
    """The shipped implementation (autodiff backward =
    select-and-scatter) — the probe baseline."""
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, 2, 2, 1), window_strides=(1, 2, 2, 1),
        padding=((0, 0), (0, 0), (0, 0), (0, 0)))


IMPLS = {
    "baseline_reduce_window": _reduce_window_pool,
    "reshape_max_first_tie": max_pool_reshape,
}


def _train_chain(n, pool):
    def win(x):
        acc = jnp.zeros((), x.dtype)
        for _ in range(n):
            y, vjp = jax.vjp(pool, x + acc * 1e-30)
            (dx,) = vjp(y)
            acc = jnp.mean(dx) + jnp.mean(y)
        return acc

    return jax.jit(win)


def probe(batch=512, repeats=6, dtype=jnp.float32):
    records = []
    for name, pool in IMPLS.items():
        for h, c in VGG_POOL_SHAPES:
            # ReLU-like data: exact zeros make ties common, as in the
            # real activations this op pools.
            x = jax.nn.relu(jax.random.normal(
                jax.random.key(0), (batch, h, h, c), dtype) - 0.3)
            t_s = best_of(_train_chain(N_SHORT, pool), (x,), repeats)
            t_l = best_of(_train_chain(N_LONG, pool), (x,), repeats)
            per = max((t_l - t_s) / (N_LONG - N_SHORT), 1e-9)
            rec = {"impl": name, "shape": f"{h}x{h}x{c}",
                   "marginal_ms_per_call": round(per * 1e3, 3),
                   "noise_limited": (t_l - t_s) < NOISE_S_PER_CALL
                   * (N_LONG - N_SHORT)}
            records.append(rec)
            print(json.dumps(rec), flush=True)
    for name in IMPLS:
        total = sum(r["marginal_ms_per_call"] for r in records
                    if r["impl"] == name)
        print(json.dumps({"impl": name,
                          "sum_marginal_ms_per_step": round(total, 3)}),
              flush=True)
    return records


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=512)
    p.add_argument("--repeats", type=int, default=6)
    p.add_argument("--bf16", action="store_true")
    args = p.parse_args()
    probe(args.batch, args.repeats,
          jnp.bfloat16 if args.bf16 else jnp.float32)


if __name__ == "__main__":
    main()
