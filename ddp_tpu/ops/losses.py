"""Losses. The reference uses only ``F.cross_entropy`` with default mean
reduction (singlegpu.py:105)."""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def cross_entropy_per_example(logits: jax.Array,
                              labels: jax.Array) -> jax.Array:
    """Per-example softmax cross-entropy, computed in fp32 for stability.

    Matches ``F.cross_entropy(..., reduction='none')``.
    """
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(logits, labels[:, None], axis=-1)[:, 0]
    return logz - picked


def cross_entropy_sum_count(logits: jax.Array, labels: jax.Array,
                            mask: Optional[jax.Array] = None,
                            ) -> Tuple[jax.Array, jax.Array]:
    """(sum of CE over valid examples, valid count).

    The mean is taken as a *global* psum(sum)/psum(count) in the train step so
    ragged final batches (padded+masked to keep XLA shapes static,
    SURVEY.md section 7 hard-part #3) don't perturb the loss, and so the
    distributed mean matches DDP's gradient averaging exactly (with torch's
    ``DistributedSampler`` every rank has an equal count, making
    mean-of-rank-means == global mean).
    """
    ce = cross_entropy_per_example(logits, labels)
    if mask is None:
        return ce.sum(), jnp.asarray(ce.shape[0], jnp.float32)
    maskf = mask.astype(jnp.float32)
    return (ce * maskf).sum(), maskf.sum()
