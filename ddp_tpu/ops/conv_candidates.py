"""Alternative 3x3 SAME-conv implementations for the conv-probe seam.

BASELINE.md's round-3 kernel-substitution analysis concluded "a hand kernel
can't win under fp32 semantics" from a fusion-barrier argument plus emitter
measurements — but the probe's pluggable ``conv=`` seam
(:func:`~ddp_tpu.ops.conv_probe.probe`) never had an actual candidate
plugged in (VERDICT r3 missing #3).  This module supplies three real
candidates and a CLI to measure them under the identical marginal-cost
harness, targeting the two sub-peak shapes (32x32 64->128 trains at
~96 TFLOP/s; 8x8 256->512 at ~134, vs 170-195 elsewhere):

- ``conv2d_shift9``: pure-lax shift-and-matmul — nine accumulated
  ``[N*H*W, Cin] @ [Cin, Cout]`` matmuls on 1-pixel-shifted views.  No
  patch materialisation; K = Cin per pass.
- ``conv2d_im2col``: pure-lax im2col — materialise the ``[N,H,W,9*Cin]``
  patch tensor, one big matmul with K = 9*Cin (MXU-friendlier K at the
  cost of 9x activation HBM traffic).
- ``conv2d_pallas``: fused shift-and-matmul in a Pallas kernel — the
  padded input block is DMA'd to VMEM once per grid cell, and the nine
  shifted views are read from VMEM and accumulated through nine MXU dots
  (shifted patches never touch HBM).  An in-kernel im2col concat
  (one K = 9*Cin dot) was tried first and is NOT implementable today:
  Mosaic rejects concatenation of lane-offset shifted slices
  ("result/input offset mismatch on non-concat dimension").

All three are numerically the conv2d contract (same SAME padding, stride
1; fp32 accumulation) and carry a custom VJP routing dgrad through the
same fast forward (dgrad of a SAME 3x3 conv IS a SAME 3x3 conv with the
spatially-flipped, in/out-transposed kernel) and wgrad through a
shifted-matmul einsum.  Measure with::

    python -m ddp_tpu.ops.conv_candidates [--bf16] [--all_shapes]

One JSON line per (candidate, shape, direction) — the BASELINE.md
evidence row, win or negative result.
"""
from __future__ import annotations

import argparse
import json

import jax
import jax.numpy as jnp
import numpy as np


def _pad_hw(x):
    return jnp.pad(x, ((0, 0), (1, 1), (1, 1), (0, 0)))


def _shift9_fwd(x, w):
    n, h, wd, cin = x.shape
    cout = w.shape[-1]
    xp = _pad_hw(x)
    acc = jnp.zeros((n, h, wd, cout), jnp.float32)
    for ky in range(3):
        for kx in range(3):
            acc = acc + jax.lax.dot_general(
                xp[:, ky:ky + h, kx:kx + wd, :], w[ky, kx],
                (((3,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
    return acc.astype(x.dtype)


def _im2col_patches(x):
    """[N,H,W,Cin] -> [N,H,W,9*Cin] patch tensor (ky-major, kx, cin-minor
    — matching w.reshape(9*cin, cout))."""
    n, h, wd, cin = x.shape
    xp = _pad_hw(x)
    return jnp.concatenate(
        [xp[:, ky:ky + h, kx:kx + wd, :]
         for ky in range(3) for kx in range(3)], axis=-1)


def _im2col_fwd(x, w):
    n, h, wd, cin = x.shape
    cout = w.shape[-1]
    p = _im2col_patches(x).reshape(n * h * wd, 9 * cin)
    y = jax.lax.dot_general(p, w.reshape(9 * cin, cout),
                            (((1,), (0,)), ((), ())),
                            preferred_element_type=jnp.float32)
    return y.reshape(n, h, wd, cout).astype(x.dtype)


def _pick_block_n(n, h, cin, cout, bytes_per_el):
    """Largest batch tile whose ESTIMATED VMEM footprint (padded input
    block + one shifted-slice copy + fp32 accumulator + weights) fits a
    6 MiB budget — Mosaic's actual stack allocation measured ~2x this
    estimate (double-buffered blocks + live dot operands), and the scoped
    limit is 16 MiB, so 6 MiB estimated keeps the real footprint inside."""
    budget = 6 * 2 ** 20
    w_bytes = 9 * cin * cout * bytes_per_el
    for bn in (128, 64, 32, 16, 8, 4, 2, 1):
        if n % bn:
            continue
        in_b = bn * (h + 2) * (h + 2) * cin * bytes_per_el
        slice_b = bn * h * h * cin * bytes_per_el
        acc_b = bn * h * h * cout * 4
        if w_bytes + in_b + slice_b + acc_b <= budget:
            return bn
    return 1


def _pallas_fwd(x, w):
    """Fused shift-and-matmul forward as a Pallas TPU kernel: nine
    accumulated K=Cin MXU dots over VMEM-resident shifted views."""
    from jax.experimental import pallas as pl

    n, h, wd, cin = x.shape
    cout = w.shape[-1]
    dtype = x.dtype
    bn = _pick_block_n(n, h, cin, cout, np.dtype(dtype).itemsize)
    xp = _pad_hw(x)
    w2 = w.reshape(9, cin, cout)

    def kernel(xp_ref, w_ref, out_ref):
        acc = jnp.zeros((bn * h * wd, cout), jnp.float32)
        for ky in range(3):
            for kx in range(3):
                xs = xp_ref[:, ky:ky + h, kx:kx + wd, :]
                acc = acc + jax.lax.dot_general(
                    xs.reshape(bn * h * wd, cin), w_ref[3 * ky + kx],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)
        out_ref[:] = acc.reshape(bn, h, wd, cout).astype(dtype)

    return pl.pallas_call(
        kernel,
        grid=(n // bn,),
        in_specs=[
            pl.BlockSpec((bn, h + 2, wd + 2, cin), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((9, cin, cout), lambda i: (0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((bn, h, wd, cout), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h, wd, cout), dtype),
    )(xp, w2)


def _flip_transpose(w):
    """dgrad kernel: spatial flip + in/out channel transpose, so dgrad is
    the SAME fast forward conv applied to dy."""
    return jnp.flip(w, axis=(0, 1)).transpose(0, 1, 3, 2)


def _wgrad(x, dy):
    """dw[ky,kx,cin,cout] = sum_nhw xpad[n, h+ky, w+kx, cin] * dy[n,h,w,cout]
    — nine [Cin, N*H*W] @ [N*H*W, Cout] matmuls."""
    n, h, wd, cin = x.shape
    cout = dy.shape[-1]
    xp = _pad_hw(x)
    dyf = dy.reshape(n * h * wd, cout)
    rows = []
    for ky in range(3):
        for kx in range(3):
            xs = xp[:, ky:ky + h, kx:kx + wd, :].reshape(n * h * wd, cin)
            rows.append(jax.lax.dot_general(
                xs, dyf, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32))
    return jnp.stack(rows).reshape(3, 3, cin, cout).astype(x.dtype)


def _xla_bwd(res, dy):
    """Backward delegated to XLA's own dgrad/wgrad conv emitters (which
    beat the hand shifted-matmul wgrad in measurement).  jax.vjp runs the
    primal forward too, but its output feeds nothing and XLA DCEs it
    under jit — the backward program that remains is the baseline's."""
    from .layers import conv2d
    x, w = res
    _, vjp = jax.vjp(conv2d, x, w)
    return vjp(dy)


def _with_vjp(fwd, bwd=None):
    """Wrap a forward into the probe's conv contract.  Default backward:
    dgrad via the same fast forward (a SAME 3x3 conv of dy with the
    flipped, transposed kernel), wgrad via shifted matmuls."""

    @jax.custom_vjp
    def conv(x, w):
        return fwd(x, w)

    def conv_fwd(x, w):
        return fwd(x, w), (x, w)

    def conv_bwd(res, dy):
        x, w = res
        return fwd(dy, _flip_transpose(w)), _wgrad(x, dy)

    conv.defvjp(conv_fwd, bwd or conv_bwd)
    return conv


conv2d_shift9 = _with_vjp(_shift9_fwd)
conv2d_im2col = _with_vjp(_im2col_fwd)
conv2d_pallas = _with_vjp(_pallas_fwd)
# The hybrid an early single-candidate run suggested could win (Pallas
# forward at an apparent 197.6 TFLOP/s vs 175.6).  The same-process
# head-to-head (BASELINE.md round-4 table) shows it LOSING every cell —
# that early delta was harness noise.  Kept as the measured negative.
conv2d_pallas_fwd_xla_bwd = _with_vjp(_pallas_fwd, bwd=_xla_bwd)

CANDIDATES = {
    "baseline_xla_conv": None,  # conv_probe's default conv2d
    "shift9_lax": conv2d_shift9,
    "im2col_lax": conv2d_im2col,
    "shift9_fused_pallas": conv2d_pallas,
    "pallas_fwd_xla_bwd": conv2d_pallas_fwd_xla_bwd,
}

# The two sub-peak shapes the round-3 roofline flagged (plus reps=1).
TARGET_SHAPES = [(32, 64, 128, 1), (8, 256, 512, 1)]


def main() -> None:
    from . import conv_probe

    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=512)
    p.add_argument("--repeats", type=int, default=6)
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--all_shapes", action="store_true",
                   help="Probe every VGG conv shape, not just the two "
                        "sub-peak targets")
    p.add_argument("--candidates", default=None,
                   help="Comma list (default: all)")
    args = p.parse_args()
    shapes = (conv_probe.VGG_CONV_SHAPES if args.all_shapes
              else TARGET_SHAPES)
    names = (args.candidates.split(",") if args.candidates
             else list(CANDIDATES))
    unknown = [n for n in names if n not in CANDIDATES]
    if unknown:
        p.error(f"unknown candidate(s) {unknown}; "
                f"valid: {', '.join(CANDIDATES)}")
    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    for name in names:
        cand = CANDIDATES[name]
        kw = {} if cand is None else {"conv": cand}
        print(json.dumps({"candidate": name}), flush=True)
        conv_probe.probe(args.batch, args.repeats, dtype, shapes=shapes,
                         **kw)


if __name__ == "__main__":
    main()
