"""Low-level NN ops in the TPU-native layout (NHWC activations, HWIO kernels).

These are the building blocks for the models in ``ddp_tpu.models``; each op's
numerics are tested for parity against the equivalent torch CPU op
(tests/test_ops.py).  The reference gets these from torch.nn / cuDNN
(singlegpu.py:64-73); on TPU we express them so XLA can tile the convolutions
onto the MXU and fuse the elementwise BN/ReLU chains into them.
"""
from __future__ import annotations

import contextlib
import functools
import threading
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# NHWC / HWIO are the layouts XLA:TPU convolutions are natively tiled for.
CONV_DIMS = ("NHWC", "HWIO", "NHWC")

# Ambient trace-time BN context, THREAD-LOCAL so concurrent traces (async
# compiles, threaded tests) can each set their own axes without
# cross-contamination.  Two fields:
#
# ``sync_axis`` — mesh axis over which batch_norm synchronises its batch
# statistics (the TPU-native SyncBatchNorm the reference keeps commented
# out, multigpu.py:127).  A trace-time context rather than a per-call
# argument so model code stays signature-identical whether BN is synced or
# not; the step builders (train/step.py) set it from their sync_bn flag.
#
# ``grad_axis`` — mesh axis over which bn_relu's hand-written VJP
# all-reduces its scale/bias cotangents.  Autodiff-generated backward gets
# this psum inserted by shard_map's replication-transpose machinery; a
# custom_vjp opts out of that machinery, so the gradient collective must be
# explicit.  Set by the REPLICATED-params cores (train/step.py
# make_loss_and_grads); deliberately NOT set by the ZeRO path
# (train/zero.py _make_local_grads), whose contract is collective-free
# LOCAL gradients reduced later by psum_scatter.
_BN_CTX = threading.local()


def _bn_sync_axis() -> Optional[str]:
    return getattr(_BN_CTX, "sync_axis", None)


def _bn_grad_axis() -> Optional[str]:
    return getattr(_BN_CTX, "grad_axis", None)


@contextlib.contextmanager
def bn_sync_axis(axis_name: Optional[str]):
    """Within this context (and thread), training-mode batch_norm psums its
    statistics over ``axis_name`` (must be inside shard_map over that
    axis)."""
    prev = _bn_sync_axis()
    _BN_CTX.sync_axis = axis_name
    try:
        yield
    finally:
        _BN_CTX.sync_axis = prev


@contextlib.contextmanager
def bn_grad_axis(axis_name: Optional[str]):
    """Within this context (and thread), bn_relu's VJP psums dγ/dβ over
    ``axis_name`` (the DDP gradient all-reduce for the fused op's
    parameters)."""
    prev = _bn_grad_axis()
    _BN_CTX.grad_axis = axis_name
    try:
        yield
    finally:
        _BN_CTX.grad_axis = prev


def conv2d(x: jax.Array, kernel: jax.Array, bias: Optional[jax.Array] = None,
           stride: int = 1, padding: int = 1) -> jax.Array:
    """3x3-style 2-D convolution. x: [N,H,W,C_in], kernel: [kh,kw,C_in,C_out]."""
    y = lax.conv_general_dilated(
        x, kernel,
        window_strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        dimension_numbers=CONV_DIMS,
    )
    if bias is not None:
        y = y + bias
    return y


def max_pool(x: jax.Array, window: int = 2, stride: int = 2,
             padding: int = 0) -> jax.Array:
    """MaxPool2d(window, stride, padding) — reference singlegpu.py:70 uses
    (2, 2, 0); ResNet-18's stem uses (3, 2, 1).

    Deliberately the ``reduce_window`` form: a reshape-max alternative
    with an elementwise first-tie VJP (``ops/pool_candidates.py``)
    measured 1.6x FASTER in isolation but 20% SLOWER at the whole-step
    level (its window-view transposes force activation relayouts that
    fight the conv layouts) — the recorded negative result in
    BASELINE.md round 4 "pool backward candidate"."""
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding=((0, 0), (padding, padding), (padding, padding), (0, 0)),
    )


def linear(x: jax.Array, weight: jax.Array,
           bias: Optional[jax.Array] = None) -> jax.Array:
    """x @ weight (+ bias). weight: [in, out]."""
    y = x @ weight
    if bias is not None:
        y = y + bias
    return y


def global_avg_pool(x: jax.Array) -> jax.Array:
    """[N,H,W,C] -> [N,C] mean over spatial dims (reference x.mean([2,3]),
    singlegpu.py:79)."""
    return x.mean(axis=(1, 2))


class BatchNormState(NamedTuple):
    """Running statistics (the reference's BN buffers)."""
    mean: jax.Array
    var: jax.Array


def batch_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               state: BatchNormState, *, train: bool,
               momentum: float = 0.1, eps: float = 1e-5,
               ) -> Tuple[jax.Array, BatchNormState]:
    """BatchNorm2d with exact torch semantics.

    Training normalises with the *biased* batch variance but updates the
    running variance with the *unbiased* one (Bessel-corrected), momentum 0.1,
    eps 1e-5 — the torch defaults the reference relies on (singlegpu.py:65).
    Under data parallelism the batch statistics are per-replica: the reference
    deliberately leaves SyncBatchNorm commented out (multigpu.py:127), and
    shard_map gives the same per-shard semantics for free.

    Statistics are accumulated in fp32 even when ``x`` is bf16 so the
    mixed-precision path stays stable.  The statistics encoding (one-pass
    per-shard variance, centered two-pass under sync) lives in
    :func:`_bn_stats`, shared with the fused :func:`bn_relu` so the two
    ops cannot drift.
    """
    if train:
        batch_mean, batch_var, count = _bn_stats(x.astype(jnp.float32),
                                                 _bn_sync_axis())
        unbiased = batch_var * (count / max(count - 1.0, 1.0))
        new_state = _blend_running_stats(state, batch_mean, unbiased,
                                         momentum)
        mean, var = batch_mean, batch_var
    else:
        new_state = state
        mean, var = state.mean, state.var
    inv = lax.rsqrt(var + eps) * scale
    y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype) + bias.astype(x.dtype)
    return y, new_state


def _blend_running_stats(state: BatchNormState, batch_mean, unbiased_var,
                         momentum: float) -> BatchNormState:
    """The torch running-buffer EMA (momentum 0.1 default) — one encoding
    shared by :func:`batch_norm` and :func:`bn_relu` so the fused and
    unfused ops' checkpointed BN buffers cannot drift."""
    return BatchNormState(
        mean=(1.0 - momentum) * state.mean + momentum * batch_mean,
        var=(1.0 - momentum) * state.var + momentum * unbiased_var,
    )


def _bn_stats(xf: jax.Array, axis: Optional[str]):
    """Batch statistics in fp32 — the ONE encoding of the trade-off both
    :func:`batch_norm` and :func:`bn_relu` use: one-pass ``E[x^2]-E[x]^2``
    per-shard (XLA fuses both channel reductions into a single read of the
    activation — BN is bandwidth-bound on TPU; measured +13% whole-step
    for VGG/512 on v5e vs two-pass), or the better-conditioned centered
    two-pass form when syncing over ``axis`` (under cancellation the
    one-pass form amplifies the psum's rounding ~10x more than centering
    does, verified against an f64 reference — sync-BN is opt-in, so the
    extra read of x buys the better statistics, the same choice torch's
    SyncBatchNorm makes).  Returns (mean, biased_var, count); ``count`` is
    the total reduced element count, always a Python float (shapes and
    mesh axis sizes are static at trace time)."""
    n = float(xf.shape[0] * xf.shape[1] * xf.shape[2])
    if axis is None:
        mean = xf.mean(axis=(0, 1, 2))
        var = jnp.maximum((xf * xf).mean(axis=(0, 1, 2)) - mean * mean, 0.0)
        return mean, var, n
    r = lax.axis_size(axis)
    mean = lax.psum(xf.mean(axis=(0, 1, 2)), axis) / r
    d = xf - mean
    var = lax.psum((d * d).mean(axis=(0, 1, 2)), axis) / r
    return mean, var, n * r


def _bn_relu_fwd_impl(eps: float, axis: Optional[str], x, scale, bias):
    xf = x.astype(jnp.float32)
    mean, var, count = _bn_stats(xf, axis)
    inv = lax.rsqrt(var + eps)
    xhat = (xf - mean) * inv
    z = jnp.maximum(xhat * scale + bias, 0.0).astype(x.dtype)
    unbiased = var * (count / max(count - 1.0, 1.0))
    return z, mean, unbiased, (x, mean, inv)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _bn_relu_train(eps: float, axis: Optional[str], grad_axis: Optional[str],
                   x, scale, bias):
    """Fused training-mode BatchNorm+ReLU with a hand-written VJP.

    The VJP recomputes the ReLU mask (``x̂·γ+β > 0``) and x̂ from ``x``
    alone, so the whole backward touches only ``(x, dz)``: one fused
    reduction pass (dβ, dγ) and one fused elementwise pass (dx) — the
    5-activation-pass minimum, exact fp32 math (the mask recompute is
    bit-exact against the forward's own ŷ).  NB the hypothesis that
    autodiff needed ~7-8 passes here (reading ``z`` for the mask and
    materialising dŷ) was MEASURED FALSE on v5e: XLA:TPU reaches the same
    structure by fusing the reductions into the conv epilogues, so this
    op is perf-neutral and kept for the explicit structure + collective
    semantics (BASELINE.md "fp32 kernel-level attack").

    Returns ``(z, batch_mean, unbiased_var)``; the running-stats blend
    happens outside in plain JAX so its (normally zero) cotangents stay
    differentiable — the bwd folds them in as the exact dμ/dσ² terms.
    """
    z, mean, unbiased, _ = _bn_relu_fwd_impl(eps, axis, x, scale, bias)
    return z, mean, unbiased


def _bn_relu_fwd(eps, axis, grad_axis, x, scale, bias):
    z, mean, unbiased, res = _bn_relu_fwd_impl(eps, axis, x, scale, bias)
    return (z, mean, unbiased), (*res, scale, bias)


def _bn_relu_bwd(eps, axis, grad_axis, res, cts):
    x, mean, inv, scale, bias = res
    ct_z, ct_mean, ct_unb = cts
    xf = x.astype(jnp.float32)
    n = float(xf.shape[0] * xf.shape[1] * xf.shape[2])
    count = n if axis is None else n * lax.axis_size(axis)
    xhat = (xf - mean) * inv
    # ReLU mask recomputed from x — identical expression to the forward's
    # ŷ, so the mask is bit-consistent and z is never read here.
    dy = jnp.where(xhat * scale + bias > 0.0,
                   ct_z.astype(jnp.float32), 0.0)
    dbeta = dy.sum(axis=(0, 1, 2))
    dgamma = (dy * xhat).sum(axis=(0, 1, 2))
    # Two distinct reductions share these sums — keep them apart:
    # 1. dx's mean-subtraction terms need the sums over the STATISTICS
    #    batch: local for per-shard BN, psum'd over ``axis`` for sync-BN
    #    (each shard's dx then carries the cross-shard terms the stats
    #    psum's transpose would have produced).
    # 2. The RETURNED dγ/dβ are the cotangents of the local objective —
    #    psum'd over ``grad_axis`` only under a replicated-params core
    #    (the DDP all-reduce); the ZeRO local-grads core leaves grad_axis
    #    unset and does its own psum_scatter later, sync-BN or not (γ/β
    #    reach the local loss only through the local normalize, so their
    #    local cotangents contain no cross-shard terms even under sync).
    sbeta, sgamma = dbeta, dgamma
    if axis is not None:
        assert grad_axis is None or grad_axis == axis, (grad_axis, axis)
        sbeta = lax.psum(dbeta, axis)
        sgamma = lax.psum(dgamma, axis)
    # dx through the normalisation (biased-var form), plus the exact terms
    # for the running-stats outputs' cotangents (zeros in training — the
    # stats are aux outputs — so XLA folds them away).
    dvar = ct_unb * (count / max(count - 1.0, 1.0))
    dx = (inv * (dy * scale - (sbeta * scale) / count
                 - xhat * ((sgamma * scale) / count))
          + ct_mean / count + dvar * (2.0 / count) * (xf - mean))
    if grad_axis is not None:
        dbeta = sbeta if axis is not None else lax.psum(dbeta, grad_axis)
        dgamma = sgamma if axis is not None else lax.psum(dgamma, grad_axis)
    return dx.astype(x.dtype), dgamma, dbeta


_bn_relu_train.defvjp(_bn_relu_fwd, _bn_relu_bwd)


def bn_relu(x: jax.Array, scale: jax.Array, bias: jax.Array,
            state: BatchNormState, *, train: bool,
            momentum: float = 0.1, eps: float = 1e-5,
            ) -> Tuple[jax.Array, BatchNormState]:
    """``relu(batch_norm(x))`` as one op — semantics identical to
    :func:`batch_norm` followed by ``jax.nn.relu`` (torch defaults, same
    sync-BN context), with the hand-written backward of
    :func:`_bn_relu_train` (reads only ``(x, dz)`` — the 5-activation-pass
    minimum).  Measured step-level perf is EQUAL to the autodiff
    composition on v5e (XLA:TPU already fuses the BN reductions into conv
    epilogues and reaches the same pass structure — the HLO-evidenced
    negative result in BASELINE.md); the op is kept because it makes that
    traffic structure explicit and pins the collective semantics
    (bn_grad_axis) the ZeRO/replicated cores rely on.  Use for
    conv→BN→ReLU chains; use :func:`batch_norm` where no ReLU immediately
    follows (e.g. ResNet shortcut branches)."""
    if not train:
        # Delegate so eval numerics stay BIT-identical to the composition
        # (tests/test_bn_relu.py::test_eval_mode_bit_identical).
        y, _ = batch_norm(x, scale, bias, state, train=False,
                          momentum=momentum, eps=eps)
        return jax.nn.relu(y), state
    z, batch_mean, unbiased = _bn_relu_train(eps, _bn_sync_axis(),
                                             _bn_grad_axis(), x, scale, bias)
    return z, _blend_running_stats(state, batch_mean, unbiased, momentum)


def dropout(key: jax.Array, x: jax.Array, rate: float,
            train: bool) -> jax.Array:
    """Inverted dropout (torch convention) — DeepNN uses rate 0.1
    (singlegpu.py:36)."""
    if not train or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))
