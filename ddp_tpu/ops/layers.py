"""Low-level NN ops in the TPU-native layout (NHWC activations, HWIO kernels).

These are the building blocks for the models in ``ddp_tpu.models``; each op's
numerics are tested for parity against the equivalent torch CPU op
(tests/test_ops.py).  The reference gets these from torch.nn / cuDNN
(singlegpu.py:64-73); on TPU we express them so XLA can tile the convolutions
onto the MXU and fuse the elementwise BN/ReLU chains into them.
"""
from __future__ import annotations

import contextlib
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# NHWC / HWIO are the layouts XLA:TPU convolutions are natively tiled for.
CONV_DIMS = ("NHWC", "HWIO", "NHWC")

# Ambient mesh axis over which batch_norm synchronises its batch statistics
# (the TPU-native SyncBatchNorm the reference keeps commented out,
# multigpu.py:127).  A trace-time context rather than a per-call argument so
# model code stays signature-identical whether BN is synced or not; the
# step builders (train/step.py) set it from their sync_bn flag.
_BN_SYNC_AXIS: Optional[str] = None


@contextlib.contextmanager
def bn_sync_axis(axis_name: Optional[str]):
    """Within this context, training-mode batch_norm psums its statistics
    over ``axis_name`` (must be inside shard_map over that axis)."""
    global _BN_SYNC_AXIS
    prev, _BN_SYNC_AXIS = _BN_SYNC_AXIS, axis_name
    try:
        yield
    finally:
        _BN_SYNC_AXIS = prev


def conv2d(x: jax.Array, kernel: jax.Array, bias: Optional[jax.Array] = None,
           stride: int = 1, padding: int = 1) -> jax.Array:
    """3x3-style 2-D convolution. x: [N,H,W,C_in], kernel: [kh,kw,C_in,C_out]."""
    y = lax.conv_general_dilated(
        x, kernel,
        window_strides=(stride, stride),
        padding=((padding, padding), (padding, padding)),
        dimension_numbers=CONV_DIMS,
    )
    if bias is not None:
        y = y + bias
    return y


def max_pool(x: jax.Array, window: int = 2, stride: int = 2,
             padding: int = 0) -> jax.Array:
    """MaxPool2d(window, stride, padding) — reference singlegpu.py:70 uses
    (2, 2, 0); ResNet-18's stem uses (3, 2, 1)."""
    return lax.reduce_window(
        x, -jnp.inf, lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding=((0, 0), (padding, padding), (padding, padding), (0, 0)),
    )


def linear(x: jax.Array, weight: jax.Array,
           bias: Optional[jax.Array] = None) -> jax.Array:
    """x @ weight (+ bias). weight: [in, out]."""
    y = x @ weight
    if bias is not None:
        y = y + bias
    return y


def global_avg_pool(x: jax.Array) -> jax.Array:
    """[N,H,W,C] -> [N,C] mean over spatial dims (reference x.mean([2,3]),
    singlegpu.py:79)."""
    return x.mean(axis=(1, 2))


class BatchNormState(NamedTuple):
    """Running statistics (the reference's BN buffers)."""
    mean: jax.Array
    var: jax.Array


def batch_norm(x: jax.Array, scale: jax.Array, bias: jax.Array,
               state: BatchNormState, *, train: bool,
               momentum: float = 0.1, eps: float = 1e-5,
               ) -> Tuple[jax.Array, BatchNormState]:
    """BatchNorm2d with exact torch semantics.

    Training normalises with the *biased* batch variance but updates the
    running variance with the *unbiased* one (Bessel-corrected), momentum 0.1,
    eps 1e-5 — the torch defaults the reference relies on (singlegpu.py:65).
    Under data parallelism the batch statistics are per-replica: the reference
    deliberately leaves SyncBatchNorm commented out (multigpu.py:127), and
    shard_map gives the same per-shard semantics for free.

    Statistics are accumulated in fp32 even when ``x`` is bf16 so the
    mixed-precision path stays stable.

    The variance is computed one-pass as ``E[x^2] - E[x]^2`` so XLA fuses
    both channel reductions into a single read of the activation — BN is
    bandwidth-bound on TPU and the two-pass ``mean then var`` formulation
    reads the conv output twice (measured: one-pass is +13% whole-train-step
    throughput for VGG/512 on v5e).  The cancellation error of the one-pass
    form is benign here: conv-of-normalized activations keeps
    ``E[x^2]/var`` within a few orders of magnitude, and the fp32
    accumulation leaves ~1e-6 relative error, well inside the torch-parity
    tolerances (tests/test_ops.py, tests/test_train_step.py golden trace).
    """
    if train:
        xf = x.astype(jnp.float32)
        n = jnp.asarray(x.shape[0] * x.shape[1] * x.shape[2], jnp.float32)
        if _BN_SYNC_AXIS is None:
            batch_mean = xf.mean(axis=(0, 1, 2))
            batch_var = jnp.maximum(  # one-pass biased var, to normalise
                (xf * xf).mean(axis=(0, 1, 2)) - batch_mean * batch_mean,
                0.0)
        else:
            # SyncBatchNorm: statistics over the GLOBAL batch (equal shard
            # sizes inside shard_map, so means of per-shard means are
            # exact).  The variance here is the *centered* two-pass form,
            # not the one-pass E[x^2]-E[x]^2 used above: under cancellation
            # (mean^2 >> var) the one-pass form amplifies the psum's
            # rounding ~10x more than centering does (verified against an
            # f64 reference).  Sync-BN is opt-in, so the extra read of x is
            # an acceptable price for the better-conditioned statistics —
            # the same choice torch's SyncBatchNorm makes.
            r = lax.psum(jnp.ones((), jnp.float32), _BN_SYNC_AXIS)
            batch_mean = lax.psum(xf.mean(axis=(0, 1, 2)),
                                  _BN_SYNC_AXIS) / r
            d = xf - batch_mean
            batch_var = lax.psum((d * d).mean(axis=(0, 1, 2)),
                                 _BN_SYNC_AXIS) / r
            n = n * r
        unbiased = batch_var * (n / jnp.maximum(n - 1.0, 1.0))
        new_state = BatchNormState(
            mean=(1.0 - momentum) * state.mean + momentum * batch_mean,
            var=(1.0 - momentum) * state.var + momentum * unbiased,
        )
        mean, var = batch_mean, batch_var
    else:
        new_state = state
        mean, var = state.mean, state.var
    inv = lax.rsqrt(var + eps) * scale
    y = (x - mean.astype(x.dtype)) * inv.astype(x.dtype) + bias.astype(x.dtype)
    return y, new_state


def dropout(key: jax.Array, x: jax.Array, rate: float,
            train: bool) -> jax.Array:
    """Inverted dropout (torch convention) — DeepNN uses rate 0.1
    (singlegpu.py:36)."""
    if not train or rate == 0.0:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))
