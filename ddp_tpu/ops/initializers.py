"""Parameter initialisers matching PyTorch layer defaults.

Loss-curve parity with the reference (SURVEY.md section 7, "hard parts" #1)
requires the same init *distributions* as ``nn.Conv2d`` / ``nn.Linear`` /
``nn.BatchNorm2d`` defaults, which the reference relies on implicitly
(singlegpu.py:64, 73 construct the layers with no explicit init).

PyTorch defaults:
- Conv2d / Linear weight: ``kaiming_uniform_(a=sqrt(5))``.  With
  gain = sqrt(2 / (1 + a^2)) = sqrt(1/3) and bound = sqrt(3) * gain /
  sqrt(fan_in), this reduces exactly to U(-1/sqrt(fan_in), +1/sqrt(fan_in)).
- Conv2d / Linear bias: U(-1/sqrt(fan_in), +1/sqrt(fan_in)).
- BatchNorm2d: weight (gamma) = 1, bias (beta) = 0, running_mean = 0,
  running_var = 1.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def torch_default_uniform(key: jax.Array, shape, fan_in: int,
                          dtype=jnp.float32) -> jax.Array:
    """U(-1/sqrt(fan_in), +1/sqrt(fan_in)) — PyTorch conv/linear default."""
    bound = 1.0 / math.sqrt(fan_in)
    return jax.random.uniform(key, shape, dtype, minval=-bound, maxval=bound)


def conv_kernel(key: jax.Array, kh: int, kw: int, in_ch: int, out_ch: int,
                dtype=jnp.float32) -> jax.Array:
    """HWIO conv kernel with the PyTorch Conv2d default distribution.

    PyTorch stores OIHW; we store HWIO (the native layout for XLA:TPU's
    NHWC convolutions).  fan_in = in_ch * kh * kw in both layouts.
    """
    return torch_default_uniform(key, (kh, kw, in_ch, out_ch),
                                 fan_in=in_ch * kh * kw, dtype=dtype)


def linear_weight(key: jax.Array, in_features: int, out_features: int,
                  dtype=jnp.float32) -> jax.Array:
    """[in, out] linear weight (JAX convention; torch stores [out, in])."""
    return torch_default_uniform(key, (in_features, out_features),
                                 fan_in=in_features, dtype=dtype)


def linear_bias(key: jax.Array, in_features: int, out_features: int,
                dtype=jnp.float32) -> jax.Array:
    return torch_default_uniform(key, (out_features,), fan_in=in_features,
                                 dtype=dtype)


def conv_bias(key: jax.Array, kh: int, kw: int, in_ch: int, out_ch: int,
              dtype=jnp.float32) -> jax.Array:
    return torch_default_uniform(key, (out_ch,), fan_in=in_ch * kh * kw,
                                 dtype=dtype)


def batch_norm_params(num_features: int, dtype=jnp.float32):
    """(scale, bias) = (1, 0) — BatchNorm2d affine defaults."""
    return jnp.ones((num_features,), dtype), jnp.zeros((num_features,), dtype)


def batch_norm_stats(num_features: int, dtype=jnp.float32):
    """(running_mean, running_var) = (0, 1)."""
    return jnp.zeros((num_features,), dtype), jnp.ones((num_features,), dtype)
