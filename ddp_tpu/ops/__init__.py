from .layers import (BatchNormState, batch_norm, bn_relu, conv2d, dropout,
                     global_avg_pool, linear, max_pool)
from .losses import cross_entropy_per_example, cross_entropy_sum_count

__all__ = [
    "BatchNormState", "batch_norm", "bn_relu", "conv2d", "dropout",
    "global_avg_pool", "linear", "max_pool", "cross_entropy_per_example",
    "cross_entropy_sum_count",
]
