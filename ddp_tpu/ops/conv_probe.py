"""Per-layer convolution emitter probe (round 3).

BASELINE.md's fp32 roofline attributes the step's residual gap to "conv
emitter efficiency at CIFAR-scale spatial shapes" — this tool makes that
claim *measurable per shape*: it times every distinct VGG conv layer
(forward, and backward as one dgrad+wgrad program) in isolation on the
real chip and reports achieved TFLOP/s, so the inefficiency localizes to
specific (H, C_in, C_out) combinations instead of remaining a step-level
aggregate.  Two rows per shape: pure forward, and the full trained cost
(``train(fwd+dgrad+wgrad)`` — ``jax.vjp`` executes the primal inside the
chain, so that window's FLOP multiplier is 3).

Method: each measurement jits an UNROLLED chain of N dependency-linked
convs (dependency through the tiny weight, so the activation's layout
conversion hoists out of the chain exactly as it amortizes in the real
step) and takes the best-of-repeats wall time at two chain lengths; the
reported per-call time is the MARGINAL (t_long - t_short)/(N_long -
N_short).  The differencing is essential on this box: a single dispatch
+ host value read through the axon tunnel carries ~70 ms of fixed RTT,
which at any single chain length would swamp the sub-millisecond true
cost (measured: chain totals 78/76/91 ms at N=10/20/60 for a conv whose
marginal cost is 0.38 ms).  A ``lax.scan`` chain was tried and rejected:
it adds ~2 ms/iteration on this backend (the while-loop drains the
pipeline at each iteration boundary; the unrolled chain overlaps each
conv with the previous mean-reduction).

Usage: ``python -m ddp_tpu.ops.conv_probe [--batch 512] [--bf16]``
— prints one JSON line per (shape, direction).
"""
from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from .layers import conv2d

# (H=W, C_in, C_out, reps) for each conv in VGG.ARCH (reference
# singlegpu.py:48) at the spatial size it actually sees; 'reps' folds the
# two identical 4x4 512->512 layers into one row.
VGG_CONV_SHAPES = [
    (32, 3, 64, 1),
    (32, 64, 128, 1),
    (16, 128, 256, 1),
    (16, 256, 256, 1),
    (8, 256, 512, 1),
    (8, 512, 512, 1),
    (4, 512, 512, 2),
]

N_SHORT, N_LONG = 10, 50


def conv_flops(n: int, h: int, cin: int, cout: int) -> float:
    """MAC-pair FLOPs of a SAME-padded 3x3 stride-1 conv (interior
    approximation, matching BASELINE.md's roofline accounting)."""
    return 2.0 * n * h * h * cout * 9 * cin


def _fwd_chain(n: int, conv):
    def win(x, w):
        acc = jnp.zeros((), x.dtype)
        for _ in range(n):
            acc = jnp.mean(conv(x, w + acc * 1e-30))
        return acc

    return jax.jit(win)


def _train_chain(n: int, conv):
    # NOTE: jax.vjp executes the PRIMAL forward inside the chain, so this
    # window times fwd+dgrad+wgrad — the full per-layer trained cost —
    # and its FLOP multiplier is 3, not 2.  (An earlier revision labeled
    # this row "bwd" with fmult=2.0, inflating bwd ms and deflating bwd
    # TFLOP/s by the forward's share.)
    def win(x, w):
        acc = jnp.zeros((), x.dtype)
        for _ in range(n):
            y, vjp = jax.vjp(conv, x, w + acc * 1e-30)
            dx, dw = vjp(y)
            acc = jnp.mean(dx) + jnp.mean(dw)
        return acc

    return jax.jit(win)


# Tunnel-jitter threshold: a marginal below 0.1 ms/call cannot be
# distinguished from link noise at these chain lengths (shared by the
# pool probe so the two methodologies cannot drift).
NOISE_S_PER_CALL = 1e-4


def best_of(fn, args, repeats: int) -> float:
    """Best-of-``repeats`` wall time of ``fn(*args)``, synced via a host
    value read (the one sync that cannot return early through remote
    device tunnels).  The shared timing core of every probe in this
    package."""
    float(fn(*args))  # compile + warm
    dt = float("inf")
    for _ in range(repeats):
        t0 = time.perf_counter()
        float(fn(*args))
        dt = min(dt, time.perf_counter() - t0)
    return dt


def probe(batch: int = 512, repeats: int = 6, dtype=jnp.float32,
          conv=conv2d, shapes=None) -> list:
    """Marginal per-call ms and achieved TFLOP/s for each VGG conv shape.

    ``conv`` is pluggable (signature ``conv(x, w) -> y``) so alternative
    implementations (e.g. Pallas kernels) can be measured under the
    identical harness for an apples-to-apples comparison (the candidates
    live in :mod:`~ddp_tpu.ops.conv_candidates`); ``shapes`` restricts the
    sweep (default: every VGG conv shape).  The default ``repeats=6``
    matches the recorded BASELINE.md methodology.
    """
    records = []
    for h, cin, cout, reps in (VGG_CONV_SHAPES if shapes is None
                               else shapes):
        x = jax.random.normal(jax.random.key(0), (batch, h, h, cin), dtype)
        # .astype: the numpy scalar is strongly typed, so the bare product
        # would silently promote a bfloat16 w back to float32.
        w = (jax.random.normal(jax.random.key(1), (3, 3, cin, cout), dtype)
             * np.sqrt(2.0 / (9 * cin))).astype(dtype)
        for name, chain, fmult in (("fwd", _fwd_chain, 1.0),
                                   ("train(fwd+dgrad+wgrad)", _train_chain,
                                    3.0)):
            t_s = best_of(chain(N_SHORT, conv), (x, w), repeats)
            t_l = best_of(chain(N_LONG, conv), (x, w), repeats)
            per_call = max((t_l - t_s) / (N_LONG - N_SHORT), 1e-9)
            fl = conv_flops(batch, h, cin, cout) * fmult
            # Tunnel jitter can make t_long <= t_short when the true
            # marginal cost is tiny; flag those rows instead of printing
            # an absurd TFLOP/s as fact.
            noise_limited = (t_l - t_s) < NOISE_S_PER_CALL * (N_LONG
                                                             - N_SHORT)
            rec = {
                "shape": f"{h}x{h} {cin}->{cout}" + (f" x{reps}" if reps > 1
                                                     else ""),
                "dir": name,
                "marginal_ms_per_call": round(per_call * 1e3, 3),
                "tflops": (None if noise_limited
                           else round(fl / per_call / 1e12, 1)),
                "noise_limited": noise_limited,
                "reps_in_vgg": reps,
            }
            records.append(rec)
            print(json.dumps(rec), flush=True)
    return records


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--batch", type=int, default=512)
    p.add_argument("--repeats", type=int, default=6)
    p.add_argument("--bf16", action="store_true")
    args = p.parse_args()
    recs = probe(args.batch, args.repeats,
                 jnp.bfloat16 if args.bf16 else jnp.float32)
    # The train rows already contain the forward (jax.vjp runs the
    # primal), so summing them alone gives the per-step trained total.
    # Caveats carried on the summary line: clamped noise-limited rows
    # contribute ~0 (the sum is a lower bound when any are flagged), and
    # every train row includes dgrad — for the FIRST layer the real step
    # never computes the input gradient, so the sum slightly overstates
    # the in-step trained total by conv1's dgrad share.
    train_rows = [r for r in recs if r["dir"].startswith("train")]
    total = sum(r["marginal_ms_per_call"] * r["reps_in_vgg"]
                for r in train_rows)
    print(json.dumps({
        "sum_marginal_train_ms_per_step": round(total, 2),
        "noise_limited_train_rows": sum(r["noise_limited"]
                                        for r in train_rows),
    }), flush=True)


if __name__ == "__main__":
    main()
