"""Batched row gather — the resident data path's hot op, as a Pallas kernel.

``table[idx]`` for a [M, ...] uint8 dataset table is the core of the
HBM-resident input path (train/epoch.py): every step gathers its batch by
index from the resident array.  XLA:TPU lowers that advanced-indexing
gather to a slow generic gather (~4.7 ms for 512 rows of 3 KB on v5e —
9 us/row, latency-bound); this kernel instead drives one DMA per row
through the Pallas pipeline with scalar-prefetched indices (the index_map
reads ``idx`` before the body runs, so block fetches double-buffer), which
measures ~1.1 ms for the same gather — ~4x faster, and ~20% off the whole
resident train step.

Non-TPU backends (the CPU test mesh) use the plain XLA gather — identical
values, so every numerical test covers both paths' semantics.  Override
with DDP_TPU_PALLAS=0 to force the XLA path on TPU.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

_LANE = 128


def _use_pallas() -> bool:
    return (jax.default_backend() == "tpu"
            and os.environ.get("DDP_TPU_PALLAS", "1") != "0")


def _copy_kernel(idx_ref, in_ref, out_ref):
    del idx_ref  # consumed by the index_map, not the body
    out_ref[...] = in_ref[...]


def _pallas_row_gather(table2d: jax.Array, idx: jax.Array) -> jax.Array:
    """[M, D] (D % 128 == 0), int32 [N] -> [N, D] == table2d[idx]."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, d = table2d.shape
    n = idx.shape[0]
    sub = d // _LANE
    t3 = table2d.reshape(m, sub, _LANE)
    # Block (1, sub, LANE): the last two dims equal the array dims, which
    # satisfies the Mosaic block-shape constraint for any D % 128 == 0.
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n,),
        in_specs=[pl.BlockSpec((1, sub, _LANE),
                               lambda i, idx_ref: (idx_ref[i], 0, 0))],
        out_specs=pl.BlockSpec((1, sub, _LANE),
                               lambda i, idx_ref: (i, 0, 0)),
    )
    # Inside shard_map (check_vma=True) the output's varying-axes type must
    # be declared: the gathered rows vary wherever the indices or the table
    # do (the idx matrix is sharded on ``data``; the table is replicated).
    try:
        vma = frozenset(jax.typeof(idx).vma) | frozenset(
            jax.typeof(table2d).vma)
    except AttributeError:
        vma = None
    out_shape = (jax.ShapeDtypeStruct((n, sub, _LANE), table2d.dtype,
                                      vma=vma)
                 if vma is not None
                 else jax.ShapeDtypeStruct((n, sub, _LANE), table2d.dtype))
    out = pl.pallas_call(
        _copy_kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
    )(idx, t3)
    return out.reshape(n, d)


def gather_rows(table: jax.Array, idx: jax.Array) -> jax.Array:
    """``table[idx]`` along axis 0, via the Pallas DMA kernel when the row
    byte-count allows (TPU, row size a multiple of 128 elements), else the
    plain XLA gather.  Values are identical either way."""
    n = idx.shape[0]
    row_shape = table.shape[1:]
    d = 1
    for s in row_shape:
        d *= s
    if _use_pallas() and d % _LANE == 0:
        # Clamp like XLA's gather does: an out-of-range block index in the
        # Pallas index_map would be undefined behaviour (OOB DMA), not the
        # clamped read the fallback path gives.
        idx = jnp.clip(idx.astype(jnp.int32), 0, table.shape[0] - 1)
        flat = _pallas_row_gather(table.reshape(table.shape[0], d), idx)
        return flat.reshape((n,) + row_shape)
    return table[idx]
