"""Installed console entry points (``pip install .`` exposes the two
reference-shaped commands without needing the repo-root scripts).

``ddp-tpu-single`` == ``python singlegpu.py`` (mesh of 1,
singlegpu.py:254-263); ``ddp-tpu-multi`` == ``python multigpu.py``
(all devices, multigpu.py:254-263).  Identical argv surface.

Exit-status contract (ddp_tpu/resilience/; a restart wrapper keys off it):
  0    normal completion
  75   preempted (SIGTERM/SIGINT): a coordinated emergency checkpoint is
       on disk — relaunch the same command with ``--resume``
  124  watchdog expired (``--watchdog_secs``): no step/epoch progress —
       investigate before relaunching
  1    a real failure (multi-host: after the non-blocking distributed
       abort that unblocks peer processes)
"""
from __future__ import annotations

from .cli import build_parser, main


def main_single() -> None:
    main(build_parser("single-device distributed training job").parse_args(),
         num_devices=1)


def main_multi() -> None:
    main(build_parser("simple distributed training job").parse_args(),
         num_devices=None)
