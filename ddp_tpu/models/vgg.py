"""VGG-11-style CIFAR classifier — the reference's flagship model
(singlegpu.py:47-82; multigpu.py:36-71).

Same architecture string, layer naming (``conv0``/``bn0``/... from the
``add()`` helper, singlegpu.py:56-58), and parameter count (9,228,362 params,
35.20 MiB fp32 — SURVEY.md 2.4), expressed functionally over NHWC activations
so XLA:TPU tiles the convolutions onto the MXU.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import initializers as init_lib
from ..ops.layers import (BatchNormState, bn_relu, conv2d, global_avg_pool,
                          linear, max_pool)

NAME = "vgg"
NUM_CLASSES = 10
# Reference singlegpu.py:48
ARCH = [64, 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"]

Params = Dict[str, Any]
BatchStats = Dict[str, Any]


def init(key: jax.Array, dtype=jnp.float32) -> Tuple[Params, BatchStats]:
    """Build params + running stats with PyTorch-default init distributions."""
    backbone: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    in_ch = 3
    idx = 0
    for x in ARCH:
        if x == "M":
            continue
        key, wkey = jax.random.split(key)
        # conv3x3, padding 1, bias=False (singlegpu.py:64)
        backbone[f"conv{idx}"] = {
            "kernel": init_lib.conv_kernel(wkey, 3, 3, in_ch, x, dtype)
        }
        scale, bias = init_lib.batch_norm_params(x, dtype)
        backbone[f"bn{idx}"] = {"scale": scale, "bias": bias}
        mean, var = init_lib.batch_norm_stats(x, dtype)
        stats[f"bn{idx}"] = {"mean": mean, "var": var}
        in_ch = x
        idx += 1
    key, wkey, bkey = jax.random.split(key, 3)
    params: Params = {
        "backbone": backbone,
        "classifier": {
            "weight": init_lib.linear_weight(wkey, 512, NUM_CLASSES, dtype),
            "bias": init_lib.linear_bias(bkey, 512, NUM_CLASSES, dtype),
        },
    }
    return params, stats


def apply(params: Params, batch_stats: BatchStats, x: jax.Array, *,
          train: bool, rng: Optional[jax.Array] = None,
          compute_dtype: Optional[jnp.dtype] = None,
          ) -> Tuple[jax.Array, BatchStats]:
    """Forward pass: [N,32,32,3] -> [N,10] logits (reference singlegpu.py:75-82).

    ``compute_dtype=jnp.bfloat16`` gives the mixed-precision variant
    (BASELINE.json config #4): activations and matmul/conv inputs in bf16,
    BN statistics and the loss in fp32, params stored fp32.
    """
    del rng  # VGG has no dropout
    cd = compute_dtype or x.dtype
    x = x.astype(cd)
    new_stats: Dict[str, Any] = {}
    backbone = params["backbone"]
    in_idx = 0
    for a in ARCH:
        if a == "M":
            x = max_pool(x, 2, 2)
            continue
        conv = backbone[f"conv{in_idx}"]
        x = conv2d(x, conv["kernel"].astype(cd), stride=1, padding=1)
        bn = backbone[f"bn{in_idx}"]
        st = batch_stats[f"bn{in_idx}"]
        # Fused BN+ReLU: same torch semantics, hand-written VJP that reads
        # only (x, dz) in backward (ops/layers.py:bn_relu).
        x, new_st = bn_relu(
            x, bn["scale"], bn["bias"],
            BatchNormState(st["mean"], st["var"]), train=train)
        new_stats[f"bn{in_idx}"] = {"mean": new_st.mean, "var": new_st.var}
        in_idx += 1
    # [N,2,2,512] -> [N,512] -> [N,10]
    x = global_avg_pool(x)
    cls = params["classifier"]
    logits = linear(x, cls["weight"].astype(cd), cls["bias"].astype(cd))
    return logits.astype(jnp.float32), new_stats
