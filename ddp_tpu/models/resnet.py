"""ResNet-18, torchvision-compatible, for the "drop a real conv workload into
the Trainer" config (BASELINE.json config #3; the reference's model seam is
``load_train_objs``, multigpu.py:122-126).

Architecture and init follow torchvision.models.resnet18 exactly (7x7/2 stem +
3x3/2 maxpool, four stages of two BasicBlocks, kaiming-normal fan-out conv
init, BN gamma=1 beta=0, linear default init) so the implementation is
parity-testable against torch weights via
``utils.torch_interop.resnet18_from_torch_state_dict``.
"""
from __future__ import annotations

import math
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import initializers as init_lib
from ..ops.layers import (BatchNormState, batch_norm, bn_relu, conv2d,
                          global_avg_pool, linear, max_pool)

NAME = "resnet18"
NUM_CLASSES = 10
STAGES = [(64, 1), (128, 2), (256, 2), (512, 2)]  # (width, first-block stride)
BLOCKS_PER_STAGE = 2


def _kaiming_normal_fan_out(key, kh, kw, in_ch, out_ch, dtype=jnp.float32):
    """torchvision conv init: kaiming_normal_(mode='fan_out',
    nonlinearity='relu') -> N(0, sqrt(2/fan_out)), fan_out = out_ch*kh*kw."""
    std = math.sqrt(2.0 / (out_ch * kh * kw))
    return std * jax.random.normal(key, (kh, kw, in_ch, out_ch), dtype)


def _bn_init(ch, dtype=jnp.float32):
    scale, bias = init_lib.batch_norm_params(ch, dtype)
    mean, var = init_lib.batch_norm_stats(ch, dtype)
    return {"scale": scale, "bias": bias}, {"mean": mean, "var": var}


def init(key: jax.Array, dtype=jnp.float32) -> Tuple[Dict, Dict]:
    params: Dict[str, Any] = {}
    stats: Dict[str, Any] = {}
    key, k = jax.random.split(key)
    params["conv1"] = {"kernel": _kaiming_normal_fan_out(k, 7, 7, 3, 64, dtype)}
    params["bn1"], stats["bn1"] = _bn_init(64, dtype)

    in_ch = 64
    for si, (width, stride) in enumerate(STAGES, start=1):
        for bi in range(BLOCKS_PER_STAGE):
            blk_stride = stride if bi == 0 else 1
            name = f"layer{si}.block{bi}"
            blk: Dict[str, Any] = {}
            bstats: Dict[str, Any] = {}
            key, k1, k2, k3 = jax.random.split(key, 4)
            blk["conv1"] = {"kernel": _kaiming_normal_fan_out(
                k1, 3, 3, in_ch, width, dtype)}
            blk["bn1"], bstats["bn1"] = _bn_init(width, dtype)
            blk["conv2"] = {"kernel": _kaiming_normal_fan_out(
                k2, 3, 3, width, width, dtype)}
            blk["bn2"], bstats["bn2"] = _bn_init(width, dtype)
            if blk_stride != 1 or in_ch != width:
                blk["downsample"] = {"conv": {"kernel": _kaiming_normal_fan_out(
                    k3, 1, 1, in_ch, width, dtype)}}
                blk["downsample"]["bn"], bstats["downsample_bn"] = _bn_init(
                    width, dtype)
            params[name] = blk
            stats[name] = bstats
            in_ch = width

    key, wk, bk = jax.random.split(key, 3)
    params["fc"] = {
        "weight": init_lib.linear_weight(wk, 512, NUM_CLASSES, dtype),
        "bias": init_lib.linear_bias(bk, 512, NUM_CLASSES, dtype),
    }
    return params, stats


def _bn_apply(p, st, x, train, new_stats, key_out, relu=False):
    """BN (+ fused ReLU where one immediately follows — bn1 spots; the
    bn2/shortcut outputs feed the residual add first, so they stay bare)."""
    op = bn_relu if relu else batch_norm
    y, new_st = op(x, p["scale"], p["bias"],
                   BatchNormState(st["mean"], st["var"]), train=train)
    new_stats[key_out] = {"mean": new_st.mean, "var": new_st.var}
    return y


def apply(params: Dict, batch_stats: Dict, x: jax.Array, *, train: bool,
          rng: Optional[jax.Array] = None,
          compute_dtype: Optional[jnp.dtype] = None,
          ) -> Tuple[jax.Array, Dict]:
    del rng
    cd = compute_dtype or x.dtype
    x = x.astype(cd)
    new_stats: Dict[str, Any] = {}

    x = conv2d(x, params["conv1"]["kernel"].astype(cd), stride=2, padding=3)
    x = _bn_apply(params["bn1"], batch_stats["bn1"], x, train, new_stats,
                  "bn1", relu=True)
    x = max_pool(x, window=3, stride=2, padding=1)

    in_ch = 64
    for si, (width, stride) in enumerate(STAGES, start=1):
        for bi in range(BLOCKS_PER_STAGE):
            blk_stride = stride if bi == 0 else 1
            name = f"layer{si}.block{bi}"
            blk, bst = params[name], batch_stats[name]
            ns: Dict[str, Any] = {}
            identity = x
            y = conv2d(x, blk["conv1"]["kernel"].astype(cd),
                       stride=blk_stride, padding=1)
            y = _bn_apply(blk["bn1"], bst["bn1"], y, train, ns, "bn1",
                          relu=True)
            y = conv2d(y, blk["conv2"]["kernel"].astype(cd),
                       stride=1, padding=1)
            y = _bn_apply(blk["bn2"], bst["bn2"], y, train, ns, "bn2")
            if "downsample" in blk:
                identity = conv2d(x, blk["downsample"]["conv"]["kernel"]
                                  .astype(cd), stride=blk_stride, padding=0)
                identity = _bn_apply(blk["downsample"]["bn"],
                                     bst["downsample_bn"], identity, train,
                                     ns, "downsample_bn")
            x = jax.nn.relu(y + identity)
            new_stats[name] = ns
            in_ch = width

    x = global_avg_pool(x)
    logits = linear(x, params["fc"]["weight"].astype(cd),
                    params["fc"]["bias"].astype(cd))
    return logits.astype(jnp.float32), new_stats
