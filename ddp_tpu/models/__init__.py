"""Model registry — the TPU-native analogue of the reference's
``load_train_objs`` model seam (multigpu.py:122-126), which makes the Trainer
model-agnostic."""
from __future__ import annotations

from typing import Any, Callable, Dict, NamedTuple, Optional, Tuple

import jax


class ModelDef(NamedTuple):
    name: str
    init: Callable[..., Tuple[Dict, Dict]]
    apply: Callable[..., Tuple[jax.Array, Dict]]


def get_model(name: str) -> ModelDef:
    if name == "vgg":
        from . import vgg
        return ModelDef("vgg", vgg.init, vgg.apply)
    if name == "deepnn":
        from . import deepnn
        return ModelDef("deepnn", deepnn.init, deepnn.apply)
    if name == "resnet18":
        from . import resnet
        return ModelDef("resnet18", resnet.init, resnet.apply)
    if name == "transformer":
        from . import transformer
        return ModelDef("transformer", transformer.init, transformer.apply)
    if name == "tinylm":
        from . import transformer
        return ModelDef("tinylm", transformer.lm_init, transformer.lm_apply)
    raise ValueError(f"unknown model {name!r}; available: vgg, deepnn, "
                     "resnet18, transformer, tinylm")
