"""DeepNN — plain CNN from the reference (singlegpu.py:18-44).

Dead code there (defined but never instantiated — SURVEY.md 2.5), implemented
here anyway as part of the declared surface and as a second numerics fixture.
1,186,986 params.

Layout note: torch flattens NCHW ([N,32,8,8] -> channel-major 2048); we flatten
NHWC ([N,8,8,32] -> spatial-major 2048).  ``utils.torch_interop`` permutes the
first classifier weight accordingly, so forward numerics still match torch
exactly.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import initializers as init_lib
from ..ops.layers import conv2d, dropout, linear, max_pool

NAME = "deepnn"
NUM_CLASSES = 10
DROPOUT_RATE = 0.1  # singlegpu.py:36

# (in_ch, out_ch) of the four 3x3 convs; 'M' = maxpool2 (singlegpu.py:21-32)
_FEATURES = [(3, 128), (128, 64), "M", (64, 64), (64, 32), "M"]

# Tensor-parallel recipe (parallel/tp/plan.py): back-to-back blocks pair
# column-then-row so the column-sharded activation feeds the row layer
# directly and only the row output needs a psum over ``model``.  ONE
# source of truth: the planner derives the per-leaf PartitionSpecs from
# this mapping, and apply() below consults it for which convs/linears run
# row-parallel under ``tp_axis`` — they cannot drift.
TP_RECIPE = {
    "features/conv0": "column",
    "features/conv1": "row",
    "features/conv2": "column",
    "features/conv3": "row",
    "classifier/linear0": "column",
    "classifier/linear1": "row",
}

# Activation-width barriers for the auto-plan search
# (parallel/tp/autoplan.py): the activation LEAVING each named layer must
# be full-width.  conv3 feeds the NHWC flatten ([N,8,8,32] -> [N,2048]); a
# channel-sharded input would flatten to an interleaved subset of the 2048
# vector that no contiguous row-parallel weight shard matches.
TP_BARRIERS = ("features/conv3",)

# The layer consuming the NETWORK INPUT.  Declared (not inferred) because
# the plan's expected-collectives accounting needs it: a train step takes
# gradients w.r.t. params only, so the stem's column-style input-gradient
# psum is dead code and XLA-free jaxpr tracing already omits it
# (parallel/tp/plan.py:expected_collectives, ddp_tpu/analysis/).
TP_STEM = "features/conv0"

# Pipeline-parallel block list (parallel/pp/partition.py): the model as an
# ordered sequence of cut-able units, one per TP_RECIPE layer, each block
# owning the layer plus its trailing elementwise/pool/reshape ops so a cut
# between any two blocks is a clean activation handoff.  Block names ARE
# the recipe paths — the pp planner prices them with the same
# layer_forward_costs table the tp auto-planner uses, and the param
# subtree of block "a/b" is params["a"]["b"] (one source of truth for
# splitting state by stage).
PP_BLOCKS = (
    "features/conv0",     # conv + relu
    "features/conv1",     # conv + relu + maxpool
    "features/conv2",     # conv + relu
    "features/conv3",     # conv + relu + maxpool + NHWC flatten
    "classifier/linear0",  # linear + relu + dropout(train)
    "classifier/linear1",  # linear + float32 logits cast
)

# Blocks whose OUTPUT activation is model-sharded under the TP recipe
# (column layers): a pipeline cut after one would hand a sharded
# activation across stages, so the pp planner rejects those cut points
# when m > 1 (parallel/pp/partition.py).
PP_SHARDED_OUT = tuple(p for p, s in TP_RECIPE.items() if s == "column")

Params = Dict[str, Any]


def init(key: jax.Array, dtype=jnp.float32) -> Tuple[Params, Dict]:
    features: Dict[str, Any] = {}
    idx = 0
    for spec in _FEATURES:
        if spec == "M":
            continue
        in_ch, out_ch = spec
        key, wkey, bkey = jax.random.split(key, 3)
        features[f"conv{idx}"] = {
            "kernel": init_lib.conv_kernel(wkey, 3, 3, in_ch, out_ch, dtype),
            "bias": init_lib.conv_bias(bkey, 3, 3, in_ch, out_ch, dtype),
        }
        idx += 1
    key, w0, b0, w1, b1 = jax.random.split(key, 5)
    params: Params = {
        "features": features,
        "classifier": {
            "linear0": {"weight": init_lib.linear_weight(w0, 2048, 512, dtype),
                        "bias": init_lib.linear_bias(b0, 2048, 512, dtype)},
            "linear1": {"weight": init_lib.linear_weight(w1, 512, NUM_CLASSES,
                                                         dtype),
                        "bias": init_lib.linear_bias(b1, 512, NUM_CLASSES,
                                                     dtype)},
        },
    }
    return params, {}  # no batch-norm -> no running stats


def apply(params: Params, batch_stats: Dict, x: jax.Array, *, train: bool,
          rng: Optional[jax.Array] = None,
          compute_dtype: Optional[jnp.dtype] = None,
          tp_axis: Optional[str] = None,
          tp_recipe: Optional[Dict[str, str]] = None,
          ) -> Tuple[jax.Array, Dict]:
    """Forward pass.  With ``tp_axis`` set (inside a shard_map over that
    mesh axis, params sharded per the recipe), the row-parallel members run
    through the tp wrappers — partial sums psum'd over ``tp_axis``, bias
    after the reduction — and dropout draws the full-width mask so its
    bits match the unsharded run (parallel/tp/layers.py).  Column-parallel
    members are locally byte-identical to the unsharded ops, so they only
    branch for the backward's ``column_input`` psum.

    ``tp_recipe`` overrides the module's TP_RECIPE with an explicit
    per-layer style mapping (the auto-plan path,
    parallel/tp/autoplan.py); layers it omits — or maps to
    ``"replicated"`` — run the plain unsharded ops even under ``tp_axis``
    (their params are replicated over ``model``, and every model shard on
    one data row computes the same activations from the same rng)."""
    return apply_blocks(params, batch_stats, x, blocks=(0, len(PP_BLOCKS)),
                        train=train, rng=rng, compute_dtype=compute_dtype,
                        tp_axis=tp_axis, tp_recipe=tp_recipe)


def apply_blocks(params: Params, batch_stats: Dict, x: jax.Array, *,
                 blocks: Tuple[int, int], train: bool,
                 rng: Optional[jax.Array] = None,
                 compute_dtype: Optional[jnp.dtype] = None,
                 tp_axis: Optional[str] = None,
                 tp_recipe: Optional[Dict[str, str]] = None,
                 ) -> Tuple[jax.Array, Dict]:
    """Run the contiguous PP_BLOCKS half-open range ``blocks=(lo, hi)`` —
    the pipeline-parallel per-stage forward (parallel/pp/schedule.py).
    ``x`` is the network input for ``lo == 0``, otherwise the activation
    handed over from the previous stage.  ``(0, len(PP_BLOCKS))`` IS the
    whole model: :func:`apply` delegates here, so the staged and unstaged
    paths cannot drift (and s=1 stays bit-identical by construction).

    ``params`` may be the full tree or any subtree that still contains
    the blocks in range (the pp planner hands each stage only its own
    leaves)."""
    del batch_stats
    lo, hi = blocks
    if not 0 <= lo < hi <= len(PP_BLOCKS):
        raise ValueError(
            f"blocks must be a non-empty range within "
            f"(0, {len(PP_BLOCKS)}), got {blocks!r}")
    recipe = TP_RECIPE if tp_recipe is None else tp_recipe
    if tp_axis is not None:
        from ..parallel.tp.layers import (column_conv2d, column_linear,
                                          row_conv2d, row_linear,
                                          sharded_dropout)
    def style(path):
        if tp_axis is None:
            return None
        return recipe.get(path, "replicated")
    cd = compute_dtype or x.dtype
    x = x.astype(cd)

    def conv_block(x, idx, pool):
        conv = params["features"][f"conv{idx}"]
        k, b = conv["kernel"].astype(cd), conv["bias"].astype(cd)
        s = style(f"features/conv{idx}")
        if s == "row":
            x = row_conv2d(x, k, b, tp_axis, stride=1, padding=1)
        elif s == "column":
            x = column_conv2d(x, k, b, tp_axis, stride=1, padding=1)
        else:
            x = conv2d(x, k, b, stride=1, padding=1)
        x = jax.nn.relu(x)
        return max_pool(x, 2, 2) if pool else x

    for name in PP_BLOCKS[lo:hi]:
        if name == "features/conv0":
            x = conv_block(x, 0, pool=False)
        elif name == "features/conv1":
            x = conv_block(x, 1, pool=True)
        elif name == "features/conv2":
            x = conv_block(x, 2, pool=False)
        elif name == "features/conv3":
            x = conv_block(x, 3, pool=True)
            x = x.reshape(x.shape[0], -1)  # [N,8,8,32] -> [N,2048] (NHWC)
        elif name == "classifier/linear0":
            l0 = params["classifier"]["linear0"]
            w0, b0 = l0["weight"].astype(cd), l0["bias"].astype(cd)
            s0 = style("classifier/linear0")
            if s0 == "column":
                x = column_linear(x, w0, b0, tp_axis)
            elif s0 == "row":
                x = row_linear(x, w0, b0, tp_axis)
            else:
                x = linear(x, w0, b0)
            x = jax.nn.relu(x)
            if train:
                if rng is None:
                    raise ValueError(
                        "DeepNN needs an rng for dropout in train mode")
                # The mask is always drawn at FULL width; the sharded form
                # only exists to slice it when the activation is linear0's
                # column shard.
                if s0 == "column":
                    x = sharded_dropout(rng, x, DROPOUT_RATE, train=True,
                                        axis_name=tp_axis)
                else:
                    x = dropout(rng, x, DROPOUT_RATE, train=True)
        elif name == "classifier/linear1":
            l1 = params["classifier"]["linear1"]
            w1, b1 = l1["weight"].astype(cd), l1["bias"].astype(cd)
            s1 = style("classifier/linear1")
            if s1 == "row":
                x = row_linear(x, w1, b1, tp_axis)
            elif s1 == "column":
                x = column_linear(x, w1, b1, tp_axis)
            else:
                x = linear(x, w1, b1)
            x = x.astype(jnp.float32)
    return x, {}
