"""DeepNN — plain CNN from the reference (singlegpu.py:18-44).

Dead code there (defined but never instantiated — SURVEY.md 2.5), implemented
here anyway as part of the declared surface and as a second numerics fixture.
1,186,986 params.

Layout note: torch flattens NCHW ([N,32,8,8] -> channel-major 2048); we flatten
NHWC ([N,8,8,32] -> spatial-major 2048).  ``utils.torch_interop`` permutes the
first classifier weight accordingly, so forward numerics still match torch
exactly.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import initializers as init_lib
from ..ops.layers import conv2d, dropout, linear, max_pool

NAME = "deepnn"
NUM_CLASSES = 10
DROPOUT_RATE = 0.1  # singlegpu.py:36

# (in_ch, out_ch) of the four 3x3 convs; 'M' = maxpool2 (singlegpu.py:21-32)
_FEATURES = [(3, 128), (128, 64), "M", (64, 64), (64, 32), "M"]

# Tensor-parallel recipe (parallel/tp/plan.py): back-to-back blocks pair
# column-then-row so the column-sharded activation feeds the row layer
# directly and only the row output needs a psum over ``model``.  ONE
# source of truth: the planner derives the per-leaf PartitionSpecs from
# this mapping, and apply() below consults it for which convs/linears run
# row-parallel under ``tp_axis`` — they cannot drift.
TP_RECIPE = {
    "features/conv0": "column",
    "features/conv1": "row",
    "features/conv2": "column",
    "features/conv3": "row",
    "classifier/linear0": "column",
    "classifier/linear1": "row",
}

# Activation-width barriers for the auto-plan search
# (parallel/tp/autoplan.py): the activation LEAVING each named layer must
# be full-width.  conv3 feeds the NHWC flatten ([N,8,8,32] -> [N,2048]); a
# channel-sharded input would flatten to an interleaved subset of the 2048
# vector that no contiguous row-parallel weight shard matches.
TP_BARRIERS = ("features/conv3",)

# The layer consuming the NETWORK INPUT.  Declared (not inferred) because
# the plan's expected-collectives accounting needs it: a train step takes
# gradients w.r.t. params only, so the stem's column-style input-gradient
# psum is dead code and XLA-free jaxpr tracing already omits it
# (parallel/tp/plan.py:expected_collectives, ddp_tpu/analysis/).
TP_STEM = "features/conv0"

Params = Dict[str, Any]


def init(key: jax.Array, dtype=jnp.float32) -> Tuple[Params, Dict]:
    features: Dict[str, Any] = {}
    idx = 0
    for spec in _FEATURES:
        if spec == "M":
            continue
        in_ch, out_ch = spec
        key, wkey, bkey = jax.random.split(key, 3)
        features[f"conv{idx}"] = {
            "kernel": init_lib.conv_kernel(wkey, 3, 3, in_ch, out_ch, dtype),
            "bias": init_lib.conv_bias(bkey, 3, 3, in_ch, out_ch, dtype),
        }
        idx += 1
    key, w0, b0, w1, b1 = jax.random.split(key, 5)
    params: Params = {
        "features": features,
        "classifier": {
            "linear0": {"weight": init_lib.linear_weight(w0, 2048, 512, dtype),
                        "bias": init_lib.linear_bias(b0, 2048, 512, dtype)},
            "linear1": {"weight": init_lib.linear_weight(w1, 512, NUM_CLASSES,
                                                         dtype),
                        "bias": init_lib.linear_bias(b1, 512, NUM_CLASSES,
                                                     dtype)},
        },
    }
    return params, {}  # no batch-norm -> no running stats


def apply(params: Params, batch_stats: Dict, x: jax.Array, *, train: bool,
          rng: Optional[jax.Array] = None,
          compute_dtype: Optional[jnp.dtype] = None,
          tp_axis: Optional[str] = None,
          tp_recipe: Optional[Dict[str, str]] = None,
          ) -> Tuple[jax.Array, Dict]:
    """Forward pass.  With ``tp_axis`` set (inside a shard_map over that
    mesh axis, params sharded per the recipe), the row-parallel members run
    through the tp wrappers — partial sums psum'd over ``tp_axis``, bias
    after the reduction — and dropout draws the full-width mask so its
    bits match the unsharded run (parallel/tp/layers.py).  Column-parallel
    members are locally byte-identical to the unsharded ops, so they only
    branch for the backward's ``column_input`` psum.

    ``tp_recipe`` overrides the module's TP_RECIPE with an explicit
    per-layer style mapping (the auto-plan path,
    parallel/tp/autoplan.py); layers it omits — or maps to
    ``"replicated"`` — run the plain unsharded ops even under ``tp_axis``
    (their params are replicated over ``model``, and every model shard on
    one data row computes the same activations from the same rng)."""
    del batch_stats
    recipe = TP_RECIPE if tp_recipe is None else tp_recipe
    if tp_axis is not None:
        from ..parallel.tp.layers import (column_conv2d, column_linear,
                                          row_conv2d, row_linear,
                                          sharded_dropout)
    def style(path):
        if tp_axis is None:
            return None
        return recipe.get(path, "replicated")
    cd = compute_dtype or x.dtype
    x = x.astype(cd)
    idx = 0
    for spec in _FEATURES:
        if spec == "M":
            x = max_pool(x, 2, 2)
            continue
        conv = params["features"][f"conv{idx}"]
        k, b = conv["kernel"].astype(cd), conv["bias"].astype(cd)
        s = style(f"features/conv{idx}")
        if s == "row":
            x = row_conv2d(x, k, b, tp_axis, stride=1, padding=1)
        elif s == "column":
            x = column_conv2d(x, k, b, tp_axis, stride=1, padding=1)
        else:
            x = conv2d(x, k, b, stride=1, padding=1)
        x = jax.nn.relu(x)
        idx += 1
    x = x.reshape(x.shape[0], -1)  # [N,8,8,32] -> [N,2048] (NHWC order)
    cls = params["classifier"]
    w0, b0 = (cls["linear0"]["weight"].astype(cd),
              cls["linear0"]["bias"].astype(cd))
    s0 = style("classifier/linear0")
    if s0 == "column":
        x = column_linear(x, w0, b0, tp_axis)
    elif s0 == "row":
        x = row_linear(x, w0, b0, tp_axis)
    else:
        x = linear(x, w0, b0)
    x = jax.nn.relu(x)
    if train:
        if rng is None:
            raise ValueError("DeepNN needs an rng for dropout in train mode")
        # The mask is always drawn at FULL width; the sharded form only
        # exists to slice it when the activation is linear0's column shard.
        if s0 == "column":
            x = sharded_dropout(rng, x, DROPOUT_RATE, train=True,
                                axis_name=tp_axis)
        else:
            x = dropout(rng, x, DROPOUT_RATE, train=True)
    w1, b1 = (cls["linear1"]["weight"].astype(cd),
              cls["linear1"]["bias"].astype(cd))
    s1 = style("classifier/linear1")
    if s1 == "row":
        logits = row_linear(x, w1, b1, tp_axis)
    elif s1 == "column":
        logits = column_linear(x, w1, b1, tp_axis)
    else:
        logits = linear(x, w1, b1)
    return logits.astype(jnp.float32), {}
