"""Transformer workloads: a CIFAR patch encoder and a byte-level LM.

Two models share one block implementation (pre-LN attention + MLP) and
one parameter layout, so a single ``TP_RECIPE`` describes both:

- ``transformer`` — a small vision transformer over 4x4 CIFAR patches
  (64 tokens x 48 dims -> d_model), mean-pooled into the same 10-way
  classifier head every other model in the zoo exposes.  Same uint8
  [N,32,32,3] wire format, so the Trainer, data loaders, serve engine
  and registry programs all apply unchanged.
- ``tinylm`` — a decoder-only byte LM (vocab 256, causal blocks, weight
  layout identical to the encoder's blocks).  Its forward has a second,
  incremental form (:func:`lm_prefill` / :func:`lm_decode_step`) that
  reads and writes a per-stream KV cache — the serving-side decode path
  (serve/kvcache.py).

Tensor-parallel layout (the canonical Megatron pattern, arXiv:1909.08053;
named-axis composition per Mesh-TensorFlow, arXiv:1811.02084):

- ``attn/qkv`` is ONE fused column layer ([d, 3d], head-major output
  columns): a contiguous 1/m column shard is a whole group of heads with
  their q, k and v rows — attention itself then runs on local heads with
  ZERO communication, and the backward contributes exactly one
  ``column_input`` psum.
- ``attn/out`` is row-parallel ([h*hd, d], head-major rows): the one
  forward psum per attention block happens after the output projection.
- ``mlp/fc1`` column / ``mlp/fc2`` row — the standard pair.
- LayerNorms, embeddings and the output head stay replicated.

Per block that is fwd=2 / bwd=2 psums over ``model``; see
``expected_collectives_by_layer`` (parallel/tp/plan.py) for the named
per-layer table the auditor prints on a mismatch.

Pipeline seam: the residual stream makes per-recipe-layer cuts
meaningless, so ``PP_BLOCKS`` is coarse — embed / one entry per
transformer block / head — and every block hands over the full-width
[B, T, d] stream (``PP_SHARDED_OUT`` is empty).
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops import initializers as init_lib
from ..ops.layers import linear

NAME = "transformer"
LM_NAME = "tinylm"
NUM_CLASSES = 10

# Shared architecture constants (both models; kept small enough that the
# whole CPU-mesh test matrix traces and runs in seconds).
PATCH = 4                       # 4x4 patches -> 64 tokens of 48 dims
TOKENS = (32 // PATCH) ** 2     # 64
PATCH_DIM = PATCH * PATCH * 3   # 48
D_MODEL = 64
N_HEADS = 4
HEAD_DIM = D_MODEL // N_HEADS   # 16
N_LAYERS = 2
MLP_HIDDEN = 4 * D_MODEL        # 256

# LM-specific
VOCAB = 256                     # byte-level
T_MAX = 128                     # positional table / KV-cache depth bound

# Marks the LM for the analysis registry (analysis/programs.py): token
# batches + the lm_* program set instead of the CIFAR classifier set.
LM_WORKLOAD = "lm"

# One recipe serves both models: the param paths below exist in both
# trees (parallel/tp/plan.py matches rules by path prefix).
TP_RECIPE = {}
for _i in range(N_LAYERS):
    TP_RECIPE[f"blocks/block{_i}/attn/qkv"] = "column"
    TP_RECIPE[f"blocks/block{_i}/attn/out"] = "row"
    TP_RECIPE[f"blocks/block{_i}/mlp/fc1"] = "column"
    TP_RECIPE[f"blocks/block{_i}/mlp/fc2"] = "row"
del _i

# No barrier layers: every row output is already full-width, and the
# residual stream never crosses a sharded reshape.
TP_BARRIERS = ()

# The network input feeds the REPLICATED patch/token embedding, not a
# column layer, so no stem elision applies: every column layer's
# backward input psum is live (the cotangent flows into the residual
# stream and the embedding parameters above it).
TP_STEM = None

# Coarse pipeline blocks: the residual stream forbids cutting inside a
# transformer block, so each block is one unit.  Block "blocks/blockN"
# owns params["blocks"]["blockN"] (the PP_BLOCKS subtree contract); the
# recipe layers UNDER a block are counted by prefix match in
# parallel/pp/partition.py:stage_model_psums.
PP_BLOCKS = ("embed",) + tuple(
    f"blocks/block{i}" for i in range(N_LAYERS)) + ("head",)

# Every block output is the full-width residual stream (row outputs are
# psum'd inside the block) -> no sharded handoffs, every cut is legal.
PP_SHARDED_OUT = ()

Params = Dict[str, Any]

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


# ---------------------------------------------------------------------------
# init


def _ln_params(d: int, dtype) -> Dict[str, jax.Array]:
    return {"scale": jnp.ones((d,), dtype), "bias": jnp.zeros((d,), dtype)}


def _block_init(key: jax.Array, dtype) -> Dict[str, Any]:
    kq, kqb, ko, kob, k1, k1b, k2, k2b = jax.random.split(key, 8)
    return {
        "ln1": _ln_params(D_MODEL, dtype),
        "attn": {
            "qkv": {"weight": init_lib.linear_weight(kq, D_MODEL,
                                                     3 * D_MODEL, dtype),
                    "bias": init_lib.linear_bias(kqb, D_MODEL,
                                                 3 * D_MODEL, dtype)},
            "out": {"weight": init_lib.linear_weight(ko, D_MODEL,
                                                     D_MODEL, dtype),
                    "bias": init_lib.linear_bias(kob, D_MODEL,
                                                 D_MODEL, dtype)},
        },
        "ln2": _ln_params(D_MODEL, dtype),
        "mlp": {
            "fc1": {"weight": init_lib.linear_weight(k1, D_MODEL,
                                                     MLP_HIDDEN, dtype),
                    "bias": init_lib.linear_bias(k1b, D_MODEL,
                                                 MLP_HIDDEN, dtype)},
            "fc2": {"weight": init_lib.linear_weight(k2, MLP_HIDDEN,
                                                     D_MODEL, dtype),
                    "bias": init_lib.linear_bias(k2b, MLP_HIDDEN,
                                                 D_MODEL, dtype)},
        },
    }


def init(key: jax.Array, dtype=jnp.float32) -> Tuple[Params, Dict]:
    """The CIFAR encoder's parameters (no batch-norm -> no stats)."""
    kp, kpos, khead, *kblocks = jax.random.split(key, 3 + N_LAYERS)
    params: Params = {
        "embed": {
            "patch": {"weight": init_lib.linear_weight(kp, PATCH_DIM,
                                                       D_MODEL, dtype),
                      "bias": init_lib.linear_bias(kp, PATCH_DIM,
                                                   D_MODEL, dtype)},
            "pos": 0.02 * jax.random.normal(kpos, (TOKENS, D_MODEL), dtype),
        },
        "blocks": {f"block{i}": _block_init(kblocks[i], dtype)
                   for i in range(N_LAYERS)},
        "head": {
            "ln": _ln_params(D_MODEL, dtype),
            "linear": {"weight": init_lib.linear_weight(khead, D_MODEL,
                                                        NUM_CLASSES, dtype),
                       "bias": init_lib.linear_bias(khead, D_MODEL,
                                                    NUM_CLASSES, dtype)},
        },
    }
    return params, {}


def lm_init(key: jax.Array, dtype=jnp.float32) -> Tuple[Params, Dict]:
    """The byte LM's parameters — same block subtree paths as the
    encoder, so TP_RECIPE (and any plan built from it) covers both."""
    ktok, kpos, khead, *kblocks = jax.random.split(key, 3 + N_LAYERS)
    params: Params = {
        "embed": {
            "tok": 0.02 * jax.random.normal(ktok, (VOCAB, D_MODEL), dtype),
            "pos": 0.02 * jax.random.normal(kpos, (T_MAX, D_MODEL), dtype),
        },
        "blocks": {f"block{i}": _block_init(kblocks[i], dtype)
                   for i in range(N_LAYERS)},
        "head": {
            "ln": _ln_params(D_MODEL, dtype),
            "linear": {"weight": init_lib.linear_weight(khead, D_MODEL,
                                                        VOCAB, dtype),
                       "bias": init_lib.linear_bias(khead, D_MODEL,
                                                    VOCAB, dtype)},
        },
    }
    return params, {}


# ---------------------------------------------------------------------------
# shared forward pieces


def _layer_norm(x: jax.Array, p: Dict[str, jax.Array]) -> jax.Array:
    """LayerNorm with fp32 statistics (the cast costs nothing in fp32
    and keeps bf16 runs stable), output back in x's dtype."""
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + 1e-6)
    return (y * p["scale"].astype(jnp.float32)
            + p["bias"].astype(jnp.float32)).astype(x.dtype)


def _split_heads(qkv: jax.Array) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """[..., 3*h*hd] head-major -> (q, k, v) each [..., h, hd].  The
    reshape DEFINES the fused layout: column j = (head, {q,k,v}, dim),
    so a contiguous column shard is whole heads — the one property the
    TP path depends on."""
    *lead, width = qkv.shape
    h = width // (3 * HEAD_DIM)
    qkv = qkv.reshape(*lead, h, 3, HEAD_DIM)
    return qkv[..., 0, :], qkv[..., 1, :], qkv[..., 2, :]


def _attention(q: jax.Array, k: jax.Array, v: jax.Array,
               mask: Optional[jax.Array]) -> jax.Array:
    """[B,Tq,h,hd] x [B,Tk,h,hd] -> [B,Tq,h,hd]; softmax statistics in
    fp32 (guide-standard), additive mask pre-softmax."""
    scale = 1.0 / float(HEAD_DIM) ** 0.5
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        scores = jnp.where(mask, scores, _NEG_INF)
    w = jax.nn.softmax(scores, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", w, v)


def _qkv_proj(x, blk, path, style_fn, tp_axis, cd):
    p = blk["attn"]["qkv"]
    w, b = p["weight"].astype(cd), p["bias"].astype(cd)
    if style_fn(f"{path}/attn/qkv") == "column":
        from ..parallel.tp.layers import column_linear
        return column_linear(x, w, b, tp_axis)
    return linear(x, w, b)


def _out_proj(x, blk, path, style_fn, tp_axis, cd):
    p = blk["attn"]["out"]
    w, b = p["weight"].astype(cd), p["bias"].astype(cd)
    if style_fn(f"{path}/attn/out") == "row":
        from ..parallel.tp.layers import row_linear
        return row_linear(x, w, b, tp_axis)
    return linear(x, w, b)


def _mlp(x, blk, path, style_fn, tp_axis, cd):
    p1, p2 = blk["mlp"]["fc1"], blk["mlp"]["fc2"]
    w1, b1 = p1["weight"].astype(cd), p1["bias"].astype(cd)
    w2, b2 = p2["weight"].astype(cd), p2["bias"].astype(cd)
    if style_fn(f"{path}/mlp/fc1") == "column":
        from ..parallel.tp.layers import column_linear
        h = column_linear(x, w1, b1, tp_axis)
    else:
        h = linear(x, w1, b1)
    h = jax.nn.gelu(h)
    if style_fn(f"{path}/mlp/fc2") == "row":
        from ..parallel.tp.layers import row_linear
        return row_linear(h, w2, b2, tp_axis)
    return linear(h, w2, b2)


def _block_forward(blk, path, x, *, causal, style_fn, tp_axis, cd):
    """One pre-LN block over the full sequence.  Returns the new
    residual stream and this block's (k, v) tensors ([B, T, h_local,
    hd]) so prefill can seed a KV cache from the same trace."""
    h = _layer_norm(x, blk["ln1"])
    qkv = _qkv_proj(h, blk, path, style_fn, tp_axis, cd)
    q, k, v = _split_heads(qkv)
    mask = None
    if causal:
        t = x.shape[-2]
        rows = jax.lax.broadcasted_iota(jnp.int32, (t, t), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (t, t), 1)
        mask = (cols <= rows)[None, None, :, :]
    a = _attention(q, k, v, mask)
    a = a.reshape(*a.shape[:-2], -1)  # [B,T,h,hd] -> [B,T,h*hd] head-major
    x = x + _out_proj(a, blk, path, style_fn, tp_axis, cd)
    x = x + _mlp(_layer_norm(x, blk["ln2"]), blk, path, style_fn,
                 tp_axis, cd)
    return x, (k, v)


def _make_style_fn(tp_axis, tp_recipe):
    recipe = TP_RECIPE if tp_recipe is None else tp_recipe

    def style(p):
        if tp_axis is None:
            return None
        return recipe.get(p, "replicated")
    return style


def _patchify(x: jax.Array) -> jax.Array:
    """[B,32,32,3] -> [B, 64, 48] of 4x4 patches (row-major)."""
    b = x.shape[0]
    g = 32 // PATCH
    x = x.reshape(b, g, PATCH, g, PATCH, 3)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, TOKENS, PATCH_DIM)


# ---------------------------------------------------------------------------
# CIFAR encoder


def apply(params: Params, batch_stats: Dict, x: jax.Array, *, train: bool,
          rng: Optional[jax.Array] = None,
          compute_dtype: Optional[jnp.dtype] = None,
          tp_axis: Optional[str] = None,
          tp_recipe: Optional[Dict[str, str]] = None,
          ) -> Tuple[jax.Array, Dict]:
    """Encoder forward.  Under ``tp_axis`` (inside a shard_map over that
    mesh axis, params sharded per the recipe) the fused QKV / fc1 run
    column-parallel and out / fc2 row-parallel; everything else is
    replicated compute.  No dropout, so ``rng`` is accepted and unused —
    the shared step builders pass it unconditionally."""
    return apply_blocks(params, batch_stats, x, blocks=(0, len(PP_BLOCKS)),
                        train=train, rng=rng, compute_dtype=compute_dtype,
                        tp_axis=tp_axis, tp_recipe=tp_recipe)


def apply_blocks(params: Params, batch_stats: Dict, x: jax.Array, *,
                 blocks: Tuple[int, int], train: bool,
                 rng: Optional[jax.Array] = None,
                 compute_dtype: Optional[jnp.dtype] = None,
                 tp_axis: Optional[str] = None,
                 tp_recipe: Optional[Dict[str, str]] = None,
                 ) -> Tuple[jax.Array, Dict]:
    """Run the contiguous PP_BLOCKS range ``blocks=(lo, hi)``; ``x`` is
    the image batch for ``lo == 0``, else the [B, T, d] residual stream
    handed over from the previous stage.  ``(0, len(PP_BLOCKS))`` IS
    :func:`apply`, so staged and unstaged paths cannot drift."""
    del batch_stats, train, rng  # no BN, no dropout
    lo, hi = blocks
    if not 0 <= lo < hi <= len(PP_BLOCKS):
        raise ValueError(
            f"blocks must be a non-empty range within "
            f"(0, {len(PP_BLOCKS)}), got {blocks!r}")
    style = _make_style_fn(tp_axis, tp_recipe)
    cd = compute_dtype or x.dtype
    x = x.astype(cd)

    for name in PP_BLOCKS[lo:hi]:
        if name == "embed":
            e = params["embed"]
            x = _patchify(x)
            x = linear(x, e["patch"]["weight"].astype(cd),
                       e["patch"]["bias"].astype(cd))
            x = x + e["pos"].astype(cd)[None, :, :]
        elif name == "head":
            hd = params["head"]
            x = _layer_norm(x, hd["ln"])
            x = jnp.mean(x, axis=-2)  # mean-pool tokens
            x = linear(x, hd["linear"]["weight"].astype(cd),
                       hd["linear"]["bias"].astype(cd))
            x = x.astype(jnp.float32)
        else:
            blk = params["blocks"][name.split("/", 1)[1]]
            x, _ = _block_forward(blk, name, x, causal=False,
                                  style_fn=style, tp_axis=tp_axis, cd=cd)
    return x, {}


# ---------------------------------------------------------------------------
# decoder-only LM


def lm_apply(params: Params, batch_stats: Dict, tokens: jax.Array, *,
             train: bool, rng: Optional[jax.Array] = None,
             compute_dtype: Optional[jnp.dtype] = None,
             tp_axis: Optional[str] = None,
             tp_recipe: Optional[Dict[str, str]] = None,
             ) -> Tuple[jax.Array, Dict]:
    """Full-sequence causal forward: int tokens [B, T] -> fp32 logits
    [B, T, VOCAB].  The uncached reference the KV-cached decode is
    parity-tested against (tests/test_kvcache.py)."""
    del batch_stats, train, rng
    if tokens.shape[-1] > T_MAX:
        raise ValueError(f"sequence length {tokens.shape[-1]} exceeds "
                         f"T_MAX={T_MAX}")
    style = _make_style_fn(tp_axis, tp_recipe)
    cd = compute_dtype or jnp.float32
    e = params["embed"]
    t = tokens.shape[-1]
    x = e["tok"].astype(cd)[tokens] + e["pos"].astype(cd)[None, :t, :]
    for i in range(N_LAYERS):
        x, _ = _block_forward(params["blocks"][f"block{i}"],
                              f"blocks/block{i}", x, causal=True,
                              style_fn=style, tp_axis=tp_axis, cd=cd)
    hd = params["head"]
    x = _layer_norm(x, hd["ln"])
    x = linear(x, hd["linear"]["weight"].astype(cd),
               hd["linear"]["bias"].astype(cd))
    return x.astype(jnp.float32), {}


def lm_prefill(params: Params, tokens: jax.Array, *,
               compute_dtype: Optional[jnp.dtype] = None,
               tp_axis: Optional[str] = None,
               tp_recipe: Optional[Dict[str, str]] = None,
               ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Causal forward over a prompt [B, T_bucket] that ALSO returns the
    per-block key/value tensors: (logits [B, T, V] fp32, k, v) with
    k/v stacked [L, B, T, h_local, hd] — the slot image a KV cache
    stores.  Padding beyond the true prompt length is masked at decode
    time (by the stream's length), never here."""
    style = _make_style_fn(tp_axis, tp_recipe)
    cd = compute_dtype or jnp.float32
    e = params["embed"]
    t = tokens.shape[-1]
    x = e["tok"].astype(cd)[tokens] + e["pos"].astype(cd)[None, :t, :]
    ks, vs = [], []
    for i in range(N_LAYERS):
        x, (k, v) = _block_forward(params["blocks"][f"block{i}"],
                                   f"blocks/block{i}", x, causal=True,
                                   style_fn=style, tp_axis=tp_axis, cd=cd)
        ks.append(k)
        vs.append(v)
    hd = params["head"]
    x = _layer_norm(x, hd["ln"])
    x = linear(x, hd["linear"]["weight"].astype(cd),
               hd["linear"]["bias"].astype(cd))
    return (x.astype(jnp.float32),
            jnp.stack(ks, axis=0), jnp.stack(vs, axis=0))


def lm_decode_step(params: Params, tokens: jax.Array, positions: jax.Array,
                   k_cache: jax.Array, v_cache: jax.Array, *,
                   compute_dtype: Optional[jnp.dtype] = None,
                   tp_axis: Optional[str] = None,
                   tp_recipe: Optional[Dict[str, str]] = None,
                   ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One incremental decode step over every cache slot.

    ``tokens`` [S] int32 (this step's input token per slot),
    ``positions`` [S] int32 (its position: the slot's current length),
    ``k_cache``/``v_cache`` [L, S, T_max, h_local, hd].  Inactive slots
    simply compute garbage that the caller never reads — the program
    shape is FIXED so serving compiles it exactly once.

    Returns (logits [S, V] fp32, new_k_cache, new_v_cache) with this
    step's k/v written at ``positions`` (per-slot scatter via a vmapped
    dynamic_update_slice — the cache-update program the auditor prices).
    """
    style = _make_style_fn(tp_axis, tp_recipe)
    cd = compute_dtype or jnp.float32
    e = params["embed"]
    t_max = k_cache.shape[2]
    # [S] -> [S, 1, d]: token embedding + per-slot positional row.
    x = (e["tok"].astype(cd)[tokens]
         + e["pos"].astype(cd)[positions])[:, None, :]

    def write(cache_l, new, pos):
        # cache_l [T_max, h, hd], new [1, h, hd], pos scalar
        return jax.lax.dynamic_update_slice_in_dim(cache_l, new, pos, axis=0)

    new_k, new_v = [], []
    for i in range(N_LAYERS):
        blk = params["blocks"][f"block{i}"]
        path = f"blocks/block{i}"
        h = _layer_norm(x, blk["ln1"])
        qkv = _qkv_proj(h, blk, path, style, tp_axis, cd)
        q, k, v = _split_heads(qkv)          # [S, 1, h, hd]
        kc = jax.vmap(write)(k_cache[i].astype(cd), k, positions)
        vc = jax.vmap(write)(v_cache[i].astype(cd), v, positions)
        new_k.append(kc)
        new_v.append(vc)
        # Attend over the cache up to and including this position.
        valid = (jax.lax.broadcasted_iota(jnp.int32, (t_max,), 0)[None, :]
                 <= positions[:, None])        # [S, T_max]
        a = _attention(q, kc, vc, valid[:, None, None, :])
        a = a.reshape(*a.shape[:-2], -1)
        x = x + _out_proj(a, blk, path, style, tp_axis, cd)
        x = x + _mlp(_layer_norm(x, blk["ln2"]), blk, path, style,
                     tp_axis, cd)
    hd = params["head"]
    x = _layer_norm(x, hd["ln"])
    x = linear(x, hd["linear"]["weight"].astype(cd),
               hd["linear"]["bias"].astype(cd))
    return (x[:, 0, :].astype(jnp.float32),
            jnp.stack(new_k, axis=0), jnp.stack(new_v, axis=0))
