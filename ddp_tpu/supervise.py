"""``python -m ddp_tpu.supervise -- <training command>`` — the restart
wrapper entry point.  All logic lives in resilience/supervisor.py; this
module only exists so the wrapper is spelled the same way as the other
executables (``-m ddp_tpu.serve``, ``-m ddp_tpu.analysis``)."""
from .resilience.supervisor import main

if __name__ == "__main__":
    raise SystemExit(main())
