"""Tensor-model parallelism over the 2-D (data × model) mesh.

``plan.py`` is the sharding planner (declarative layer rules -> per-leaf
PartitionSpecs + a human-readable plan table); ``layers.py`` is the sharded
compute (column/row-parallel dense and conv over the ops/layers.py
primitives, with the row-parallel output ``psum`` fused inside the jitted
step).  See the package docstrings for the axis-correctness contract.
"""
from .plan import TPPlan, format_plan_table, plan_for_model, state_shardings

__all__ = ["TPPlan", "format_plan_table", "plan_for_model",
           "state_shardings"]
