"""``python -m ddp_tpu.parallel.tp`` — print a model's sharding plan table.

The offline view of what the CLI prints at startup under ``--mesh_shape``:
resolve the model's TP_RECIPE against a fresh param pytree at the given
model-axis size, validate it, print the plan table with the per-layer
predicted-cost column (``analysis.costmodel.layer_forward_costs``; the
column is omitted when the recipe doesn't map 1:1 onto the traced
conv/dot ops), and exit non-zero on an infeasible plan.  CI
schema-checks this output, footers included.
"""
from __future__ import annotations

import argparse

import jax

from .plan import format_plan_table, plan_for_model


def main() -> None:
    p = argparse.ArgumentParser(
        prog="python -m ddp_tpu.parallel.tp",
        description=__doc__.splitlines()[0])
    p.add_argument("--model", default="deepnn",
                   choices=["vgg", "deepnn", "resnet18"])
    p.add_argument("--model_axis", default=4, type=int, metavar="M",
                   help="model-axis size to plan for (default 4)")
    args = p.parse_args()
    from ...analysis.costmodel import layer_forward_costs
    from ...models import get_model
    model = get_model(args.model)
    params, batch_stats = model.init(jax.random.key(0))
    plan = plan_for_model(args.model, params, batch_stats,
                          model_size=args.model_axis)
    costs = layer_forward_costs(model, plan, params, batch_stats)
    print(format_plan_table(plan, layer_costs=costs))


if __name__ == "__main__":
    main()
