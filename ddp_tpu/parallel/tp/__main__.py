"""``python -m ddp_tpu.parallel.tp`` — plan tables and the auto-plan search.

Default mode is the offline view of what the CLI prints at startup under
``--mesh_shape``: resolve the model's TP_RECIPE against a fresh param
pytree at the given model-axis size, validate it, print the plan table
with the per-layer predicted-cost column
(``analysis.costmodel.layer_forward_costs``; the column is omitted when
the recipe doesn't map 1:1 onto the traced conv/dot ops), and exit
non-zero on an infeasible plan.  CI schema-checks this output, footers
included.

``--search`` runs the auto-sharding search instead (tp/autoplan.py):
enumerate layouts x mesh shapes x ZeRO over ``--devices`` (ANY device
budget — candidates are priced on a deviceless abstract mesh, so a
laptop can search v4-128 shapes), print the ranked candidate table and
the chosen plan's table, and write the plan-as-data JSON with ``--out``
— the file ``ddp_tpu.cli --auto_plan`` loads.  ``--calib`` points at a
``bench.py --calibrate_cost`` record (or any prior auto-plan JSON) for
the measured per-op-class coefficients the pricing needs.
"""
from __future__ import annotations

import argparse
import json
import sys

import jax

from .plan import format_plan_table, plan_for_model


def _parse_shape(arg: str):
    parts = [int(v) for v in arg.replace("x", ",").split(",") if v]
    if len(parts) != 2 or min(parts) < 1:
        raise SystemExit(f"--mesh_shape wants D,M (got {arg!r})")
    return tuple(parts)


def _search(args) -> int:
    from ...analysis.search import coefficients_from
    from .autoplan import (format_search_table, plan_doc_dumps,
                           plan_from_doc, search_plan)
    try:
        with open(args.calib, "r", encoding="utf-8") as fh:
            coeffs = coefficients_from(json.load(fh))
    except (OSError, json.JSONDecodeError, ValueError) as e:
        print(f"--calib: {e}", file=sys.stderr)
        return 2
    zero_options = {"both": (False, True), "on": (True,),
                    "off": (False,)}[args.zero]
    try:
        result = search_plan(
            args.model, coefficients=coeffs,
            total_devices=args.devices,
            mesh_shapes=([_parse_shape(args.mesh_shape)]
                         if args.mesh_shape else None),
            hbm_budget_bytes=(int(args.hbm_budget_gb * 2**30)
                              if args.hbm_budget_gb else None),
            global_batch=args.global_batch,
            zero_options=zero_options,
            log=print if args.verbose else None)
    except ValueError as e:
        print(f"search failed: {e}", file=sys.stderr)
        return 1
    print(format_search_table(result, args.model))
    doc = result.doc
    from ...models import get_model
    model = get_model(args.model)
    params, batch_stats = model.init(jax.random.key(0))
    plan = plan_from_doc(doc, params, batch_stats)
    if plan is not None:
        from ...analysis.costmodel import layer_forward_costs
        costs = layer_forward_costs(model, plan, params, batch_stats)
        print(format_plan_table(plan, layer_costs=costs))
    else:
        print(f"chosen plan is pure data parallelism over "
              f"{doc['mesh_shape'][0]} devices — no tensor-parallel "
              f"plan table")
    if args.out:
        with open(args.out, "w", encoding="utf-8") as fh:
            fh.write(plan_doc_dumps(doc))
        print(f"wrote auto plan to {args.out}")
    return 0


def main() -> None:
    p = argparse.ArgumentParser(
        prog="python -m ddp_tpu.parallel.tp",
        description=__doc__.splitlines()[0])
    p.add_argument("--model", default="deepnn",
                   choices=["vgg", "deepnn", "resnet18"])
    p.add_argument("--model_axis", default=4, type=int, metavar="M",
                   help="model-axis size to plan for (default 4; "
                        "plan-table mode only)")
    p.add_argument("--search", action="store_true",
                   help="run the auto-sharding search instead of "
                        "printing the hand recipe's table")
    p.add_argument("--devices", default=8, type=int, metavar="N",
                   help="total device budget to search over (default 8; "
                        "any size — pricing is static, no devices "
                        "needed)")
    p.add_argument("--mesh_shape", default=None, metavar="D,M",
                   help="constrain the search to one mesh shape "
                        "(default: every factorization of --devices)")
    p.add_argument("--calib", default=None, metavar="CALIB.json",
                   help="calibrated coefficients source: a bench.py "
                        "--calibrate_cost record or a prior auto-plan "
                        "JSON (required with --search)")
    p.add_argument("--hbm_budget_gb", default=None, type=float,
                   metavar="GB",
                   help="prune candidates whose per-shard liveness peak "
                        "exceeds this budget (default: no memory prune)")
    p.add_argument("--global_batch", default=32, type=int,
                   help="global rows per step the candidates are priced "
                        "at (default 32)")
    p.add_argument("--zero", default="both", choices=["both", "on", "off"],
                   help="ZeRO dimension of the search space "
                        "(default both)")
    p.add_argument("--out", default=None, metavar="PLAN.json",
                   help="write the chosen plan-as-data JSON here "
                        "(the file cli --auto_plan loads)")
    p.add_argument("--verbose", action="store_true",
                   help="log every candidate as it is priced")
    args = p.parse_args()
    if args.search:
        if not args.calib:
            p.error("--search needs --calib (a bench.py --calibrate_cost "
                    "record or a prior auto-plan JSON)")
        raise SystemExit(_search(args))
    from ...analysis.costmodel import layer_forward_costs
    from ...models import get_model
    model = get_model(args.model)
    params, batch_stats = model.init(jax.random.key(0))
    plan = plan_for_model(args.model, params, batch_stats,
                          model_size=args.model_axis)
    costs = layer_forward_costs(model, plan, params, batch_stats)
    print(format_plan_table(plan, layer_costs=costs))


if __name__ == "__main__":
    main()
