"""Sharded compute for tensor-model parallelism: column/row-parallel dense
and conv wrappers over the ``ops/layers.py`` primitives.

The Megatron/Mesh-TensorFlow pairing (PAPERS.md arxiv 1811.02084) on the
``model`` axis of a 2-D (data × model) mesh:

- **column-parallel**: the weight's OUTPUT dimension is sharded, so each
  model shard computes a feature *slice* of the layer's output from the
  full (replicated) input.  The local FORWARD is byte-identical to the
  unsharded op on the kernel slice; the wrapper's real job is the
  backward — :func:`column_input` (Megatron's "f") sums the input
  cotangent over ``model``, because each shard's backward contributes
  only its weight slice's share of dx.
- **row-parallel**: the weight's INPUT dimension is sharded, consuming the
  column-sharded activation directly (no gather between the pair); each
  shard produces a PARTIAL sum over its input slice and the full output is
  ``psum`` over ``model`` — fused inside the jitted step, where XLA lowers
  it onto ICI.  The bias is replicated and added AFTER the psum (adding a
  per-shard bias would count it model-axis-size times).

Axis-correctness contract (the whole game): every collective here reduces
over the ``model`` axis ONLY; the gradient ``pmean``/``psum`` of the train
steps stays on ``data`` only (train/step.py, train/zero.py).  The
row-parallel forward psum carries a custom transpose
(:func:`psum_keepgrad`): its output is replicated over ``model``
downstream, so the adjoint of the shard-sum is the IDENTITY on the
cotangent.  The runtime's own psum transpose is another psum — correct for
varying cotangents, but a silent ``model``-axis-size overcount for the
replicated ones every row-parallel layer produces (and each row layer on
the path would multiply again).  The m=1 bit-identity and 1-D-parity tests
(tests/test_tp.py) pin this numerically, and the program auditor pins it
structurally: ``python -m ddp_tpu.analysis`` counts the traced
``psum(model)`` equations against the plan's expected-collectives
arithmetic (row layers psum in the forward, column layers in the
backward, the stem's input-grad psum elided — plan.py
``expected_collectives``), so an extra or missing model-axis collective
fails CI before it costs ICI bandwidth.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ...ops.layers import conv2d, linear


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _psum_keepgrad(axis_name: str, x: jax.Array) -> jax.Array:
    # Nondiff axis name first — the custom_vjp convention ops/layers.py's
    # bn_relu already follows.
    return lax.psum(x, axis_name)


def _psum_keepgrad_fwd(axis_name, x):
    return lax.psum(x, axis_name), None


def _psum_keepgrad_bwd(axis_name, _res, ct):
    return (ct,)


_psum_keepgrad.defvjp(_psum_keepgrad_fwd, _psum_keepgrad_bwd)


def psum_keepgrad(x: jax.Array, axis_name: str) -> jax.Array:
    """``lax.psum`` over ``axis_name`` whose transpose is the identity —
    the correct adjoint when the summed output is consumed replicated over
    that axis (every row-parallel layer's situation).  See the module
    docstring for why the default psum transpose would overcount."""
    return _psum_keepgrad(axis_name, x)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _column_input(axis_name: str, x: jax.Array) -> jax.Array:
    return x


def _column_input_fwd(axis_name, x):
    return x, None


def _column_input_bwd(axis_name, _res, ct):
    return (lax.psum(ct, axis_name),)


_column_input.defvjp(_column_input_fwd, _column_input_bwd)


def column_input(x: jax.Array, axis_name: str) -> jax.Array:
    """Megatron's "f" operator — the column-parallel layers' dual of
    :func:`psum_keepgrad`: identity forward, ``psum`` over ``axis_name``
    backward.  A column layer's input is REPLICATED over ``model`` while
    its weight slice differs per shard, so each shard's backward produces
    only its slice's *contribution* to the input cotangent; the sum over
    shards is the real dx.  Without this psum every parameter upstream of
    a column layer silently trains on a 1/m-ish gradient (caught by the
    per-leaf gradient parity test in tests/test_tp.py).  At m=1 the psum
    is over one shard — identity, bit-for-bit."""
    return _column_input(axis_name, x)


def column_linear(x: jax.Array, weight: jax.Array,
                  bias: Optional[jax.Array], axis_name: str) -> jax.Array:
    """Column-parallel dense: ``weight`` is the ``[in, out/m]`` shard, the
    output is the matching feature slice.  The forward math is
    ``ops.linear`` on the slice (full-length contractions — every output
    element is the same dot product the unsharded layer computes); the
    wrapper's job is the BACKWARD: :func:`column_input` sums the input
    cotangent over ``axis_name``."""
    return linear(column_input(x, axis_name), weight, bias)


def row_linear(x: jax.Array, weight: jax.Array,
               bias: Optional[jax.Array], axis_name: str) -> jax.Array:
    """Row-parallel dense: ``x`` is the column-sharded ``[..., in/m]``
    activation, ``weight`` the ``[in/m, out]`` shard; partial products are
    ``psum``-ed over ``axis_name`` and the replicated ``bias`` is added
    once, after the reduction."""
    y = psum_keepgrad(linear(x, weight, None), axis_name)
    if bias is not None:
        y = y + bias
    return y


def column_conv2d(x: jax.Array, kernel: jax.Array,
                  bias: Optional[jax.Array], axis_name: str, *,
                  stride: int = 1, padding: int = 1) -> jax.Array:
    """Column-parallel conv: ``kernel`` is the ``[kh, kw, in, out/m]``
    shard, output channels are the matching slice.  Forward math is
    ``ops.conv2d`` on the slice; :func:`column_input` carries the
    backward's ``model``-axis sum (see :func:`column_linear`)."""
    return conv2d(column_input(x, axis_name), kernel, bias, stride=stride,
                  padding=padding)


def row_conv2d(x: jax.Array, kernel: jax.Array, bias: Optional[jax.Array],
               axis_name: str, *, stride: int = 1,
               padding: int = 1) -> jax.Array:
    """Row-parallel conv: ``x`` carries the column-sharded ``in/m``
    channels, ``kernel`` is the ``[kh, kw, in/m, out]`` shard; the partial
    channel sums are ``psum``-ed over ``axis_name``, replicated ``bias``
    added after."""
    y = psum_keepgrad(conv2d(x, kernel, None, stride=stride,
                             padding=padding), axis_name)
    if bias is not None:
        y = y + bias
    return y


def sharded_dropout(key: jax.Array, x: jax.Array, rate: float, train: bool,
                    axis_name: str) -> jax.Array:
    """Dropout on a feature-sharded activation that draws the SAME mask
    the unsharded layer would: the full-width mask is generated on every
    model shard (a few KB — noise next to the matmuls around it) and each
    shard takes its own column block.  Drawing a per-shard-shaped mask
    instead would give every shard the byte-identical mask for *different*
    feature slices — a distribution change vs the 1-D run.  At m=1 the
    slice is the whole mask and the expression reduces bit-for-bit to
    ``ops.layers.dropout`` (tests/test_tp.py pins it)."""
    if not train or rate == 0.0:
        return x
    keep = 1.0 - rate
    m = lax.axis_size(axis_name)
    local = x.shape[-1]
    mask = jax.random.bernoulli(key, keep, x.shape[:-1] + (local * m,))
    mask = lax.dynamic_slice_in_dim(mask, lax.axis_index(axis_name) * local,
                                    local, axis=mask.ndim - 1)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))
