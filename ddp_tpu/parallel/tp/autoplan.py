"""Auto-sharding search: the cost-model-driven layout planner.

Retires the hand-written ``TP_RECIPE`` as the *only* way to shard a
model: for a given model and device budget this module enumerates
candidate per-layer layouts (replicated / column-parallel /
row-parallel) x candidate mesh shapes ``(d, m)`` x ZeRO on/off, prices
every candidate STATICALLY (analysis/search.py: the real step builders
traced on a deviceless abstract mesh, costed through the calibrated
coefficients, peak-HBM from the liveness walk), prunes the infeasible
ones, and emits the cheapest survivor as a ``TP_RECIPE``-compatible
plan-as-data JSON doc — loadable via ``--auto_plan`` on the CLI and
printable via ``python -m ddp_tpu.parallel.tp --search``.  The search
is exactly the automatic-layout framing of Mesh-TensorFlow (arXiv
1811.02084) over the weight-update sharding space of arXiv 2004.13336
(PAPERS.md), grounded in this repo's measured coefficients.

**The layout space is a DFA over activation width.**  Walking the
model's recipe layers in network order, the activation entering each
layer is either ``full`` (every model shard holds all features) or
``sharded`` (each shard holds its column slice):

- ``column`` consumes full, produces sharded (output dim split);
- ``row`` consumes sharded, produces full (partial sums psum'd);
- ``replicated`` consumes full, produces full (plain op);
- the terminal state must be full (the loss consumes full logits), and
  every model-declared ``TP_BARRIERS`` layer must produce full — e.g.
  deepnn's conv3, whose NHWC flatten would interleave a channel-sharded
  activation into a slice no contiguous row shard matches.

Everything the hand path enforces, the auto path enforces identically:
candidate plans resolve through ``plan_for_model``'s divisibility/drift
rules (tp/plan.py), and every candidate's traced program must satisfy
its own plan's ``expected_collectives`` arithmetic under the strict
jaxpr auditor before it may win — a plan the auditor rejects is pruned,
never emitted.

**Pruning reasons** (reported per candidate, and counted in the doc):

- ``batch``       — global batch not divisible by the data axis;
- ``divisibility``— a sharded dim not divisible by the model axis;
- ``audit``       — traced collectives violate the plan's invariants;
- ``hbm``         — liveness peak exceeds the ``--hbm_budget`` bytes.

The emitted doc is deterministic — same model, device budget and
coefficients produce bit-identical JSON (no timestamps, sorted keys) —
so golden plans can be committed and CI can diff them.
"""
from __future__ import annotations

import importlib
import json
from typing import Dict, List, NamedTuple, Optional, Tuple

PLAN_FORMAT_VERSION = 1
PLAN_KIND = "ddp_tpu.autoplan"

_STYLE_LETTER = {"column": "c", "row": "r", "replicated": "-"}

# Model registry name -> module name where it differs (tp/plan.py's map).
_MODULE_FOR = {"resnet18": "resnet"}


class SearchSpace(NamedTuple):
    """What the model declares about its shardable structure."""
    layers: Tuple[str, ...]    # recipe layers, network order
    barriers: Tuple[str, ...]  # layers whose OUTPUT must be full-width
    stem: Optional[str]        # the layer consuming the network input


def search_space_for(model_name: str) -> SearchSpace:
    """The search space a model module declares: its ``TP_RECIPE`` keys
    (network order — the order the hand recipe already relies on for the
    column/row pairing), ``TP_BARRIERS``, ``TP_STEM``.  A model with no
    recipe has an EMPTY layer space: the search still runs, over mesh
    shapes and ZeRO only (pure data parallelism)."""
    mod = importlib.import_module(
        f"ddp_tpu.models.{_MODULE_FOR.get(model_name, model_name)}")
    recipe = getattr(mod, "TP_RECIPE", None) or {}
    return SearchSpace(layers=tuple(recipe),
                       barriers=tuple(getattr(mod, "TP_BARRIERS", ())),
                       stem=getattr(mod, "TP_STEM", None))


def enumerate_recipes(space: SearchSpace) -> List[Dict[str, str]]:
    """Every per-layer style assignment the activation-width DFA admits
    (module docstring).  Deterministic order: depth-first with styles
    tried replicated -> column -> row at each layer."""
    layers = space.layers
    barriers = set(space.barriers)

    def walk(i: int, sharded: bool) -> List[List[str]]:
        if i == len(layers):
            return [[]] if not sharded else []
        out: List[List[str]] = []
        for style in ("replicated", "column", "row"):
            if style == "row":
                if not sharded:
                    continue          # row consumes a sharded activation
            elif sharded:
                continue              # replicated/column consume full
            next_sharded = style == "column"
            if next_sharded and layers[i] in barriers:
                continue              # barrier output must be full-width
            for rest in walk(i + 1, next_sharded):
                out.append([style] + rest)
        return out

    return [dict(zip(layers, styles)) for styles in walk(0, False)]


def candidate_mesh_shapes(total_devices: int) -> List[Tuple[int, int]]:
    """Every ``(d, m)`` factorization of the device budget, m ascending
    — ``(N, 1)`` (pure DP) through ``(1, N)`` (pure TP)."""
    if total_devices < 1:
        raise ValueError(f"total_devices must be >= 1, got {total_devices}")
    return [(total_devices // m, m) for m in range(1, total_devices + 1)
            if total_devices % m == 0]


def _is_sharded(recipe: Dict[str, str]) -> bool:
    return any(s in ("column", "row") for s in recipe.values())


def _candidate_key(mesh_shape, recipe, zero) -> str:
    return json.dumps({"mesh_shape": list(mesh_shape), "recipe": recipe,
                       "zero": bool(zero)}, sort_keys=True)


class SearchResult(NamedTuple):
    doc: dict               # the chosen plan-as-data JSON doc
    candidates: List[dict]  # every candidate row, ranked, pruned last
    pruned: Dict[str, int]  # prune-reason -> count


def search_plan(model_name: str, *, coefficients: Dict[str, float],
                total_devices: Optional[int] = None,
                mesh_shapes: Optional[List[Tuple[int, int]]] = None,
                hbm_budget_bytes: Optional[int] = None,
                global_batch: int = 32,
                zero_options: Tuple[bool, ...] = (False, True),
                log=None) -> SearchResult:
    """Run the full search.  Pass ``mesh_shapes`` to constrain the mesh
    (the CI golden search pins ``[(2, 4)]``), else every factorization
    of ``total_devices`` is explored.  ``coefficients`` are the four
    calibrated per-op-class rates (``bench.py --calibrate_cost``, or any
    doc ``analysis.search.coefficients_from`` accepts).

    Ranking: lowest predicted per-shard ms, ties broken by lower peak
    HBM, then by the candidate's canonical JSON key — fully
    deterministic.  Raises ``ValueError`` when every candidate was
    pruned (e.g. an HBM budget nothing fits under)."""
    from ...analysis.search import (audit_candidate, coefficients_from,
                                    price_closed, trace_candidate)
    coefficients = coefficients_from(coefficients)
    if mesh_shapes is None:
        if total_devices is None:
            raise ValueError("pass total_devices or mesh_shapes")
        mesh_shapes = candidate_mesh_shapes(total_devices)
    else:
        mesh_shapes = [(int(d), int(m)) for d, m in mesh_shapes]
        total_devices = total_devices or max(d * m for d, m in mesh_shapes)
    space = search_space_for(model_name)
    recipes = enumerate_recipes(space)

    candidates: List[dict] = []
    pruned: Dict[str, int] = {}

    def note(reason: str) -> str:
        pruned[reason] = pruned.get(reason, 0) + 1
        return reason

    for d, m in mesh_shapes:
        if m == 1:
            # All recipes collapse at m=1 — one canonical pure-DP entry.
            recs: List[Dict[str, str]] = [{}]
        else:
            # The all-replicated recipe at m>1 is strictly dominated by
            # (d*m, 1): same per-layer math on fewer rows per shard.
            recs = [r for r in recipes if _is_sharded(r)]
        for recipe in recs:
            stem = space.stem if (recipe and space.stem in recipe) else None
            for zero in zero_options:
                row = {"mesh_shape": [d, m], "recipe": recipe,
                       "stem": stem, "zero": bool(zero), "pruned": None}
                candidates.append(row)
                if global_batch % d:
                    row["pruned"] = note("batch")
                    row["detail"] = (f"global batch {global_batch} not "
                                     f"divisible by d={d}")
                    continue
                try:
                    closed, plan = trace_candidate(
                        model_name, (d, m),
                        recipe=recipe if recipe else None, stem=stem,
                        zero=zero, global_batch=global_batch)
                except ValueError as e:
                    row["pruned"] = note("divisibility")
                    row["detail"] = str(e).splitlines()[0]
                    continue
                row.update(price_closed(closed, coefficients))
                errors = audit_candidate(
                    f"{model_name}@{d}x{m}", closed, plan=plan, zero=zero)
                if errors:
                    row["pruned"] = note("audit")
                    row["detail"] = "; ".join(errors)
                    continue
                if (hbm_budget_bytes is not None
                        and row["peak_live_bytes"] > hbm_budget_bytes):
                    row["pruned"] = note("hbm")
                    row["detail"] = (f"peak {row['peak_live_bytes']} B > "
                                     f"budget {hbm_budget_bytes} B")
                    continue
                if log is not None:
                    log(f"  {d}x{m} zero={int(zero)} "
                        f"{recipe_summary(recipe, space)} -> "
                        f"{row['predicted_ms']:.3f} ms/shard")

    alive = [r for r in candidates if r["pruned"] is None]
    if not alive:
        raise ValueError(
            f"auto-plan search for {model_name!r} pruned every candidate "
            f"({dict(sorted(pruned.items()))}); relax the HBM budget or "
            "the mesh constraints")
    rank = lambda r: (r["predicted_ms"], r["peak_live_bytes"],  # noqa: E731
                      _candidate_key(r["mesh_shape"], r["recipe"],
                                     r["zero"]))
    alive.sort(key=rank)
    candidates.sort(key=lambda r: (r["pruned"] is not None,
                                   rank(r) if r["pruned"] is None
                                   else (0.0, 0, _candidate_key(
                                       r["mesh_shape"], r["recipe"],
                                       r["zero"]))))
    best = alive[0]
    doc = {
        "format_version": PLAN_FORMAT_VERSION,
        "kind": PLAN_KIND,
        "model": model_name,
        "mesh_shape": best["mesh_shape"],
        "recipe": best["recipe"],
        "stem": best["stem"],
        "zero": best["zero"],
        "global_batch": int(global_batch),
        "predicted_ms_per_step": best["predicted_ms"],
        "flops": best["flops"],
        "bytes": best["bytes"],
        "collective_payload_bytes": best["collective_payload_bytes"],
        "peak_live_bytes": best["peak_live_bytes"],
        "coefficients": coefficients,
        "search": {
            "total_devices": int(total_devices),
            "mesh_shapes": [list(s) for s in mesh_shapes],
            "hbm_budget_bytes": hbm_budget_bytes,
            "zero_options": [bool(z) for z in zero_options],
            "candidates_considered": len(candidates),
            "candidates_alive": len(alive),
            "pruned": dict(sorted(pruned.items())),
        },
    }
    return SearchResult(doc=doc, candidates=candidates, pruned=pruned)


def plan_doc_dumps(doc: dict) -> str:
    """The canonical serialized form — sorted keys, trailing newline —
    the determinism contract (same inputs -> bit-identical bytes)."""
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"


def validate_plan_doc(doc: dict) -> None:
    """Schema check, raising ``ValueError`` with every violation at once
    (the tp/plan.py error style) — run on load AND by the CI smoke on
    the emitted file."""
    errors = []
    if not isinstance(doc, dict):
        raise ValueError(f"auto-plan doc must be a JSON object, got "
                         f"{type(doc).__name__}")
    if doc.get("kind") != PLAN_KIND:
        errors.append(f"  kind: expected {PLAN_KIND!r}, got "
                      f"{doc.get('kind')!r}")
    if doc.get("format_version") != PLAN_FORMAT_VERSION:
        errors.append(f"  format_version: expected {PLAN_FORMAT_VERSION}, "
                      f"got {doc.get('format_version')!r}")
    if not isinstance(doc.get("model"), str) or not doc.get("model"):
        errors.append("  model: expected a non-empty string")
    ms = doc.get("mesh_shape")
    if (not isinstance(ms, list) or len(ms) not in (2, 3)
            or not all(isinstance(v, int) and v >= 1 for v in ms)):
        errors.append(f"  mesh_shape: expected [d, m] or [d, m, s] "
                      f"(data, model, pipeline stage) of positive ints, "
                      f"got {ms!r}")
    recipe = doc.get("recipe")
    if not isinstance(recipe, dict):
        errors.append(f"  recipe: expected a layer->style object, got "
                      f"{type(recipe).__name__}")
    else:
        from .plan import RECIPE_STYLES
        bad = {k: v for k, v in recipe.items() if v not in RECIPE_STYLES}
        if bad:
            errors.append(f"  recipe: unknown styles {bad}; expected one "
                          f"of {RECIPE_STYLES}")
    stem = doc.get("stem")
    if stem is not None and (not isinstance(recipe, dict)
                             or stem not in recipe):
        errors.append(f"  stem: {stem!r} is not a recipe layer")
    if not isinstance(doc.get("zero"), bool):
        errors.append(f"  zero: expected a bool, got {doc.get('zero')!r}")
    if errors:
        raise ValueError("invalid auto-plan doc:\n" + "\n".join(errors))


def read_plan_doc(path: str) -> dict:
    """Load + schema-validate a plan doc from disk."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    validate_plan_doc(doc)
    return doc


def plan_from_doc(doc: dict, params, batch_stats=None):
    """Resolve a plan doc against LIVE params — the auto analogue of
    ``plan_for_model``, and the same validation: the recipe re-resolves
    against the actual param pytree, so a doc that drifted from the
    model (renamed layer, indivisible dim) fails loudly at startup,
    exactly like a drifted hand recipe.

    Returns a ``TPPlan``, or ``None`` for a trivial doc (no sharded
    layer — pure data parallelism; the caller runs the plain builders
    on the doc's mesh shape)."""
    from .plan import is_trivial, plan_for_model
    validate_plan_doc(doc)
    m = int(doc["mesh_shape"][1])
    if not doc["recipe"] or not _is_sharded(doc["recipe"]):
        return None
    plan = plan_for_model(doc["model"], params, batch_stats,
                          model_size=m, recipe=doc["recipe"],
                          stem=doc.get("stem"))
    return None if is_trivial(plan) else plan


def recipe_summary(recipe: Dict[str, str],
                   space: Optional[SearchSpace] = None) -> str:
    """Compact per-layer style string in network order — ``ccrr...``
    with ``c``=column, ``r``=row, ``-``=replicated; ``dp`` for the
    empty (pure data-parallel) recipe."""
    layers = space.layers if space is not None else tuple(recipe)
    if not recipe:
        return "dp"
    return "".join(_STYLE_LETTER.get(recipe.get(p, "replicated"), "?")
                   for p in layers)


def format_search_table(result: SearchResult, model_name: str) -> str:
    """The human-readable candidate table ``--search`` prints: ranked
    survivors first, pruned candidates with their reason after.  First
    line is the schema anchor CI greps for."""
    space = search_space_for(model_name)
    doc = result.doc
    lines = [f"auto-plan search: {model_name} | "
             f"devices={doc['search']['total_devices']} | "
             f"candidates={doc['search']['candidates_considered']} "
             f"(alive {doc['search']['candidates_alive']})"]
    cols = ("mesh", "recipe", "zero", "pred ms/shard", "peak MiB", "status")
    body = []
    for row in result.candidates:
        d, m = row["mesh_shape"]
        status = f"pruned: {row['pruned']}" if row["pruned"] else "ok"
        if row is result.candidates[0] and not row["pruned"]:
            status = "CHOSEN"
        pred = (f"{row['predicted_ms']:.3f}"
                if row.get("predicted_ms") is not None else "-")
        peak = (f"{row['peak_live_bytes'] / 2**20:.1f}"
                if row.get("peak_live_bytes") is not None else "-")
        body.append((f"{d}x{m}", recipe_summary(row["recipe"], space),
                     "on" if row["zero"] else "off", pred, peak, status))
    widths = [max(len(c), *(len(r[i]) for r in body))
              for i, c in enumerate(cols)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines.append(fmt.format(*cols))
    lines += [fmt.format(*row) for row in body]
    if space.layers:
        lines.append("recipe letters (network order): "
                     + ", ".join(space.layers))
    lines.append(f"chosen: mesh {doc['mesh_shape'][0]}x"
                 f"{doc['mesh_shape'][1]} zero="
                 f"{'on' if doc['zero'] else 'off'} "
                 f"{recipe_summary(doc['recipe'], space)} | predicted "
                 f"{doc['predicted_ms_per_step']:.3f} ms/shard | peak "
                 f"{doc['peak_live_bytes'] / 2**20:.1f} MiB/shard")
    return "\n".join(lines)
