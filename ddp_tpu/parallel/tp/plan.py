"""The sharding planner: declarative layer rules -> per-leaf PartitionSpecs.

This is the spec-driven analogue of what ``train/zero.py`` hand-rolls for
the weight update (cf. "Automatic Cross-Replica Sharding of Weight Update
in Data-Parallel Training", arXiv:2004.13336) applied to the parameters
themselves on the ``model`` axis of a 2-D mesh (Mesh-TensorFlow's
formulation, arXiv:1811.02084 — both in PAPERS.md).

A model opts in by declaring a ``TP_RECIPE``: an ordered mapping from
parameter-subtree path (``features/conv0``) to a parallel style —
``column`` (output dimension sharded) or ``row`` (input dimension sharded,
output psum'd).  Back-to-back blocks pair column-then-row so no gather is
needed between them; everything unmatched (norm scales/biases, the
row-parallel biases added after the psum, BN running stats) stays
replicated.  The planner walks the model's *actual* param pytree, emits a
``PartitionSpec`` per leaf, validates every sharded dimension divides by
the ``model``-axis size (all violations reported at once, by name), and
refuses rules that match nothing — the drift guard between a recipe and
the model it describes.

``format_plan_table`` renders the human-readable plan (printed by the CLI
at startup, schema-checked in CI); ``state_shardings`` turns the plan into
the per-leaf ``NamedSharding`` tree the jitted steps and the Trainer's
``device_put`` use — the specs asserted on live arrays in
tests/test_tp.py.
"""
from __future__ import annotations

import importlib
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..mesh import DATA_AXIS, MODEL_AXIS, model_axis_size

# Model registry name -> module name where it differs.
_MODULE_FOR = {"resnet18": "resnet", "tinylm": "transformer"}

STYLES = ("column", "row")
# Styles an EXPLICIT recipe (the auto-plan search's plan-as-data form,
# parallel/tp/autoplan.py) may assign per layer.  "replicated" is the
# explicit no-sharding choice: matched leaves keep P() specs, but the layer
# still appears in ``plan.layers`` so the recipe round-trips through JSON
# unchanged.  Hand TP_RECIPEs simply omit layers they leave replicated.
RECIPE_STYLES = STYLES + ("replicated",)


class TPPlan(NamedTuple):
    """A resolved sharding plan for one (model, model-axis-size) pair."""
    model_name: str
    model_size: int
    param_specs: Any   # pytree of PartitionSpec, same structure as params
    stats_specs: Any   # pytree of PartitionSpec for batch_stats (replicated)
    rows: Tuple       # ((path, style, shape, spec), ...) in table order
    # ((layer path, style), ...) in RECIPE (network) order — the per-layer
    # view the expected-collectives accounting needs (rows are per-leaf and
    # alphabetical, which is neither).
    layers: Tuple = ()
    # The recipe's declared stem (the layer consuming the network input),
    # whose column-style input-gradient psum is dead-code-eliminated in any
    # params-only backward — see expected_collectives.
    stem: Optional[str] = None


def _recipe_for(model_name: str) -> Dict[str, str]:
    mod = importlib.import_module(
        f"ddp_tpu.models.{_MODULE_FOR.get(model_name, model_name)}")
    recipe = getattr(mod, "TP_RECIPE", None)
    if not recipe:
        raise ValueError(
            f"model {model_name!r} declares no TP_RECIPE; tensor "
            "parallelism needs the model to name its column/row-parallel "
            "layer pairs (see models/deepnn.py) — run it on a 1-D mesh, "
            "or add a recipe")
    bad = [s for s in recipe.values() if s not in STYLES]
    if bad:
        raise ValueError(f"unknown TP styles {bad} in {model_name}'s "
                         f"TP_RECIPE; expected one of {STYLES}")
    stem = getattr(mod, "TP_STEM", None)
    if stem is not None and stem not in recipe:
        raise ValueError(
            f"{model_name}'s TP_STEM {stem!r} is not a TP_RECIPE rule; "
            f"the stem must name one of {list(recipe)}")
    return recipe, stem


def _walk(tree: Any, prefix: str, out: List[Tuple[str, Any]]) -> None:
    if isinstance(tree, dict):
        for k in sorted(tree):
            _walk(tree[k], f"{prefix}/{k}" if prefix else k, out)
    else:
        out.append((prefix, tree))


def _leaf_spec(style: str, ndim: int) -> P:
    """The spec a ``column``/``row`` layer's leaf gets, by rank: the
    output dimension is last (conv HWIO / linear [in, out] — the one
    layout the whole codebase uses), the input dimension second-to-last.
    Rank-1 leaves are biases: sharded with the output for ``column``,
    replicated for ``row`` (added once, after the psum)."""
    if ndim == 1:
        return P(MODEL_AXIS) if style == "column" else P()
    dim = ndim - 1 if style == "column" else ndim - 2
    entries = [None] * ndim
    entries[dim] = MODEL_AXIS
    return P(*entries)


def plan_for_model(model_name: str, params, batch_stats=None, *,
                   model_size: int, recipe=None, stem=None) -> TPPlan:
    """Resolve ``model_name``'s TP_RECIPE against its live param pytree.

    ``recipe``/``stem`` override the model module's declarations with an
    explicit per-layer mapping (the auto-plan path,
    parallel/tp/autoplan.py) — same validation, so a searched plan obeys
    exactly the divisibility/drift rules a hand recipe does.  An override
    may also assign ``"replicated"`` explicitly (RECIPE_STYLES).

    Raises ``ValueError`` when the model has no recipe, a rule matches no
    parameter subtree, or any sharded dimension does not divide by
    ``model_size`` — every violation in one message, by leaf path."""
    if model_size < 1:
        raise ValueError(f"model_size must be >= 1, got {model_size}")
    if recipe is None:
        recipe, stem = _recipe_for(model_name)
    else:
        recipe = dict(recipe)
        bad = [s for s in recipe.values() if s not in RECIPE_STYLES]
        if bad:
            raise ValueError(
                f"unknown TP styles {bad} in explicit recipe for "
                f"{model_name!r}; expected one of {RECIPE_STYLES}")
        if stem is not None and stem not in recipe:
            raise ValueError(
                f"explicit stem {stem!r} is not a recipe rule; the stem "
                f"must name one of {list(recipe)}")
        # Canonicalize to network order: ``plan.layers`` order IS the
        # module TP_RECIPE's declaration order, but an explicit recipe
        # round-tripped through a sorted-keys plan doc (tp/autoplan.py)
        # arrives alphabetical.  Re-key by the model's declared order so
        # a searched plan and the hand plan it reproduces are EQUAL,
        # not merely equivalent; layers the module doesn't declare keep
        # their given order after the declared ones.
        mod = importlib.import_module(
            f"ddp_tpu.models.{_MODULE_FOR.get(model_name, model_name)}")
        declared = tuple(getattr(mod, "TP_RECIPE", None) or ())
        recipe = {**{k: recipe[k] for k in declared if k in recipe},
                  **{k: v for k, v in recipe.items() if k not in declared}}
    leaves: List[Tuple[str, Any]] = []
    _walk(params, "", leaves)
    matched = set()
    rows, errors = [], []
    spec_flat: Dict[str, P] = {}
    for path, leaf in leaves:
        style = None
        for prefix, s in recipe.items():
            if path == prefix or path.startswith(prefix + "/"):
                style, _ = s, matched.add(prefix)
                break
        shape = tuple(np.shape(leaf))
        spec = (P() if style in (None, "replicated")
                else _leaf_spec(style, len(shape)))
        for dim, name in enumerate(spec):
            if name == MODEL_AXIS and shape[dim] % model_size:
                errors.append(
                    f"  {path}: dim {dim} extent {shape[dim]} not "
                    f"divisible by model axis size {model_size}")
        rows.append((path, style or "replicated", shape, spec))
        spec_flat[path] = spec
    unmatched = [p for p in recipe if p not in matched]
    if unmatched:
        raise ValueError(
            f"TP_RECIPE rules {unmatched} match no parameter of "
            f"{model_name!r} — the recipe and the model have drifted")
    if errors:
        raise ValueError(
            f"tensor-parallel plan for {model_name!r} is infeasible at "
            f"model axis size {model_size}:\n" + "\n".join(errors))
    param_specs = _unflatten_specs(params, spec_flat)
    stats_specs = jax.tree_util.tree_map(lambda _: P(),
                                         batch_stats or {})
    return TPPlan(model_name, model_size, param_specs, stats_specs,
                  tuple(rows), layers=tuple(recipe.items()), stem=stem)


def _unflatten_specs(params, spec_flat: Dict[str, P]):
    def rebuild(tree, prefix):
        if isinstance(tree, dict):
            return {k: rebuild(v, f"{prefix}/{k}" if prefix else k)
                    for k, v in tree.items()}
        return spec_flat[prefix]
    return rebuild(params, "")


def local_param_count(plan: TPPlan) -> int:
    """Per-model-shard parameter count (sharded leaves contribute 1/m) —
    the flat-vector length the ZeRO composition pads and slices
    (train/zero.py)."""
    n = 0
    for _path, _style, shape, spec in plan.rows:
        size = int(np.prod(shape)) if shape else 1
        if any(e == MODEL_AXIS for e in spec):
            size //= plan.model_size
        n += size
    return n


def expected_collectives(plan: TPPlan, *, backward: bool) -> Dict[str, int]:
    """The model-axis collective budget this plan implies — what the
    static auditor (ddp_tpu/analysis/) checks every traced program
    against, and what :func:`format_plan_table` prints.

    Per layer (parallel/tp/layers.py, Megatron's f/g pair): a ``row``
    layer contributes ONE forward ``psum`` over ``model`` (the partial-sum
    reduction in row_linear/row_conv2d); a ``column`` layer contributes
    ONE backward ``psum`` over ``model`` (``_column_input``'s transpose,
    reducing the input cotangent that row-sharding the next layer leaves
    partial).  A params-only backward — every train step: gradients are
    taken w.r.t. params, never the batch — dead-code-eliminates the STEM
    column layer's input psum (the cotangent it reduces is the batch's,
    which nothing consumes), so the plan must know the stem
    (``TP_STEM`` in the model module) to predict the train-step count
    exactly.  Verified empirically on this runtime: requesting the input
    gradient too restores the elided psum (tests/test_analysis.py).

    Returns ``{"psum_model_fwd", "psum_model_bwd", "psum_model",
    "elided_stem_psum"}`` where ``psum_model`` is fwd (+ bwd when
    ``backward=True``) — the exact count a forward-only program
    (``backward=False``: serve/eval forwards) or a train step
    (``backward=True``) must show in its jaxpr."""
    n_row = sum(1 for _, s in plan.layers if s == "row")
    n_col = sum(1 for _, s in plan.layers if s == "column")
    stem_is_column = any(p == plan.stem and s == "column"
                         for p, s in plan.layers)
    elided = 1 if (backward and stem_is_column) else 0
    bwd = (n_col - elided) if backward else 0
    return {"psum_model_fwd": n_row, "psum_model_bwd": bwd,
            "psum_model": n_row + bwd, "elided_stem_psum": elided}


def expected_collectives_by_layer(plan: TPPlan, *,
                                  backward: bool) -> Dict[str, Dict[str, int]]:
    """The per-layer unit table behind :func:`expected_collectives`: an
    ordered ``{layer path: {"fwd": n, "bwd": n}}`` mapping in recipe
    (network) order.  Each ``row`` layer contributes one forward psum,
    each ``column`` layer one backward psum (``backward=True`` only),
    the declared stem's backward psum is elided.  The totals are — by
    construction, pinned in tests/test_transformer.py — exactly the
    aggregate counts ``expected_collectives`` returns, so an auditor
    mismatch can name WHICH layer's arithmetic changed instead of
    reporting a bare total (the attention-recipe satellite of ISSUE 20).
    """
    table: Dict[str, Dict[str, int]] = {}
    for path, style in plan.layers:
        fwd = 1 if style == "row" else 0
        bwd = (1 if (backward and style == "column"
                     and path != plan.stem) else 0)
        table[path] = {"fwd": fwd, "bwd": bwd}
    return table


def format_collective_table(plan: TPPlan, *, backward: bool) -> str:
    """One line per recipe layer (``path style fwd+bwd``) plus the
    totals — the named breakdown the jaxpr auditor appends to a
    collective-count mismatch so a recipe edit fails with a per-layer
    delta, not a bare number."""
    table = expected_collectives_by_layer(plan, backward=backward)
    styles = dict(plan.layers)
    lines = []
    for path, counts in table.items():
        note = (" (stem: bwd psum elided)"
                if (backward and path == plan.stem
                    and styles.get(path) == "column") else "")
        lines.append(f"    {path} [{styles[path]}]: fwd={counts['fwd']} "
                     f"bwd={counts['bwd']}{note}")
    exp = expected_collectives(plan, backward=backward)
    lines.append(f"    total: fwd={exp['psum_model_fwd']} "
                 f"bwd={exp['psum_model_bwd']} = {exp['psum_model']}")
    return "\n".join(lines)


def is_trivial(plan: TPPlan) -> bool:
    """True when the plan shards nothing (no column/row layer): the
    program it implies is exactly the 1-D data-parallel one.  Callers (the
    auto-plan loader, train/step.py's wiring) run the plain step builders
    for such plans — which is how a model with no ``tp_axis`` forward can
    still carry a searched all-replicated plan."""
    return all(s not in STYLES for _, s in plan.layers)


def recipe_override(plan: TPPlan):
    """The ``tp_recipe`` kwarg this plan implies for ``model.apply``:
    ``None`` when the plan IS the model module's own TP_RECIPE/TP_STEM
    (apply's default — hand plans keep tracing byte-identically, with no
    extra kwarg), the explicit per-layer mapping otherwise (auto plans)."""
    try:
        recipe, stem = _recipe_for(plan.model_name)
    except ValueError:
        return dict(plan.layers)
    if dict(plan.layers) == dict(recipe) and plan.stem == stem:
        return None
    return dict(plan.layers)


def state_shardings(plan: TPPlan, mesh: Mesh, *, zero: bool = False):
    """Per-leaf ``NamedSharding`` tree for a ``TrainState`` under this
    plan: params/momentum follow the plan's specs (the elementwise SGD
    update preserves them), batch_stats and the step counter are
    replicated.  ``zero=True`` swaps the momentum for the ZeRO flat
    buffer's ``P(model, data)`` spec (train/zero.py's [m, L] layout — the
    spec-merge of params-along-``model`` with update-along-``data``)."""
    if model_axis_size(mesh) != plan.model_size:
        raise ValueError(
            f"plan was resolved for model axis size {plan.model_size}, "
            f"mesh has {model_axis_size(mesh)}")
    from ...optim.sgd import SGDState
    from ...train.step import TrainState
    sh = lambda spec: NamedSharding(mesh, spec)  # noqa: E731
    params = jax.tree_util.tree_map(sh, plan.param_specs)
    stats = jax.tree_util.tree_map(sh, plan.stats_specs)
    opt = (SGDState(sh(P(MODEL_AXIS, DATA_AXIS))) if zero
           else SGDState(params))
    return TrainState(params=params, batch_stats=stats, opt_state=opt,
                      step=sh(P()))


def spec_to_json(spec: P) -> list:
    """A ``PartitionSpec`` as a JSON-serializable entry list — the spec
    plumbing the sharded-checkpoint manifest records per leaf
    (train/ckpt_shard.py).  Entries: ``None``, an axis name, or a list of
    axis names (the general PartitionSpec grammar, even though this
    codebase's plans only emit single names)."""
    out: list = []
    for entry in tuple(spec):
        if entry is None or isinstance(entry, str):
            out.append(entry)
        else:
            out.append(list(entry))
    return out


def spec_from_json(entries) -> P:
    """Inverse of :func:`spec_to_json`."""
    return P(*(tuple(e) if isinstance(e, list) else e
               for e in (entries or [])))


def state_specs(plan: TPPlan, *, zero: bool = False):
    """Same tree as :func:`state_shardings` but bare ``PartitionSpec``s —
    the ``shard_map`` in/out_specs form."""
    from ...optim.sgd import SGDState
    from ...train.step import TrainState
    opt = (SGDState(P(MODEL_AXIS, DATA_AXIS)) if zero
           else SGDState(plan.param_specs))
    return TrainState(params=plan.param_specs, batch_stats=plan.stats_specs,
                      opt_state=opt, step=P())


_STYLE_COLLECTIVE = {"column": "psum(model)@bwd", "row": "psum(model)@fwd",
                     "replicated": "-"}


def _layer_of(plan: TPPlan, path: str) -> Optional[str]:
    for prefix, _style in plan.layers:
        if path == prefix or path.startswith(prefix + "/"):
            return prefix
    return None


def format_plan_table(plan: TPPlan,
                      layer_costs: Optional[Dict[str, int]] = None) -> str:
    """The human-readable plan: one row per leaf (path, style, shape,
    spec, per-shard shape, the layer's model-axis collective), then the
    totals line and the expected-collectives line the static auditor
    checks traced programs against.  First line is the schema anchor CI
    greps for.

    ``layer_costs`` (``{recipe layer path: forward flops per image}``,
    from ``analysis.costmodel.layer_forward_costs``) adds the predicted
    per-layer cost column: THIS SHARD's forward MFLOPs per image (a
    column/row layer computes 1/m of the layer; replicated leaves
    compute all of it), printed on the layer's first leaf row, plus the
    ``predicted cost:`` footer totals — schema-checked in CI like the
    expected-collectives line."""
    header = (f"tensor-parallel plan: {plan.model_name} | "
              f"model axis m={plan.model_size}")
    cols = ("leaf", "style", "shape", "spec", "per-shard", "collectives")
    if layer_costs is not None:
        cols += ("fwd-mflop",)
    body = []
    total = sharded = 0
    costed: set = set()
    for path, style, shape, spec in plan.rows:
        local = tuple(s // plan.model_size if e == MODEL_AXIS else s
                      for s, e in zip(shape,
                                      tuple(spec) + (None,) * len(shape)))
        size = int(np.prod(shape)) if shape else 1
        total += size
        if any(e == MODEL_AXIS for e in spec):
            sharded += size
        row = (path, style, str(shape), str(spec), str(local),
               _STYLE_COLLECTIVE[style])
        if layer_costs is not None:
            layer = _layer_of(plan, path)
            cell = "-"
            if (layer is not None and layer not in costed
                    and layer in layer_costs):
                costed.add(layer)
                shard_div = plan.model_size if style in STYLES else 1
                cell = f"{layer_costs[layer] / shard_div / 1e6:.2f}"
            row += (cell,)
        body.append(row)
    widths = [max(len(c), *(len(r[i]) for r in body))
              for i, c in enumerate(cols)]
    fmt = "  ".join(f"{{:<{w}}}" for w in widths)
    lines = [header, fmt.format(*cols)]
    lines += [fmt.format(*row) for row in body]
    pct = 100.0 * sharded / max(total, 1)
    lines.append(f"total {total:,} params | sharded {sharded:,} "
                 f"({pct:.2f}%) | replicated {total - sharded:,}")
    if layer_costs is not None:
        full = sum(layer_costs.values())
        per_shard = sum(
            flops / (plan.model_size if style in STYLES else 1)
            for (layer, style) in plan.layers
            for flops in (layer_costs.get(layer),) if flops is not None)
        lines.append(f"predicted cost: fwd {full / 1e6:.2f} MFLOP/img "
                     f"unsharded | {per_shard / 1e6:.2f} MFLOP/img per "
                     f"model shard")
    exp = expected_collectives(plan, backward=True)
    elision = (f" (stem {plan.stem}: input-grad psum elided)"
               if exp["elided_stem_psum"] else "")
    lines.append(f"expected collectives: psum(model) "
                 f"fwd={exp['psum_model_fwd']} bwd={exp['psum_model_bwd']} "
                 f"train={exp['psum_model']}{elision}")
    return "\n".join(lines)
