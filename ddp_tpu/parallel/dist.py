"""Multi-host runtime init — the TPU-native ``ddp_setup`` (multigpu.py:24-33).

The reference rendezvous is env-var TCP (``MASTER_ADDR=localhost``,
``MASTER_PORT=12355``, multigpu.py:30-31) followed by
``init_process_group(backend="nccl")``.  On TPU the same role is played by
``jax.distributed.initialize``: a coordinator address plus process count/id,
after which every host sees the full global device set and XLA owns the
collective schedule.  Single-host runs need no initialization at all — the
mesh over local devices just works — so this module no-ops unless a
multi-host environment is configured.

Env surface (mirroring the reference's MASTER_ADDR/MASTER_PORT knobs):
  DDP_TPU_COORDINATOR   "host:port" of process 0
  DDP_TPU_NUM_PROCESSES total host count
  DDP_TPU_PROCESS_ID    this host's id
On TPU pods proper these are auto-detected by JAX from the pod metadata, so
``initialize()`` with no env set simply calls through when JAX can
self-configure, and silently stays single-host otherwise.
"""
from __future__ import annotations

import os
import sys
from typing import Optional

import jax

_initialized = False


def initialize(coordinator: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Idempotent multi-host init (reference multigpu.py:32)."""
    global _initialized
    if _initialized:
        return
    coordinator = coordinator or os.environ.get("DDP_TPU_COORDINATOR")
    num_processes = num_processes if num_processes is not None else (
        int(os.environ["DDP_TPU_NUM_PROCESSES"])
        if "DDP_TPU_NUM_PROCESSES" in os.environ else None)
    process_id = process_id if process_id is not None else (
        int(os.environ["DDP_TPU_PROCESS_ID"])
        if "DDP_TPU_PROCESS_ID" in os.environ else None)
    if coordinator is None and num_processes is None:
        if _on_multiworker_tpu_pod():
            # TPU pod with no explicit env: JAX self-configures from the
            # pod metadata (coordinator, process count/id all auto).
            try:
                jax.distributed.initialize()
                _initialized = True
            except RuntimeError as e:
                # Backend already initialised (e.g. a host that probed
                # devices first) — proceed single-host rather than abort,
                # but LOUDLY: in a genuinely multi-worker pod, N hosts
                # degrading to single-host means N independent models
                # training in silence.
                print(
                    "WARNING: multi-worker TPU pod detected but "
                    f"jax.distributed.initialize() failed ({e!r}); "
                    "proceeding SINGLE-HOST. If this is a real pod, every "
                    "worker is now training an independent model — fix the "
                    "rendezvous (or set DDP_TPU_COORDINATOR/"
                    "DDP_TPU_NUM_PROCESSES/DDP_TPU_PROCESS_ID) and restart.",
                    file=sys.stderr)
        return  # plain single-host: nothing to rendezvous
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True


def _on_multiworker_tpu_pod() -> bool:
    """True only in a genuinely multi-worker TPU environment.  Single-worker
    markers (``TPU_WORKER_ID=0`` alone, as some single-chip runtimes set)
    must NOT trigger auto-init, or a rendezvous is attempted that can never
    complete / clashes with an already-initialised backend."""
    if "MEGASCALE_COORDINATOR_ADDRESS" in os.environ:
        return True
    hosts = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return len([h for h in hosts.split(",") if h]) > 1


def shutdown() -> None:
    """Reference ``destroy_process_group()`` (multigpu.py:250)."""
    global _initialized
    if _initialized:
        jax.distributed.shutdown()
        _initialized = False


# The jax._src.distributed.global_state attributes the non-blocking
# abort() fast path drops.  Named once so the runtime check in abort(),
# the readiness probe below, and the tier-1 canary test
# (tests/test_resilience.py::test_abort_fast_path_canary — VERDICT r5 #3)
# all pin the same contract: if a JAX upgrade moves these, the canary
# fails FAST instead of every multi-host abort silently becoming a 300 s
# graceful-shutdown hang.
_ABORT_FAST_PATH_ATTRS = ("preemption_sync_manager", "client", "service")


def abort_fast_path_ready() -> bool:
    """True when the private-internals layout :func:`abort` relies on is
    present on this JAX build (the canary's assertion)."""
    try:
        from jax._src import distributed as _internal
        state = _internal.global_state
    except Exception:
        return False
    return all(hasattr(state, a) for a in _ABORT_FAST_PATH_ATTRS)


def preemption_sync_manager():
    """The runtime's preemption sync manager (created by
    ``jax.distributed.initialize``), or None single-host / on internal
    layout drift — resilience/preemption.py polls it so preemption notices
    delivered below Python join the coordinated-checkpoint decision."""
    try:
        from jax._src import distributed as _internal
        return _internal.global_state.preemption_sync_manager
    except Exception:
        return None


def abort() -> None:
    """NON-GRACEFUL distributed teardown for abort paths — never blocks.

    ``jax.distributed.shutdown()`` is the graceful teardown: it enters a
    shutdown barrier and blocks up to ``shutdown_timeout_seconds`` (300 s
    default) for every other process to arrive — but the peers an abort
    path exists to unblock are stuck in a collective waiting for US, so
    the graceful path rides the full timeout (measured: a 2-process CPU
    run hangs its peer the whole 300 s).  Dropping the runtime-state
    references instead is instant for this process, and the peers abort
    promptly: their in-flight gloo collective fails in ~30 s on the CPU
    harness (measured), and the coordination service's error-poll /
    heartbeat machinery (<=100 s) is the backstop on real pods — when the
    failing process owns the service (rank 0), dropping it broadcasts
    UNAVAILABLE to every polling peer immediately.  Works regardless of
    whether :func:`initialize` here or the launcher did the init."""
    global _initialized
    _initialized = False
    try:
        from jax._src import distributed as _internal
        state = _internal.global_state
        for attr in _ABORT_FAST_PATH_ATTRS:
            if not hasattr(state, attr):
                # Plain setattr cannot fail on this class, so layout
                # drift must be DETECTED, not absorbed — a silently
                # dead-attribute "abort" would leave the real client
                # alive to block interpreter finalization.
                raise AttributeError(attr)
        state.preemption_sync_manager = None
        state.client = None  # destructor skips the shutdown barrier
        state.service = None
    except Exception:  # internal layout moved: last resort, may block
        try:
            jax.distributed.shutdown()
        except (RuntimeError, ValueError):
            pass


def process_index() -> int:
    """Rank of this host — gates checkpoint writes (multigpu.py:118)."""
    return jax.process_index()


def process_count() -> int:
    return jax.process_count()
