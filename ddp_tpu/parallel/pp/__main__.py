"""``python -m ddp_tpu.parallel.pp`` — the offline stage table.

The pipeline analogue of ``python -m ddp_tpu.parallel.tp``: resolve the
model's PP_BLOCKS into a balanced ``--stages``-way cut (priced with the
auto-plan cost model's per-layer forward flops), print the stage table the
CLI prints at startup under a 3-D ``--mesh_shape``, and exit non-zero on
an infeasible partition — so layouts can be sanity-checked without
owning a single chip.  ``--model_size`` restricts the cut set exactly as
the live (d, m, s) mesh would; ``--microbatches`` adds the
predicted-bubble footer the bench compares measured fractions against.
"""
from __future__ import annotations

import argparse
import sys

import jax

from .partition import format_stage_table, plan_stages


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m ddp_tpu.parallel.pp",
        description="pipeline stage partitioner (offline stage table)")
    ap.add_argument("--model", default="deepnn")
    ap.add_argument("--stages", type=int, default=2,
                    help="stage count s (the mesh's third axis)")
    ap.add_argument("--model_size", type=int, default=1,
                    help="tensor-parallel m the stages compose with "
                         "(restricts cut points to full-width boundaries)")
    ap.add_argument("--microbatches", type=int, default=None,
                    help="print the predicted bubble fraction at this "
                         "microbatch count")
    args = ap.parse_args(argv)

    from ...models import get_model
    try:
        model = get_model(args.model)
        params, batch_stats = model.init(jax.random.key(0))
        plan = plan_stages(args.model, args.stages,
                           model_size=args.model_size, params=params,
                           batch_stats=batch_stats)
    except ValueError as e:
        print(e, file=sys.stderr)
        return 1
    print(format_stage_table(plan, num_micro=args.microbatches))
    return 0


if __name__ == "__main__":
    sys.exit(main())
