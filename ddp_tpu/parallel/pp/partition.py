"""Stage partitioner — the pipeline analogue of ``tp/plan.py``.

A model opts in by declaring ``PP_BLOCKS``: its forward as an ordered
tuple of cut-able units (each a TP_RECIPE layer plus its trailing
elementwise/pool/reshape ops — models/deepnn.py), so a cut between any
two blocks is a clean activation handoff.  :func:`plan_stages` picks the
balanced contiguous s-way partition of that list, priced with the SAME
per-layer forward-flop table the tp auto-planner uses
(``analysis/costmodel.layer_forward_costs``) — min-max stage cost over
the valid cut set, every constraint violation reported at once, and a
printed stage table (:func:`format_stage_table`) whose first line is the
schema anchor CI greps for, exactly like the tp plan table.

Under tensor parallelism (m > 1) not every boundary is cut-able: a
``column`` layer's output activation is model-sharded, and a pipeline cut
there would hand a sharded activation to a different device set — the
model's ``PP_SHARDED_OUT`` names those blocks and the planner rejects
cuts after them (for deepnn this leaves the row layers conv1/conv3 and
the classifier boundary, which is also where the cheap activations are).
"""
from __future__ import annotations

import importlib
from typing import Dict, NamedTuple, Optional, Tuple

# Registry name -> module name where it differs (same map as tp/plan.py).
_MODULE_FOR = {"resnet18": "resnet", "tinylm": "transformer"}


class StagePlan(NamedTuple):
    """A resolved s-way stage partition for one model."""
    model_name: str
    num_stages: int
    # ((lo, hi), ...) half-open PP_BLOCKS index ranges, one per stage,
    # covering the whole block list contiguously.
    stages: Tuple[Tuple[int, int], ...]
    block_names: Tuple[str, ...]          # the model's PP_BLOCKS
    # Per-stage summed forward flops/image (the balance the cut minimises).
    stage_costs: Tuple[float, ...]
    uniform_costs: bool = False           # True when no cost table matched


def _blocks_for(model_name: str):
    mod = importlib.import_module(
        f"ddp_tpu.models.{_MODULE_FOR.get(model_name, model_name)}")
    return (getattr(mod, "PP_BLOCKS", None),
            tuple(getattr(mod, "PP_SHARDED_OUT", ()) or ()))


def block_costs(model_name: str, params=None, batch_stats=None,
                ) -> Optional[Dict[str, float]]:
    """``{block name: forward flops/image}`` from the auto-plan cost model
    (``analysis/costmodel.layer_forward_costs`` — block names ARE recipe
    layer paths), or None when the model has no recipe, no params were
    given, or the trace doesn't map 1:1 onto the recipe."""
    if params is None:
        return None
    from ...models import get_model
    from ...parallel.tp.plan import plan_for_model
    from ...analysis.costmodel import layer_forward_costs
    model = get_model(model_name)
    try:
        plan = plan_for_model(model_name, params, batch_stats,
                              model_size=1)
    except ValueError:
        return None
    table = layer_forward_costs(model, plan, params, batch_stats or {})
    return None if table is None else {k: float(v) for k, v in table.items()}


def plan_stages(model_name: str, num_stages: int, *, model_size: int = 1,
                params=None, batch_stats=None,
                costs: Optional[Dict[str, float]] = None) -> StagePlan:
    """Resolve the balanced ``num_stages``-way cut of ``model_name``'s
    PP_BLOCKS.  ``model_size`` (the mesh's m) restricts the cut set to
    full-width activation boundaries; ``costs`` overrides the cost-model
    table (tests inject synthetic imbalance with it).  Every violation is
    reported at once, tp-planner style."""
    errors = []
    s = int(num_stages)
    blocks, sharded_out = _blocks_for(model_name)
    if not blocks:
        raise ValueError(
            f"model {model_name!r} declares no PP_BLOCKS; pipeline "
            "parallelism needs the model's forward as an ordered block "
            "list (see models/deepnn.py) — run it with stage axis s=1, "
            "or add the block list")
    if s < 1:
        errors.append(f"stage count must be positive, got {num_stages}")
    if s > len(blocks):
        errors.append(
            f"stage count {s} exceeds the model's {len(blocks)} blocks "
            f"({', '.join(blocks)}) — there are not enough cut points")
    # Valid cut points: the boundary AFTER block i (i in 0..n-2).  Under
    # m > 1 a cut after a model-sharded-output block is invalid.
    n = len(blocks)
    valid = [i for i in range(n - 1)
             if not (model_size > 1 and blocks[i] in sharded_out)]
    if not errors and s - 1 > len(valid):
        banned = [b for b in blocks[:-1] if b in sharded_out]
        errors.append(
            f"stage count {s} needs {s - 1} cut points but only "
            f"{len(valid)} boundaries hand over a full-width activation "
            f"under model axis m={model_size} (cuts after column layers "
            f"{banned} would hand over a model-sharded activation)")
    if errors:
        raise ValueError(
            f"cannot cut {model_name!r} into {num_stages} pipeline "
            f"stage(s) at model axis size {model_size}:\n"
            + "\n".join(f"  - {e}" for e in errors))

    if costs is None:
        costs = block_costs(model_name, params, batch_stats)
    uniform = costs is None
    per_block = ([1.0] * n if uniform
                 else [float(costs.get(b, 0.0)) for b in blocks])

    cuts = _balanced_cuts(per_block, s, set(valid))
    bounds = [0] + [c + 1 for c in cuts] + [n]
    stages = tuple((bounds[i], bounds[i + 1]) for i in range(s))
    stage_costs = tuple(float(sum(per_block[lo:hi])) for lo, hi in stages)
    return StagePlan(model_name, s, stages, tuple(blocks), stage_costs,
                     uniform_costs=uniform)


def _balanced_cuts(per_block, s: int, valid: set) -> Tuple[int, ...]:
    """The s-1 cut points (boundary indices, 'after block i') minimising
    the maximum stage cost over contiguous partitions whose every cut is
    in ``valid`` — exact DP over (block prefix, stages used); the block
    lists are a handful of entries, so O(n^2 s) is nothing."""
    n = len(per_block)
    prefix = [0.0]
    for c in per_block:
        prefix.append(prefix[-1] + c)

    def seg(i, j):  # cost of blocks [i, j)
        return prefix[j] - prefix[i]

    INF = float("inf")
    # best[k][j] = minimal max-stage-cost cutting blocks [0, j) into k
    # stages; arg[k][j] = the i achieving it (last stage is [i, j)).
    best = [[INF] * (n + 1) for _ in range(s + 1)]
    arg = [[0] * (n + 1) for _ in range(s + 1)]
    best[0][0] = 0.0
    for k in range(1, s + 1):
        for j in range(1, n + 1):
            for i in range(k - 1, j):
                # the cut before this last stage sits after block i-1
                if k > 1 and (i - 1) not in valid:
                    continue
                cand = max(best[k - 1][i], seg(i, j))
                if cand < best[k][j]:
                    best[k][j] = cand
                    arg[k][j] = i
    cuts = []
    j = n
    for k in range(s, 1, -1):
        i = arg[k][j]
        cuts.append(i - 1)
        j = i
    return tuple(reversed(cuts))


def predicted_bubble(num_stages: int, num_micro: int) -> float:
    """The schedule's static bubble fraction, (s-1)/(A+s-1): of the
    A+s-1 pipeline clocks a full fwd+bwd wave needs, s-1 are ramp
    (identical for GPipe and 1F1B at uniform stage cost — 1F1B's win is
    in-flight activation MEMORY, min(s,A) vs A live micro-batches, not
    bubble time)."""
    s, a = int(num_stages), int(num_micro)
    if s < 1 or a < 1:
        raise ValueError(f"need s>=1 and A>=1, got s={num_stages}, "
                         f"A={num_micro}")
    return (s - 1) / (a + s - 1)


def stage_param_paths(plan: StagePlan, k: int) -> Tuple[Tuple[str, ...],
                                                        ...]:
    """Param-tree paths owned by stage ``k`` — block name ``"a/b"`` IS
    subtree ``params["a"]["b"]`` (the PP_BLOCKS contract)."""
    lo, hi = plan.stages[k]
    return tuple(tuple(name.split("/")) for name in plan.block_names[lo:hi])


def stage_subtree(plan: StagePlan, k: int, tree):
    """Stage ``k``'s slice of a params-shaped pytree: the same dict shape
    with only that stage's block subtrees present."""
    out: dict = {}
    for path in stage_param_paths(plan, k):
        node = tree
        for key in path:
            node = node[key]
        dst = out
        for key in path[:-1]:
            dst = dst.setdefault(key, {})
        dst[path[-1]] = node
    return out


def merge_subtrees(parts) -> dict:
    """Inverse of :func:`stage_subtree`: reassemble the full params-shaped
    tree from the per-stage slices."""
    out: dict = {}

    def merge(dst, src):
        for key, v in src.items():
            if isinstance(v, dict):
                merge(dst.setdefault(key, {}), v)
            else:
                dst[key] = v

    for part in parts:
        merge(out, part)
    return out


def stage_model_psums(plan: StagePlan, tp_plan, k: int, *,
                      role: str) -> int:
    """The ``psum``-over-``model`` count stage ``k``'s ``role`` program
    must show — the per-stage slice of ``tp/plan.expected_collectives``'s
    accounting, which the static auditor checks each staged jaxpr against
    (analysis/jaxpr_audit.py, kind ``pp_*``).

    Per layer: a ``row`` layer psums once in the forward, a ``column``
    layer once in the backward (the input-cotangent reduction).  A stage
    backward re-runs its forward under ``jax.vjp`` (recompute-style), so
    ``backward`` counts BOTH contributions; stage 0 differentiates
    w.r.t. params only, which dead-code-eliminates the stem column
    layer's input psum exactly as in the unstaged train step.  The
    fused last-stage ``fwdbwd`` requests the input cotangent, so nothing
    elides.  ``update`` programs are collective-free on every axis: the
    grads arrive pre-reduced."""
    if role not in ("forward", "backward", "fwdbwd", "update"):
        raise ValueError(f"unknown stage program role {role!r}")
    if tp_plan is None or role == "update":
        return 0
    lo, hi = plan.stages[k]
    names = plan.block_names[lo:hi]

    def under(layer: str) -> bool:
        # A recipe layer belongs to the stage owning its block.  Fine-
        # grained models (deepnn) name recipe layers AS blocks (layer ==
        # block); coarse models (transformer) put several recipe layers
        # UNDER one block ("blocks/block0" owns "blocks/block0/attn/qkv"
        # etc.) — same prefix rule the tp planner applies to param paths.
        return any(layer == b or layer.startswith(b + "/") for b in names)

    layers = [(p, s) for p, s in tp_plan.layers if under(p)]
    n_row = sum(1 for _, s in layers if s == "row")
    n_col = sum(1 for _, s in layers if s == "column")
    if role == "forward":
        return n_row
    if role == "fwdbwd":
        return n_row + n_col
    elide = (k == 0 and any(p == tp_plan.stem and s == "column"
                            for p, s in layers))
    return n_row + n_col - (1 if elide else 0)


def format_stage_table(plan: StagePlan,
                       num_micro: Optional[int] = None) -> str:
    """The human-readable stage plan: one row per stage (index, block
    range, per-stage summed fwd MFLOPs/image, share of total), then the
    balance line and — given the microbatch count — the predicted-bubble
    line the bench compares its measured fraction against.  First line is
    the schema anchor CI greps for, tp-plan-table style."""
    header = (f"pipeline-stage plan: {plan.model_name} | "
              f"stage axis s={plan.num_stages}")
    cols = ("stage", "blocks", "fwd-mflop", "share")
    total = sum(plan.stage_costs) or 1.0
    body = []
    for k, (lo, hi) in enumerate(plan.stages):
        names = plan.block_names[lo:hi]
        span = (names[0] if len(names) == 1
                else f"{names[0]} .. {names[-1]}")
        cost = plan.stage_costs[k]
        cell = "-" if plan.uniform_costs else f"{cost / 1e6:.2f}"
        body.append((str(k), f"[{lo}:{hi}) {span}", cell,
                     f"{100.0 * cost / total:.1f}%"))
    widths = [max(len(r[i]) for r in [cols] + body)
              for i in range(len(cols))]
    lines = [header,
             "  ".join(c.ljust(w) for c, w in zip(cols, widths))]
    lines += ["  ".join(c.ljust(w) for c, w in zip(r, widths))
              for r in body]
    imbalance = (max(plan.stage_costs) / (total / plan.num_stages)
                 if total else 1.0)
    lines.append(
        f"balance: max-stage/mean-stage = {imbalance:.3f}"
        + (" (uniform fallback: no cost table for this model)"
           if plan.uniform_costs else ""))
    if num_micro is not None:
        lines.append(
            f"predicted bubble: {predicted_bubble(plan.num_stages, num_micro):.3f}"
            f" at A={int(num_micro)} microbatches ((s-1)/(A+s-1))")
    return "\n".join(lines)
