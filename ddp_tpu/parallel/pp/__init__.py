"""Pipeline parallelism over the third ``stage`` mesh axis.

``partition.py`` cuts the model's declared PP_BLOCKS into balanced
contiguous stages with the auto-plan cost model's per-layer flop table;
``schedule.py`` runs GPipe / 1F1B microbatch schedules as per-stage jitted
programs over the (data × model) submesh of each stage, handing
activations across stages with explicit device transfers.  ``python -m
ddp_tpu.parallel.pp`` prints the offline stage table.
"""
from .partition import (StagePlan, format_stage_table, plan_stages,
                        predicted_bubble, stage_model_psums,
                        stage_param_paths, stage_subtree)
from .schedule import make_pp_step, place_state, pp_shard_fn, stage_submesh

__all__ = [
    "StagePlan", "format_stage_table", "plan_stages", "predicted_bubble",
    "stage_model_psums", "stage_param_paths", "stage_subtree",
    "make_pp_step", "place_state", "pp_shard_fn", "stage_submesh",
]
