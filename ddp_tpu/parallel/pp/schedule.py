"""GPipe / 1F1B microbatch schedules as per-stage jitted programs.

The 3-D mesh ``(data, model, stage)`` is a stack of s identical 2-D
(data × model) submeshes (:func:`stage_submesh`).  Each stage owns the
param/momentum subtrees of its PP_BLOCKS range and runs its OWN jitted
shard_map programs over its submesh — forward, backward (recompute-style:
the backward re-runs the stage forward under ``jax.vjp``, so no residual
crosses a stage boundary), a fused forward+backward on the last stage
(where the loss lives), and one SGD update per stage.  Activations and
cotangents cross stages as explicit ``jax.device_put`` transfers onto the
neighbour submesh — MPMD handoff, not a collective, so the staged
programs' jaxprs stay 2-D and the static auditor's collective invariants
apply per stage (analysis/jaxpr_audit.py).

Numerics are the tensor-parallel replicated-update core's, cut at block
boundaries: every stage differentiates its slice of the collective-free
LOCAL objective ``ce_sum/(count*d)`` (train/zero.py:_make_local_grads),
param grads are psum'd over ``data`` inside the owning stage's program,
and per-stage ``gsum``/``lsum`` accumulate in micro-batch order 0..A-1
from zeros — exactly :func:`~ddp_tpu.train.step.make_accum_scan`'s
accumulation, which is why (d,m,s) is bit-compatible with the (d,m)
accum step (tests/test_pp.py pins it) and why GPipe and 1F1B agree
bitwise (same per-stage accumulation order; 1F1B only changes WHEN work
is enqueued, bounding in-flight activations at min(s,A) instead of A).

RNG discipline is the shared fold structure: per-step key folded by step
then by ``axis_index(data)`` inside every stage's shard_map, per-micro
``mrng = fold_in(rng, k)``, augmentation stream ``fold_in(mrng, 1)`` —
so dropout/augmentation draw the same bits as the unstaged program no
matter which stage they land in.
"""
from __future__ import annotations

import importlib
import time
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...optim import sgd as sgd_lib
from ...ops.losses import cross_entropy_sum_count
from ...utils.compat import vma_semantics  # installs the shard_map shim
from ..mesh import (DATA_AXIS, MODEL_AXIS, STAGE_AXIS, data_axis_size,
                    stage_axis_size)
from .partition import (StagePlan, _MODULE_FOR, merge_subtrees,
                        predicted_bubble, stage_subtree)

del vma_semantics  # imported for the side effect only


def stage_submesh(mesh: Mesh, k: int) -> Mesh:
    """Stage ``k``'s 2-D (data × model) submesh — the device plane at
    stage coordinate k.  Rows keep their data coordinates, so
    ``axis_index(data)`` (and therefore every RNG fold) agrees with the
    full mesh."""
    if STAGE_AXIS not in mesh.axis_names:
        raise ValueError(
            f"mesh has axes {mesh.axis_names}; a pipeline needs the "
            f"'{STAGE_AXIS}' axis (make_mesh(shape=(d, m, s)))")
    idx = mesh.axis_names.index(STAGE_AXIS)
    s = stage_axis_size(mesh)
    if not 0 <= k < s:
        raise ValueError(f"stage {k} out of range for stage axis size {s}")
    devs = np.take(mesh.devices, k, axis=idx)
    return Mesh(devs, tuple(n for n in mesh.axis_names if n != STAGE_AXIS))


def schedule_ops(kind: str, num_micro: int, num_stages: int):
    """The enqueue order: a list of ``("F", j, k)`` / ``("B", j, k)`` /
    ``("FB", k)`` ops (stage j, micro k; the last stage always runs the
    fused FB).  Both schedules respect the same dependencies — F(j,k)
    after F(j-1,k), B(j,k) after B(j+1,k)/FB(k), per-stage micros in
    order — so they are numerically interchangeable; they differ in how
    long forward activations stay alive (GPipe: all A per stage; 1F1B:
    min(s, A))."""
    a, s = int(num_micro), int(num_stages)
    if kind not in ("gpipe", "1f1b"):
        raise ValueError(f"unknown pipeline schedule {kind!r}; "
                         "expected 'gpipe' or '1f1b'")
    if s < 2:
        raise ValueError(f"a pipeline schedule needs s>=2 stages, got {s}")
    if kind == "gpipe":
        ops = [("F", j, k) for k in range(a) for j in range(s - 1)]
        for k in range(a):
            ops.append(("FB", k))
            ops.extend(("B", j, k) for j in range(s - 2, -1, -1))
        return ops
    # 1F1B: per-stage local sequences (warmup forwards, then strict
    # backward/forward alternation), merged into one dependency-
    # respecting enqueue order.
    local = []
    for j in range(s - 1):
        warm = min(a, s - 1 - j)
        seq = [("F", j, f) for f in range(warm)]
        fw, bw = warm, 0
        while bw < a:
            seq.append(("B", j, bw))
            bw += 1
            if fw < a:
                seq.append(("F", j, fw))
                fw += 1
        local.append(seq)
    local.append([("FB", k) for k in range(a)])

    done = set()

    def ready(op):
        if op[0] == "F":
            _, j, k = op
            return j == 0 or ("F", j - 1, k) in done
        if op[0] == "FB":
            return ("F", s - 2, op[1]) in done
        _, j, k = op
        return (("FB", k) if j == s - 2 else ("B", j + 1, k)) in done

    ptr = [0] * s
    ops = []
    total = sum(len(q) for q in local)
    while len(ops) < total:
        progressed = False
        for j in range(s):
            if ptr[j] < len(local[j]) and ready(local[j][ptr[j]]):
                op = local[j][ptr[j]]
                ops.append(op)
                done.add(op)
                ptr[j] += 1
                progressed = True
        if not progressed:  # pragma: no cover - schedule bug backstop
            raise RuntimeError("1F1B schedule deadlocked; per-stage "
                               f"pointers {ptr}")
    return ops


def _apply_blocks_for(model_name: str):
    mod = importlib.import_module(
        f"ddp_tpu.models.{_MODULE_FOR.get(model_name, model_name)}")
    fn = getattr(mod, "apply_blocks", None)
    if fn is None:
        raise ValueError(
            f"model {model_name!r} has no apply_blocks; pipeline stages "
            "need the block-range forward (see models/deepnn.py)")
    return fn


def _specs_like(tree, spec_tree):
    if spec_tree is not None:
        return spec_tree
    return jax.tree_util.tree_map(lambda _: P(), tree)


def place_state(state, mesh: Mesh, pp_plan: StagePlan, tp_plan=None):
    """Place a (host or replicated) TrainState onto its pipeline layout:
    each stage's param/momentum subtree lands on that stage's submesh
    with the tp plan's per-leaf specs (P() without a non-trivial plan).
    The step counter and batch_stats stay as they are — the canonical
    checkpoint format is unchanged, which is what makes any (d,m,s)
    snapshot restore onto any (d',m',s')."""
    from ..tp.plan import is_trivial
    use_tp = tp_plan is not None and not is_trivial(tp_plan)
    params_parts, mom_parts = [], []
    for k in range(pp_plan.num_stages):
        sub = stage_submesh(mesh, k)
        spec_sub = (stage_subtree(pp_plan, k, tp_plan.param_specs)
                    if use_tp else None)
        p_sub = stage_subtree(pp_plan, k, state.params)
        m_sub = stage_subtree(pp_plan, k, state.opt_state.momentum_buf)
        shard = jax.tree_util.tree_map(
            lambda s: NamedSharding(sub, s), _specs_like(p_sub, spec_sub))
        params_parts.append(jax.device_put(p_sub, shard))
        mom_parts.append(jax.device_put(m_sub, shard))
    from ...train.step import TrainState
    return TrainState(merge_subtrees(params_parts), state.batch_stats,
                      sgd_lib.SGDState(merge_subtrees(mom_parts)),
                      state.step)


def pp_shard_fn(pp_plan: StagePlan):
    """``shard_fn(batch, mesh)`` for the prefetch stream: the stacked
    ``[A, B, ...]`` images land on stage 0's submesh (split on ``data``),
    the labels on the last stage's (where the loss lives) — the pipeline
    reuses the grad-accum group stream as its microbatch injector."""

    def shard(batch: dict, mesh: Mesh) -> dict:
        sub0 = stage_submesh(mesh, 0)
        sublast = stage_submesh(mesh, pp_plan.num_stages - 1)
        return {
            "image": jax.device_put(
                batch["image"], NamedSharding(sub0, P(None, DATA_AXIS))),
            "label": jax.device_put(
                batch["label"], NamedSharding(sublast,
                                              P(None, DATA_AXIS))),
        }

    return shard


def eval_params_for(state, pp_plan: StagePlan, tp_plan, eval_mesh: Mesh):
    """Gather the stage-scattered params/stats back onto ONE 2-D mesh for
    evaluation: host round-trip (stages live on disjoint device sets), then
    the tp placement evaluate() expects on ``eval_mesh``."""
    from ..tp.plan import is_trivial
    params, stats = jax.device_get((state.params, state.batch_stats))
    if tp_plan is not None and not is_trivial(tp_plan):
        shard = jax.tree_util.tree_map(
            lambda s: NamedSharding(eval_mesh, s), tp_plan.param_specs)
        return jax.device_put(params, shard), stats
    rep = NamedSharding(eval_mesh, P())
    return (jax.tree_util.tree_map(lambda x: jax.device_put(x, rep),
                                   params), stats)


class _PPStep:
    """The pipeline train step: ``step_fn(state, batch, rng) -> (state,
    loss)``, signature-compatible with
    :func:`~ddp_tpu.train.step.make_train_step_accum` — ``batch`` is the
    stacked ``{"image": [A,B,...], "label": [A,B]}`` group placed by
    :func:`pp_shard_fn`.  Per-stage programs compile lazily on first use
    and re-trace per distinct A, exactly like the accum step."""

    def __init__(self, model_name: str, sgd_config, lr_schedule, mesh,
                 pp_plan: StagePlan, *, compute_dtype=None,
                 device_augment: bool = False, tp_plan=None,
                 schedule: str = "1f1b", tracer=None):
        from ..tp.plan import is_trivial, recipe_override
        if pp_plan.num_stages < 2:
            raise ValueError("make_pp_step needs s>=2 pipeline stages; "
                             "run s=1 through the standard step builders")
        if stage_axis_size(mesh) != pp_plan.num_stages:
            raise ValueError(
                f"stage plan has {pp_plan.num_stages} stages but the mesh "
                f"stage axis is {stage_axis_size(mesh)}")
        self.mesh = mesh
        self.plan = pp_plan
        self.schedule = schedule
        self.tracer = tracer
        self._sgd = sgd_config
        self._lr = lr_schedule
        self._cd = compute_dtype
        self._augment = device_augment
        self._apply_blocks = _apply_blocks_for(model_name)
        use_tp = tp_plan is not None and not is_trivial(tp_plan)
        self._tp_axis = MODEL_AXIS if use_tp else None
        self._tp_recipe = recipe_override(tp_plan) if use_tp else None
        self._tp_plan = tp_plan if use_tp else None
        self._R = data_axis_size(mesh)
        self.s = pp_plan.num_stages
        self.subs = [stage_submesh(mesh, k) for k in range(self.s)]
        self._progs: Optional[dict] = None   # built on first call
        self._updates: Dict[int, list] = {}  # per-A update programs
        self._ops: Dict[int, list] = {}      # per-A schedule op lists
        self._timed_for: set = set()         # A values already timed
        self.bubble: Optional[dict] = None   # last timed-step stats
        self.peak_inflight = 0

    # -- per-stage forward bodies ---------------------------------------

    def _stage_forward(self, k_stage: int):
        lo, hi = self.plan.stages[k_stage]
        apply_blocks = self._apply_blocks
        cd, tp_axis, tp_recipe = self._cd, self._tp_axis, self._tp_recipe

        def fwd(params, x, mrng):
            out, _ = apply_blocks(
                params, {}, x, blocks=(lo, hi), train=True, rng=mrng,
                compute_dtype=cd,
                **({} if tp_axis is None else {"tp_axis": tp_axis}),
                **({} if tp_recipe is None else {"tp_recipe": tp_recipe}))
            return out

        return fwd

    def _fold(self, rng, step, k):
        rng = jax.random.fold_in(rng, step)
        rng = jax.random.fold_in(rng, lax.axis_index(DATA_AXIS))
        return jax.random.fold_in(rng, k)

    def _micro_images(self, images, mrng, k):
        from ...train.step import _as_input
        x = lax.dynamic_index_in_dim(images, k, keepdims=False)
        if self._augment:
            from ...data.device_augment import random_crop_flip
            x = random_crop_flip(jax.random.fold_in(mrng, 1), x)
        return _as_input(x, self._cd)

    # -- program construction -------------------------------------------

    def _build(self, state):
        plan, subs, s = self.plan, self.subs, self.s
        specs, shards = [], []
        for k in range(s):
            p_sub = stage_subtree(plan, k, state.params)
            spec_sub = _specs_like(
                p_sub, (stage_subtree(plan, k, self._tp_plan.param_specs)
                        if self._tp_plan is not None else None))
            specs.append(spec_sub)
            shards.append(jax.tree_util.tree_map(
                lambda sp, _k=k: NamedSharding(subs[_k], sp), spec_sub))
        extra = {"check_vma": False}
        R = self._R
        progs: dict = {"specs": specs, "shards": shards,
                       "zeros": [], "fwd": {}, "bwd": {}}

        for k in range(s):
            progs["zeros"].append(jax.jit(
                lambda tree_shape=jax.tree_util.tree_map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype),
                    stage_subtree(plan, k, state.params)):
                jax.tree_util.tree_map(
                    lambda sd: jnp.zeros(sd.shape, sd.dtype), tree_shape),
                out_shardings=shards[k]))

        def act_spec():
            return P(DATA_AXIS)

        # forward: stage 0 (slices + prepares the micro) and middles
        for j in range(s - 1):
            fwd_blocks = self._stage_forward(j)
            first = (j == 0)

            def body(params, x, rng, step, k, _fwd=fwd_blocks,
                     _first=first):
                mrng = self._fold(rng, step, k)
                xin = self._micro_images(x, mrng, k) if _first else x
                return _fwd(params, xin, mrng)

            in_x = P(None, DATA_AXIS) if first else act_spec()
            mapped = jax.shard_map(
                body, mesh=subs[j],
                in_specs=(specs[j], in_x, P(), P(), P()),
                out_specs=act_spec(), **extra)
            progs["fwd"][j] = jax.jit(
                mapped, out_shardings=NamedSharding(subs[j], act_spec()))

        # fused forward+backward on the last stage (loss + gsum/lsum)
        fwd_last = self._stage_forward(s - 1)

        def fb_body(params, gsum, lsum, x, labels, rng, step, k):
            mrng = self._fold(rng, step, k)
            y = lax.dynamic_index_in_dim(labels, k, keepdims=False)

            def local_obj(p, xin):
                logits = fwd_last(p, xin, mrng)
                ce_sum, count = cross_entropy_sum_count(logits, y)
                return ce_sum / (count * R), (ce_sum, count)

            (gp, gx), (ce_sum, count) = jax.grad(
                local_obj, argnums=(0, 1), has_aux=True)(params, x)
            loss = (lax.psum(ce_sum, DATA_AXIS)
                    / lax.psum(count, DATA_AXIS))
            gsum = jax.tree_util.tree_map(
                lambda a, g: a + lax.psum(g, DATA_AXIS), gsum, gp)
            return gsum, lsum + loss, gx

        mapped = jax.shard_map(
            fb_body, mesh=subs[s - 1],
            in_specs=(specs[s - 1], specs[s - 1], P(), act_spec(),
                      P(None, DATA_AXIS), P(), P(), P()),
            out_specs=(specs[s - 1], P(), act_spec()), **extra)
        progs["fb"] = jax.jit(
            mapped, donate_argnums=(1, 2),
            out_shardings=(shards[s - 1],
                           NamedSharding(subs[s - 1], P()),
                           NamedSharding(subs[s - 1], act_spec())))

        # backward: middles take the saved input activation and the
        # cotangent from the next stage; stage 0 re-slices its micro and
        # differentiates w.r.t. params ONLY (the input cotangent is dead,
        # preserving the stem elision the auditor counts on).
        for j in range(s - 2, -1, -1):
            fwd_blocks = self._stage_forward(j)
            first = (j == 0)

            def bwd_body(params, gsum, x, g_out, rng, step, k,
                         _fwd=fwd_blocks, _first=first):
                mrng = self._fold(rng, step, k)
                # analysis: divergence-ok(_first is a trace-time stage constant, identical on every host)
                if _first:
                    xin = self._micro_images(x, mrng, k)
                    _, vjp = jax.vjp(lambda p: _fwd(p, xin, mrng), params)
                    (gp,) = vjp(g_out)
                    gsum = jax.tree_util.tree_map(
                        lambda a, g: a + lax.psum(g, DATA_AXIS), gsum, gp)
                    return gsum
                _, vjp = jax.vjp(lambda p, xi: _fwd(p, xi, mrng),
                                 params, x)
                gp, gx = vjp(g_out)
                gsum = jax.tree_util.tree_map(
                    lambda a, g: a + lax.psum(g, DATA_AXIS), gsum, gp)
                return gsum, gx

            in_x = P(None, DATA_AXIS) if first else act_spec()
            out_specs = (specs[j] if first else (specs[j], act_spec()))
            out_sh = (shards[j] if first
                      else (shards[j], NamedSharding(subs[j], act_spec())))
            mapped = jax.shard_map(
                bwd_body, mesh=subs[j],
                in_specs=(specs[j], specs[j], in_x, act_spec(),
                          P(), P(), P()),
                out_specs=out_specs, **extra)
            progs["bwd"][j] = jax.jit(mapped, donate_argnums=(1,),
                                      out_shardings=out_sh)
        self._progs = progs

    def _update_programs(self, a: int):
        progs = self._progs
        out = []
        for k in range(self.s):
            def upd_body(params, mom, gsum, step, _a=float(a)):
                grads = jax.tree_util.tree_map(lambda g: g / _a, gsum)
                lr_t = self._lr(step)
                return sgd_lib.apply_updates(params, grads,
                                             sgd_lib.SGDState(mom),
                                             lr_t, self._sgd)

            mapped = jax.shard_map(
                upd_body, mesh=self.subs[k],
                in_specs=(progs["specs"][k], progs["specs"][k],
                          progs["specs"][k], P()),
                out_specs=(progs["specs"][k],
                           sgd_lib.SGDState(progs["specs"][k])),
                check_vma=False)
            # donate params+momentum only: gsum has no same-shaped OUTPUT
            # to alias into (grads/a is an intermediate), so donating it
            # would just trip the unusable-donation warning.
            out.append(jax.jit(
                mapped, donate_argnums=(0, 1),
                out_shardings=(progs["shards"][k],
                               sgd_lib.SGDState(progs["shards"][k]))))
        return out

    # -- the step --------------------------------------------------------

    def __call__(self, state, batch, rng):
        from ...train.step import TrainState
        if self._progs is None:
            self._build(state)
        progs = self._progs
        s, subs, plan = self.s, self.subs, self.plan
        a = int(jax.tree_util.tree_leaves(batch)[0].shape[0])
        if a not in self._ops:
            self._ops[a] = schedule_ops(self.schedule, a, s)
            self._updates[a] = self._update_programs(a)
        ops = self._ops[a]
        timed = a not in self._timed_for and self.tracer is not None
        host_step = int(state.step)
        step32 = np.int32(host_step)
        rngs = [jax.device_put(rng, NamedSharding(sub, P()))
                for sub in subs]

        p_sub = [stage_subtree(plan, k, state.params) for k in range(s)]
        m_sub = [stage_subtree(plan, k, state.opt_state.momentum_buf)
                 for k in range(s)]
        gsum = [progs["zeros"][k]() for k in range(s)]
        lsum = jax.device_put(jnp.zeros((), jnp.float32),
                              NamedSharding(subs[-1], P()))
        images, labels = batch["image"], batch["label"]

        act_in: dict = {}   # (stage, micro) -> saved input activation
        g_out: dict = {}    # (stage, micro) -> incoming cotangent
        durations = []      # (op, seconds) when timed
        inflight_peak = 0

        def run(op):
            nonlocal lsum, inflight_peak
            if op[0] == "F":
                _, j, k = op
                x = images if j == 0 else act_in[(j, k)]
                act = progs["fwd"][j](p_sub[j], x, rngs[j], step32,
                                      np.int32(k))
                act_in[(j + 1, k)] = jax.device_put(
                    act, NamedSharding(subs[j + 1], P(DATA_AXIS)))
                return (act_in[(j + 1, k)],)
            if op[0] == "FB":
                k = op[1]
                gsum[s - 1], lsum, gx = progs["fb"](
                    p_sub[s - 1], gsum[s - 1], lsum,
                    act_in.pop((s - 1, k)), labels, rngs[s - 1], step32,
                    np.int32(k))
                g_out[(s - 2, k)] = jax.device_put(
                    gx, NamedSharding(subs[s - 2], P(DATA_AXIS)))
                return (lsum, g_out[(s - 2, k)])
            _, j, k = op
            if j == 0:
                gsum[0] = progs["bwd"][0](
                    p_sub[0], gsum[0], images, g_out.pop((0, k)),
                    rngs[0], step32, np.int32(k))
                return (jax.tree_util.tree_leaves(gsum[0])[0],)
            gsum[j], gx = progs["bwd"][j](
                p_sub[j], gsum[j], act_in.pop((j, k)),
                g_out.pop((j, k)), rngs[j], step32, np.int32(k))
            g_out[(j - 1, k)] = jax.device_put(
                gx, NamedSharding(subs[j - 1], P(DATA_AXIS)))
            return (g_out[(j - 1, k)],)

        for op in ops:
            if timed:
                t0 = time.perf_counter()
                outs = run(op)
                jax.block_until_ready(outs)
                durations.append((op, time.perf_counter() - t0))
            else:
                run(op)
            inflight_peak = max(inflight_peak, len(act_in))

        upd = self._updates[a]
        new_p, new_m = [], []
        for k in range(s):
            pk, mk = upd[k](p_sub[k], m_sub[k], gsum[k], step32)
            new_p.append(pk)
            new_m.append(mk.momentum_buf)
        loss_host = np.float32(jax.device_get(lsum)) / np.float32(a)
        new_state = TrainState(
            merge_subtrees(new_p), state.batch_stats,
            sgd_lib.SGDState(merge_subtrees(new_m)),
            state.step + 1)
        self.peak_inflight = max(self.peak_inflight, inflight_peak)
        if timed:
            self._timed_for.add(a)
            self._record_bubble(a, durations, inflight_peak, host_step)
        return new_state, jnp.float32(loss_host)

    # -- bubble accounting ----------------------------------------------

    def _record_bubble(self, a, durations, inflight_peak, host_step):
        """Reconstruct the schedule makespan from the measured per-program
        durations (dependency-aware critical path over the op DAG) and
        derive the MEASURED bubble fraction — what fraction of the s-stage
        pipeline's makespan the stages sat idle — next to the static
        (s-1)/(A+s-1) prediction.  Emitted as the ``pp_bubble`` span so
        the flight recorder / metrics pipeline can plot it."""
        s = self.s
        dur = {op: d for op, d in durations}

        def stage_of(op):
            return s - 1 if op[0] == "FB" else op[1]

        done: Dict[tuple, float] = {}
        free = [0.0] * s
        busy = [0.0] * s
        for op, d in durations:
            deps = []
            if op[0] == "F" and op[1] > 0:
                deps.append(("F", op[1] - 1, op[2]))
            elif op[0] == "FB":
                deps.append(("F", s - 2, op[1]))
            elif op[0] == "B":
                _, j, k = op
                deps.append(("FB", k) if j == s - 2 else ("B", j + 1, k))
            j = stage_of(op)
            start = max([free[j]] + [done[dep] for dep in deps
                                     if dep in done])
            done[op] = start + d
            free[j] = done[op]
            busy[j] += d
        makespan = max(free) if free else 0.0
        total_busy = sum(busy)
        measured = (1.0 - total_busy / (s * makespan)) if makespan else 0.0
        self.bubble = {
            "schedule": self.schedule,
            "num_stages": s,
            "num_micro": a,
            "bubble_measured": float(measured),
            "bubble_predicted": float(predicted_bubble(s, a)),
            "makespan_s": float(makespan),
            "peak_inflight_acts": int(inflight_peak),
        }
        if self.tracer is not None:
            bubble_s = (s * makespan - total_busy) / s
            self.tracer.add_span("pp_bubble", time.monotonic() - bubble_s,
                                 bubble_s, step=host_step)


def make_pp_step(model_name: str, sgd_config, lr_schedule, mesh: Mesh,
                 pp_plan: StagePlan, *, compute_dtype=None,
                 device_augment: bool = False, tp_plan=None,
                 schedule: str = "1f1b", tracer=None) -> Callable:
    """Build the pipeline train step over ``mesh``'s (d, m, s) shape —
    see :class:`_PPStep`.  Returns ``step_fn(state, batch, rng) ->
    (state, loss)``; ``state`` must be laid out by :func:`place_state`,
    ``batch`` by :func:`pp_shard_fn`'s stream."""
    return _PPStep(model_name, sgd_config, lr_schedule, mesh, pp_plan,
                   compute_dtype=compute_dtype,
                   device_augment=device_augment, tp_plan=tp_plan,
                   schedule=schedule, tracer=tracer)
