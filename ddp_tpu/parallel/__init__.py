from .mesh import (DATA_AXIS, MODEL_AXIS, STAGE_AXIS, batch_sharding,
                   data_axis_size, local_batch_slice, make_mesh,
                   model_axis_size, replicated_sharding, stage_axis_size)
from .dist import initialize, process_count, process_index, shutdown

__all__ = [
    "DATA_AXIS", "MODEL_AXIS", "STAGE_AXIS", "batch_sharding",
    "data_axis_size", "local_batch_slice", "make_mesh", "model_axis_size",
    "replicated_sharding", "stage_axis_size", "initialize", "process_count",
    "process_index", "shutdown",
]
