from .mesh import (DATA_AXIS, batch_sharding, local_batch_slice, make_mesh,
                   replicated_sharding)
from .dist import initialize, process_count, process_index, shutdown

__all__ = [
    "DATA_AXIS", "batch_sharding", "local_batch_slice", "make_mesh",
    "replicated_sharding", "initialize", "process_count", "process_index",
    "shutdown",
]
