"""Device mesh + shardings — the TPU-native data-parallel substrate.

The reference's entire distribution layer (NCCL process group at
multigpu.py:24-33, ``DDP(model, device_ids=[gpu_id])`` at multigpu.py:89,
one process per GPU via ``mp.spawn`` at multigpu.py:262-263) collapses here
into a 1-D ``jax.sharding.Mesh`` over all chips plus two ``NamedSharding``s:
batches split along the ``data`` axis, params/optimizer state replicated.
XLA lowers the gradient ``pmean`` inside the jitted train step to an
all-reduce over ICI (DCN across slices) — there is no NCCL-like library to
manage and no per-rank process fan-out; one process per *host* drives all
its local chips SPMD.

The default mesh stays 1-D for parity with the reference (DP is the only
parallelism it has — SURVEY.md §2 checklist); ``make_mesh(shape=(d, m))``
adds the promised second ``model`` axis (tensor-model parallelism,
ddp_tpu/parallel/tp/) without touching any 1-D caller: batches stay split
along ``data`` only (replicated over ``model``), and the per-leaf parameter
shardings come from the tp planner's PartitionSpecs rather than blanket
replication.
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
STAGE_AXIS = "stage"

_AXIS_ORDER = (DATA_AXIS, MODEL_AXIS, STAGE_AXIS)


def _check_shape(shape) -> Tuple[int, ...]:
    """Validate a requested (d[, m[, s]]) mesh shape; returns it as ints.

    The three named axes, in fixed order, are ``data`` (batch shards),
    ``model`` (tensor-parallel) and ``stage`` (pipeline-parallel); errors
    name all three so a malformed ``--mesh_shape`` points straight at the
    contract rather than at an unpacking traceback."""
    dims = tuple(shape)
    if not 1 <= len(dims) <= 3:
        raise ValueError(
            f"mesh shape wants 1-3 axes (data[, model[, stage]]), got "
            f"{len(dims)} entries: {shape!r}")
    try:
        dims = tuple(int(v) for v in dims)
    except (TypeError, ValueError):
        raise ValueError(
            f"mesh shape entries must be integers "
            f"(data[, model[, stage]]), got {shape!r}") from None
    if any(v < 1 for v in dims):
        raise ValueError(
            f"mesh shape axes (data, model, stage) must all be positive, "
            f"got {shape!r}")
    return dims


def make_mesh(num_devices: Optional[int] = None,
              devices: Optional[list] = None,
              shape: Optional[Tuple[int, ...]] = None) -> Mesh:
    """Device mesh: 1-D data-parallel by default, 2-D (data × model) or
    3-D (data × model × stage) on request.

    ``make_mesh(1)`` is the singlegpu.py path, ``make_mesh()`` the
    multigpu.py path — the reference's one structural diff (SURVEY.md §1)
    expressed as a mesh shape.  ``make_mesh(shape=(d, m))`` builds the
    tensor-parallel 2-D mesh with named ``(data, model)`` axes over the
    first ``d*m`` devices; ``shape=(d, 1)`` is a genuine 2-D mesh (the
    tp code paths run, trivially) — the 1-D default is untouched.
    ``shape=(d, m, s)`` with s>1 grows the third ``stage`` axis for
    pipeline parallelism (parallel/pp/); ``(d, m, 1)`` collapses to the
    identical 2-D mesh so a trailing-1 stage axis is bit-compatible with
    the tp path by construction.
    """
    if devices is None:
        devices = jax.devices()
    if shape is not None:
        if num_devices is not None:
            raise ValueError("pass num_devices or shape, not both")
        dims = _check_shape(shape)
        if len(dims) == 1:
            return make_mesh(num_devices=dims[0], devices=devices)
        if len(dims) == 3 and dims[2] == 1:
            dims = dims[:2]  # (d, m, 1) IS the 2-D mesh — bit-compat anchor
        n = int(np.prod(dims))
        if n > len(devices):
            raise ValueError(
                f"mesh shape {'x'.join(map(str, dims))} "
                f"(data x model{' x stage' if len(dims) == 3 else ''}) "
                f"needs {n} devices, have {len(devices)}")
        return Mesh(np.asarray(devices[:n]).reshape(dims),
                    _AXIS_ORDER[:len(dims)])
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def abstract_mesh(shape: Tuple[int, ...]):
    """A deviceless ``(data, model[, stage])`` AbstractMesh — the auto-plan
    search's substrate (parallel/tp/autoplan.py): ``jax.make_jaxpr`` traces
    the REAL step builders against it for ANY mesh shape, so a laptop/CI
    CPU box can price v4-128 layouts without owning a single chip.  Only
    tracing works on it — no ``device_put``, no execution."""
    dims = _check_shape(shape)
    if len(dims) == 1:
        dims = (dims[0], 1)
    if len(dims) == 3 and dims[2] == 1:
        dims = dims[:2]
    return jax.sharding.AbstractMesh(
        tuple(zip(_AXIS_ORDER[:len(dims)], dims)))


def mesh_size(mesh) -> int:
    """Total device count of a mesh, via its axis extents — unlike
    ``mesh.devices.size`` this also works on a deviceless
    :func:`abstract_mesh`."""
    return int(np.prod([int(v) for v in dict(mesh.shape).values()]))


def data_axis_size(mesh: Mesh) -> int:
    """Number of batch shards — the ``data`` axis extent.  THE divisor for
    every piece of batch math: on a 2-D mesh the batch is split over
    ``data`` only (replicated over ``model``), so ``mesh.devices.size``
    overcounts by the model-axis factor."""
    return int(dict(mesh.shape).get(DATA_AXIS, 1))


def model_axis_size(mesh: Mesh) -> int:
    """Model-axis extent (1 on the default 1-D mesh)."""
    return int(dict(mesh.shape).get(MODEL_AXIS, 1))


def stage_axis_size(mesh: Mesh) -> int:
    """Stage-axis extent (1 on 1-D/2-D meshes — no pipeline)."""
    return int(dict(mesh.shape).get(STAGE_AXIS, 1))


_SCAN_UNROLL_CAP = 32


def scan_unroll(mesh: Optional[Mesh] = None, length: Optional[int] = None):
    """Unroll factor for ``lax.scan`` loops whose body contains model
    compute (epoch scans, micro-batch accumulation): full unroll on the
    CPU backend for short scans, rolled scan everywhere else.

    XLA:CPU compiles convolutions inside while-loop bodies to a naive
    serial fallback instead of its fast runtime kernels: the identical
    8-step DeepNN train epoch measured 20.7 s rolled vs 0.6 s fully
    unrolled on this image's jaxlib (and the unrolled program also
    *compiles* 5x faster, 4.9 s vs 25.6 s — compiling conv-in-loop is
    itself pathological).  Only a full unroll helps; ``unroll=4`` still
    leaves a while loop and stays slow.  The CPU backend normally runs
    the virtual-device test mesh and the driver's multi-chip dryrun,
    whose epochs are a few steps; ``length`` (the static scan length,
    known at trace time) caps the policy so a genuinely long CPU scan —
    a real 98-step CIFAR epoch on a CPU-only box — keeps the rolled
    program instead of compiling 98 inlined fwd+bwd bodies.  On TPU the
    rolled scan is always right: compile time stays independent of epoch
    length and the loop costs nothing (BASELINE.md round-4 dispatch
    measurements).
    """
    platform = (mesh.devices.flat[0].platform if mesh is not None
                else jax.default_backend())
    if platform != "cpu":
        return 1
    if length is not None and length > _SCAN_UNROLL_CAP:
        return 1
    return True


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) axis split across ``data`` — the analogue of
    ``DistributedSampler`` handing each rank its shard (multigpu.py:153)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated — params/opt-state, like DDP's per-rank replicas
    kept in lockstep (multigpu.py:89, 97)."""
    return NamedSharding(mesh, P())


def local_replica_ids(mesh: Mesh) -> list:
    """``data``-axis positions of THIS process's devices, in mesh order —
    the replica ids this process feeds (loaders' ``local_replicas``) and
    the one definition the per-process assembly order hangs on
    (:func:`assemble_from_local` assumes ascending mesh order).  Asymmetric
    topologies make the blocks unequal, so every consumer must derive
    them from the mesh like this rather than from range arithmetic on a
    uniform per-host count.

    On a 2-D (data × model) mesh a "replica" is a data-axis ROW (its
    ``model``-axis devices all consume the same batch shard), so the ids
    are the distinct data coordinates this process owns devices in — NOT
    flat device positions, which would overcount by the model-axis factor
    (the regression tests/test_tp.py pins)."""
    pid = jax.process_index()
    if mesh.devices.ndim == 1:
        return [i for i, d in enumerate(mesh.devices.flat)
                if d.process_index == pid]
    data_dim = mesh.axis_names.index(DATA_AXIS)
    rows = np.moveaxis(mesh.devices, data_dim, 0)
    return [i for i in range(rows.shape[0])
            if any(d.process_index == pid for d in rows[i].flat)]


def assemble_from_local(sharding: NamedSharding, v, axis: int) -> jax.Array:
    """``jax.make_array_from_process_local_data`` with the global shape made
    EXPLICIT along the sharded ``axis``: the library's inference assumes
    every process contributes equal-sized blocks and fails on asymmetric
    host->replica topologies (e.g. a 2/1/1 split of a 4-device mesh),
    which real pods can have even though the reference's mp.spawn fan-out
    never does (multigpu.py:262-263).  Each of this process's addressable
    mesh devices holds the same per-replica extent, so the global extent is
    ``local_extent / n_local * n_total``.

    Shard counts are AXIS-AWARE: they come from the spec's entry for
    ``axis`` (distinct shard positions along the mesh axes that actually
    split it), not from raw device counts — on a 2-D (data × model) mesh a
    ``P(data)`` batch is replicated over ``model``, so counting devices
    would inflate both the local and the global block count by the
    model-axis factor (regression-pinned in tests/test_tp.py)."""
    if len(sharding.addressable_devices) == 0:
        raise ValueError(
            f"process {jax.process_index()} owns no devices of this mesh; "
            "it cannot contribute process-local data (every participating "
            "process must hold at least one mesh device)")
    mesh = sharding.mesh
    entry = sharding.spec[axis] if axis < len(sharding.spec) else None
    names = ((entry,) if isinstance(entry, str) else tuple(entry or ()))
    dims = [mesh.axis_names.index(n) for n in names]
    shape_d = dict(mesh.shape)
    n_total = int(np.prod([shape_d[n] for n in names])) if names else 1
    pid = jax.process_index()
    local = {tuple(np.asarray(pos)[dims])
             for pos in np.ndindex(mesh.devices.shape)
             if mesh.devices[pos].process_index == pid}
    n_local = len(local)
    shape = list(v.shape)
    if shape[axis] % n_local:
        raise ValueError(
            f"process-local extent {shape[axis]} along axis {axis} is not "
            f"divisible by this process's {n_local} mesh devices — each "
            "local device must hold an equal block")
    shape[axis] = shape[axis] // n_local * n_total
    return jax.make_array_from_process_local_data(sharding, v, tuple(shape))


def process_min_mib(mesh: Mesh, value_bytes: Optional[int]) -> Optional[int]:
    """Global minimum byte count over processes, asymmetric-topology-safe;
    ``None`` anywhere (or everywhere) means "no limit" and wins.

    ``multihost_utils.process_allgather`` reshapes ``jax.devices()`` into
    ``(process_count, local_device_count)`` and so breaks on unequal
    per-host device counts; this instead places each process's value on its
    own mesh devices and jit-reduces with a replicated output every process
    can read.  The value crosses the device in MiB, not bytes: without
    x64 enabled JAX canonicalizes int64 to int32, where real HBM byte
    capacities (2^34...) overflow — 16 GiB wraps to exactly 0 — while MiB
    counts stay int32-exact up to 2 TiB.  Returns ceil-MiB bytes: the
    guard's comparison tolerance is far coarser than 1 MiB either way, but
    flooring would turn a reported sub-MiB capacity into 0 bytes and flip
    the resident-HBM guard from advisory into an unconditional error
    (unreachable for real HBM sizes; ADVICE r4).

    Every participating process must own at least one mesh device — a
    deviceless process cannot contribute to (or read) the collective and
    gets :func:`assemble_from_local`'s explicit error; such topologies
    are unsupported throughout (an SPMD program over the mesh has no
    work for that process)."""
    import jax.numpy as jnp
    mib = -1 if value_bytes is None else -(-value_bytes // 2 ** 20)
    vals = assemble_from_local(
        batch_sharding(mesh),
        np.full(len(local_replica_ids(mesh)), mib, np.int32), 0)
    gmin = int(jax.jit(jnp.min,
                       out_shardings=replicated_sharding(mesh))(vals))
    return None if gmin < 0 else gmin * 2 ** 20


def local_batch_slice(global_batch: int, mesh: Mesh) -> int:
    """Per-host slice of a global batch (multi-host data feeding).

    Batch math uses the ``data`` axis size ONLY: on a 2-D (data × model)
    mesh the batch is split over ``data`` and replicated over ``model``,
    so dividing by the raw device count would shrink every shard by the
    model-axis factor (and reject batches a (2,4) mesh handles fine —
    the regression tests/test_tp.py pins both)."""
    n_shards = data_axis_size(mesh)
    if global_batch % n_shards:
        raise ValueError(
            f"global batch {global_batch} not divisible by the mesh's "
            f"{n_shards}-way data axis")
    per_shard = global_batch // n_shards
    return per_shard * len(local_replica_ids(mesh))
