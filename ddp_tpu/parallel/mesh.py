"""Device mesh + shardings — the TPU-native data-parallel substrate.

The reference's entire distribution layer (NCCL process group at
multigpu.py:24-33, ``DDP(model, device_ids=[gpu_id])`` at multigpu.py:89,
one process per GPU via ``mp.spawn`` at multigpu.py:262-263) collapses here
into a 1-D ``jax.sharding.Mesh`` over all chips plus two ``NamedSharding``s:
batches split along the ``data`` axis, params/optimizer state replicated.
XLA lowers the gradient ``pmean`` inside the jitted train step to an
all-reduce over ICI (DCN across slices) — there is no NCCL-like library to
manage and no per-rank process fan-out; one process per *host* drives all
its local chips SPMD.

The mesh is deliberately 1-D for parity with the reference (DP is the only
parallelism it has — SURVEY.md §2 checklist), but every consumer takes the
mesh as an argument so a second (``model``) axis can be added without
touching the train step's callers.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(num_devices: Optional[int] = None,
              devices: Optional[list] = None) -> Mesh:
    """1-D data-parallel mesh over ``num_devices`` (default: all) chips.

    ``make_mesh(1)`` is the singlegpu.py path, ``make_mesh()`` the
    multigpu.py path — the reference's one structural diff (SURVEY.md §1)
    expressed as a mesh shape.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


_SCAN_UNROLL_CAP = 32


def scan_unroll(mesh: Optional[Mesh] = None, length: Optional[int] = None):
    """Unroll factor for ``lax.scan`` loops whose body contains model
    compute (epoch scans, micro-batch accumulation): full unroll on the
    CPU backend for short scans, rolled scan everywhere else.

    XLA:CPU compiles convolutions inside while-loop bodies to a naive
    serial fallback instead of its fast runtime kernels: the identical
    8-step DeepNN train epoch measured 20.7 s rolled vs 0.6 s fully
    unrolled on this image's jaxlib (and the unrolled program also
    *compiles* 5x faster, 4.9 s vs 25.6 s — compiling conv-in-loop is
    itself pathological).  Only a full unroll helps; ``unroll=4`` still
    leaves a while loop and stays slow.  The CPU backend normally runs
    the virtual-device test mesh and the driver's multi-chip dryrun,
    whose epochs are a few steps; ``length`` (the static scan length,
    known at trace time) caps the policy so a genuinely long CPU scan —
    a real 98-step CIFAR epoch on a CPU-only box — keeps the rolled
    program instead of compiling 98 inlined fwd+bwd bodies.  On TPU the
    rolled scan is always right: compile time stays independent of epoch
    length and the loop costs nothing (BASELINE.md round-4 dispatch
    measurements).
    """
    platform = (mesh.devices.flat[0].platform if mesh is not None
                else jax.default_backend())
    if platform != "cpu":
        return 1
    if length is not None and length > _SCAN_UNROLL_CAP:
        return 1
    return True


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) axis split across ``data`` — the analogue of
    ``DistributedSampler`` handing each rank its shard (multigpu.py:153)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated — params/opt-state, like DDP's per-rank replicas
    kept in lockstep (multigpu.py:89, 97)."""
    return NamedSharding(mesh, P())


def local_replica_ids(mesh: Mesh) -> list:
    """Flat-mesh positions of THIS process's devices, in mesh order — the
    replica ids this process feeds (loaders' ``local_replicas``) and the
    one definition the per-process assembly order hangs on
    (:func:`assemble_from_local` assumes ascending mesh order).  Asymmetric
    topologies make the blocks unequal, so every consumer must derive
    them from the mesh like this rather than from range arithmetic on a
    uniform per-host count."""
    return [i for i, d in enumerate(mesh.devices.flat)
            if d.process_index == jax.process_index()]


def assemble_from_local(sharding: NamedSharding, v, axis: int) -> jax.Array:
    """``jax.make_array_from_process_local_data`` with the global shape made
    EXPLICIT along the sharded ``axis``: the library's inference assumes
    every process contributes equal-sized blocks and fails on asymmetric
    host->replica topologies (e.g. a 2/1/1 split of a 4-device mesh),
    which real pods can have even though the reference's mp.spawn fan-out
    never does (multigpu.py:262-263).  Each of this process's addressable
    mesh devices holds the same per-replica extent, so the global extent is
    ``local_extent / n_local * n_total``."""
    n_local = len(sharding.addressable_devices)
    if n_local == 0:
        raise ValueError(
            f"process {jax.process_index()} owns no devices of this mesh; "
            "it cannot contribute process-local data (every participating "
            "process must hold at least one mesh device)")
    n_total = sharding.mesh.devices.size
    shape = list(v.shape)
    if shape[axis] % n_local:
        raise ValueError(
            f"process-local extent {shape[axis]} along axis {axis} is not "
            f"divisible by this process's {n_local} mesh devices — each "
            "local device must hold an equal block")
    shape[axis] = shape[axis] // n_local * n_total
    return jax.make_array_from_process_local_data(sharding, v, tuple(shape))


def process_min_mib(mesh: Mesh, value_bytes: Optional[int]) -> Optional[int]:
    """Global minimum byte count over processes, asymmetric-topology-safe;
    ``None`` anywhere (or everywhere) means "no limit" and wins.

    ``multihost_utils.process_allgather`` reshapes ``jax.devices()`` into
    ``(process_count, local_device_count)`` and so breaks on unequal
    per-host device counts; this instead places each process's value on its
    own mesh devices and jit-reduces with a replicated output every process
    can read.  The value crosses the device in MiB, not bytes: without
    x64 enabled JAX canonicalizes int64 to int32, where real HBM byte
    capacities (2^34...) overflow — 16 GiB wraps to exactly 0 — while MiB
    counts stay int32-exact up to 2 TiB.  Returns ceil-MiB bytes: the
    guard's comparison tolerance is far coarser than 1 MiB either way, but
    flooring would turn a reported sub-MiB capacity into 0 bytes and flip
    the resident-HBM guard from advisory into an unconditional error
    (unreachable for real HBM sizes; ADVICE r4).

    Every participating process must own at least one mesh device — a
    deviceless process cannot contribute to (or read) the collective and
    gets :func:`assemble_from_local`'s explicit error; such topologies
    are unsupported throughout (an SPMD program over the mesh has no
    work for that process)."""
    import jax.numpy as jnp
    mib = -1 if value_bytes is None else -(-value_bytes // 2 ** 20)
    vals = assemble_from_local(
        batch_sharding(mesh),
        np.full(len(local_replica_ids(mesh)), mib, np.int32), 0)
    gmin = int(jax.jit(jnp.min,
                       out_shardings=replicated_sharding(mesh))(vals))
    return None if gmin < 0 else gmin * 2 ** 20


def local_batch_slice(global_batch: int, mesh: Mesh) -> int:
    """Per-host slice of a global batch (multi-host data feeding)."""
    if global_batch % mesh.devices.size:
        raise ValueError(
            f"global batch {global_batch} not divisible by mesh size "
            f"{mesh.devices.size}")
    per_device = global_batch // mesh.devices.size
    return per_device * jax.local_device_count()
