"""Device mesh + shardings — the TPU-native data-parallel substrate.

The reference's entire distribution layer (NCCL process group at
multigpu.py:24-33, ``DDP(model, device_ids=[gpu_id])`` at multigpu.py:89,
one process per GPU via ``mp.spawn`` at multigpu.py:262-263) collapses here
into a 1-D ``jax.sharding.Mesh`` over all chips plus two ``NamedSharding``s:
batches split along the ``data`` axis, params/optimizer state replicated.
XLA lowers the gradient ``pmean`` inside the jitted train step to an
all-reduce over ICI (DCN across slices) — there is no NCCL-like library to
manage and no per-rank process fan-out; one process per *host* drives all
its local chips SPMD.

The mesh is deliberately 1-D for parity with the reference (DP is the only
parallelism it has — SURVEY.md §2 checklist), but every consumer takes the
mesh as an argument so a second (``model``) axis can be added without
touching the train step's callers.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(num_devices: Optional[int] = None,
              devices: Optional[list] = None) -> Mesh:
    """1-D data-parallel mesh over ``num_devices`` (default: all) chips.

    ``make_mesh(1)`` is the singlegpu.py path, ``make_mesh()`` the
    multigpu.py path — the reference's one structural diff (SURVEY.md §1)
    expressed as a mesh shape.
    """
    if devices is None:
        devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}")
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (DATA_AXIS,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Leading (batch) axis split across ``data`` — the analogue of
    ``DistributedSampler`` handing each rank its shard (multigpu.py:153)."""
    return NamedSharding(mesh, P(DATA_AXIS))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Fully replicated — params/opt-state, like DDP's per-rank replicas
    kept in lockstep (multigpu.py:89, 97)."""
    return NamedSharding(mesh, P())


def local_batch_slice(global_batch: int, mesh: Mesh) -> int:
    """Per-host slice of a global batch (multi-host data feeding)."""
    if global_batch % mesh.devices.size:
        raise ValueError(
            f"global batch {global_batch} not divisible by mesh size "
            f"{mesh.devices.size}")
    per_device = global_batch // mesh.devices.size
    return per_device * jax.local_device_count()
