"""Triangular LR schedule — reference singlegpu.py:142-149 / multigpu.py:136-143.

``lr(step) = base_lr * interp(step / steps_per_epoch,
                              [0, 0.3 * num_epochs, num_epochs], [0, 1, 0])``

i.e. linear warmup from 0 to base_lr at epoch 6 (of 20), then linear decay to
0 at epoch 20, advanced PER BATCH (scheduler.step() in _run_batch,
singlegpu.py:108).  torch's LambdaLR applies lambda(t) to the optimizer step
taken at global batch index t (starting at 0, so the very first update uses
lr=0) — we reproduce that indexing exactly.

The reference hardcodes steps_per_epoch (98 single-GPU, 49 assuming exactly 2
ranks) and num_epochs=20 independent of the CLI epoch count (SURVEY.md 2.9
and appendix).  We derive steps_per_epoch from the real shard size by default
— the one sanctioned behavioral fix — but accept explicit overrides to
reproduce the reference curve bit-for-bit.
"""
from __future__ import annotations

import jax.numpy as jnp


def triangular_lr(step, *, base_lr: float = 0.4, num_epochs: int = 20,
                  steps_per_epoch: int = 98, peak_frac: float = 0.3):
    """Effective LR at global batch index ``step`` (traceable: step may be a
    JAX scalar)."""
    e = step / steps_per_epoch
    peak = num_epochs * peak_frac
    warm = e / peak
    decay = (num_epochs - e) / (num_epochs - peak)
    return base_lr * jnp.clip(jnp.minimum(warm, decay), 0.0, 1.0)
