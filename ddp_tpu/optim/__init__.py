from .schedule import triangular_lr
from .sgd import SGDConfig, SGDState, apply_updates, init

__all__ = ["SGDConfig", "SGDState", "apply_updates", "init", "triangular_lr"]
