"""SGD with the exact PyTorch update convention the reference uses
(singlegpu.py:135-140: lr=0.4, momentum=0.9, weight_decay=5e-4, applied to
ALL params including BN scale/bias).

PyTorch semantics (dampening=0, nesterov=False):
    g   <- grad + weight_decay * param
    buf <- momentum * buf + g          (buf starts at 0, so step 0 gives buf=g)
    p   <- p - lr * buf

Implemented directly (rather than via optax) so the torch weight-decay
placement — decay folded into the gradient *before* the momentum trace, with
no decoupling — is explicit and independently testable; the update rule is
golden-tested against ``torch.optim.SGD`` per-step (tests/test_optim.py).
"""
from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp


class SGDConfig(NamedTuple):
    """Hyperparameters (reference defaults, singlegpu.py:135-140).

    ``lr`` is the *base* learning rate; the trainer passes it to the LR
    schedule as ``base_lr`` and feeds the resulting effective per-step rate
    to ``apply_updates`` as ``lr_t``.
    """
    lr: float = 0.4
    momentum: float = 0.9
    weight_decay: float = 5e-4


class SGDState(NamedTuple):
    momentum_buf: Any  # pytree matching params, zeros-initialised


def init(params) -> SGDState:
    return SGDState(jax.tree_util.tree_map(jnp.zeros_like, params))


def apply_updates(params, grads, state: SGDState, lr_t,
                  config: SGDConfig):
    """One SGD step at effective learning rate ``lr_t`` (a scalar array so
    the per-batch LR schedule doesn't trigger recompilation).

    Returns (new_params, new_state).
    """
    mu, wd = config.momentum, config.weight_decay
    new_buf = jax.tree_util.tree_map(
        lambda p, g, b: mu * b + g + wd * p, params, grads,
        state.momentum_buf)
    new_params = jax.tree_util.tree_map(
        lambda p, b: p - lr_t * b, params, new_buf)
    return new_params, SGDState(new_buf)
