"""Front-end request router: the fleet's fault-tolerance layer.

One :class:`Router` load-balances N *replica handles* (in-process
:class:`~ddp_tpu.serve.fleet.LocalReplica` pairs and/or
:class:`~ddp_tpu.serve.fleet.HTTPReplica` backends — anything with the
small protocol documented on :class:`Router`).  A single ``ServeEngine``
behind one HTTP listener (PR 8's stack) turns every replica-level
incident into shed traffic: one crashed replica, one stalled forward, or
one checkpoint reload and clients see errors.  The router absorbs those
incidents with three mechanisms, each bounded and observable:

- **Health-driven ejection.**  A background probe thread polls every
  replica's health; ``eject_after`` consecutive failures eject it from
  rotation (an ``eject`` span + stderr event), and an ejected replica is
  re-probed on an exponential backoff until it answers again
  (``readmit`` span).  Routing never waits on a dead replica's TCP
  timeout — the probe thread pays that cost off the request path.

- **Retry with a deadline budget.**  Every request carries one deadline;
  a replica failure consumes one of ``max_retries`` bounded retries with
  jittered exponential backoff (a ``retry`` span), the breaker below is
  informed, and no attempt — first or retried — ever waits past the
  request's remaining budget.  There is no retry storm: the budget is
  per-request and spent attempts never revive.

- **Per-replica circuit breaker.**  ``breaker_trip_after`` consecutive
  failures trip the replica's breaker OPEN; after a cooldown it goes
  HALF-OPEN and admits *exactly one* probe request — success closes it,
  failure re-opens with a doubled (capped) cooldown.  The breaker
  reacts at request latency; the health prober at probe latency — a
  replica that fails requests but still answers health probes is
  contained by the breaker alone.

Graceful degradation: when nothing can take the request the router
sheds it *immediately* with a machine-actionable hint instead of letting
it time out — :class:`NoHealthyReplicas` (everything ejected/open, retry
after the soonest re-admission probe), :class:`RouterOverloaded`
(every healthy replica's admission queue full, retry after the live
backlog drains at the measured service rate), or
:class:`RouterDraining` (every candidate answered ``Draining`` twice —
a fleet mid-shutdown, not mid-swap — shed now like the single-engine
503, never spin to the deadline).  All carry ``retry_after_s`` and
subclass :class:`~ddp_tpu.serve.batcher.QueueFull` so the HTTP layer's
503 + ``Retry-After`` mapping and bench.py's shed accounting apply
unchanged.

Telemetry: ``route`` (replica selection, per routed attempt) and
``retry`` (the backoff wait) are ``overlap=True`` handler-thread spans;
``eject``/``readmit`` mark rotation changes — all visible in
``python -m ddp_tpu.obs`` and the Perfetto export next to the engine's
pad/h2d/forward/d2h pipeline.  The router additionally MINTS a request
id at admission (``q<N>``) and threads it through every span and the
replica ``submit`` call, so one request — across retries, replicas and
a mid-request hot-swap — reconstructs as a single connected flow
(obs/export.py ``request_chains``; ``python -m ddp_tpu.obs --requests``).

Counters live in the shared :class:`~ddp_tpu.obs.registry
.MetricsRegistry` (``ddp_router_*`` families; the legacy ``stats()``
field names are read-only views of the same children), scrapeable at
``/metrics`` when the fleet passes its registry down.
"""
from __future__ import annotations

import random
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..obs.registry import MetricsRegistry
from ..obs.tracer import get_tracer
from .batcher import Draining, QueueFull
from .engine import RequestTooLarge, ServeError

_BREAKER_STATE_CODE = {"closed": 0.0, "half-open": 1.0, "open": 2.0}


class ReplicaCrashed(ServeError):
    """A replica died mid-request (process gone, engine wedged, fault
    injection) — retryable on another replica, breaker-countable."""


class RouterShed(QueueFull):
    """Shed at the ROUTER with a derived ``Retry-After`` — subclasses
    :class:`QueueFull` so every existing 503-with-backpressure mapping
    (http.py, bench.py load loops) treats it as a shed, never a failure."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = max(float(retry_after_s), 1.0)


class NoHealthyReplicas(RouterShed):
    """Every replica is ejected or breaker-open; ``retry_after_s`` is the
    soonest re-admission probe."""


class RouterOverloaded(RouterShed):
    """Every healthy replica's admission queue is full; ``retry_after_s``
    is the live backlog divided by the measured service rate."""


class RouterDraining(RouterShed, Draining):
    """Every candidate replica answered ``Draining`` repeatedly — the
    fleet is shutting down, not mid-swap.  Subclasses both
    :class:`RouterShed` (503 + ``Retry-After``, shed accounting) and
    :class:`~ddp_tpu.serve.batcher.Draining` (single-engine parity for
    callers that catch the drain specifically)."""


class CircuitBreaker:
    """Consecutive-failure circuit: CLOSED -> OPEN -> HALF-OPEN -> CLOSED.

    ``allow()`` is the gate the router consults per attempt: always True
    when CLOSED; False while OPEN (until the cooldown expires); in
    HALF-OPEN it returns True exactly once (the single probe) and False
    until that probe's outcome is recorded.  A failure while HALF-OPEN
    (or ``trip_after`` consecutive failures while CLOSED) re-opens with
    an exponentially doubled cooldown, capped at ``cooldown_max_s``;
    any success snaps back to CLOSED and resets the backoff.
    """

    def __init__(self, trip_after: int = 3, cooldown_s: float = 1.0,
                 cooldown_max_s: float = 30.0):
        if trip_after < 1:
            raise ValueError(f"trip_after must be >= 1, got {trip_after}")
        self._lock = threading.Lock()
        self._base_cooldown_s = float(cooldown_s)
        self._cooldown_max_s = float(cooldown_max_s)
        self.trip_after = int(trip_after)
        self.state = "closed"           # analysis: shared-under(_lock)
        self.failures = 0               # analysis: shared-under(_lock)
        self.trips = 0                  # analysis: shared-under(_lock)
        # analysis: shared-under(_lock)
        self._cooldown_s = float(cooldown_s)
        self._open_until = 0.0          # analysis: shared-under(_lock)
        self._probe_out = False         # analysis: shared-under(_lock)

    def allow(self) -> bool:
        """May a request go to this replica NOW?  Claims the single
        half-open probe slot when it grants one."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if time.monotonic() < self._open_until:
                    return False
                self.state = "half-open"
                self._probe_out = False
            # half-open: exactly one in-flight probe.
            if self._probe_out:
                return False
            self._probe_out = True
            return True

    def record_success(self) -> None:
        with self._lock:
            self.state = "closed"
            self.failures = 0
            self._probe_out = False
            self._cooldown_s = self._base_cooldown_s

    def release_probe(self) -> None:
        """Release the half-open probe slot WITHOUT recording an outcome.

        The router calls this when an attempt exits through a path that
        says nothing about replica health — QueueFull, Draining, or the
        client's own bad request.  Without it a granted probe whose
        attempt never reached the replica's forward would leave
        ``_probe_out`` latched True and ``allow()`` False forever: the
        replica would be silently removed from rotation with no breaker
        trip and no ejection to recover from."""
        with self._lock:
            self._probe_out = False

    def record_failure(self) -> None:
        with self._lock:
            self.failures += 1
            if self.state == "half-open" or (
                    self.state == "closed"
                    and self.failures >= self.trip_after):
                self.state = "open"
                self._open_until = time.monotonic() + self._cooldown_s
                self._cooldown_s = min(self._cooldown_s * 2.0,
                                       self._cooldown_max_s)
                self._probe_out = False
                self.trips += 1

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self.state, "failures": self.failures,
                    "trips": self.trips,
                    "cooldown_s": round(self._cooldown_s, 3)}


class _ReplicaState:
    """Router-side bookkeeping for one replica handle (no thread of its
    own; every field is touched under the owning Router's ``_lock``)."""

    def __init__(self, replica, breaker: CircuitBreaker):
        self.replica = replica
        self.breaker = breaker
        self.ejected = False
        self.health_failures = 0
        self.ejections = 0
        self.readmit_at = 0.0           # monotonic; next probe time
        self.readmit_backoff_s = 0.0
        self.served = 0
        self.failed = 0


class Router:
    """Load balancer + failure absorber over a fixed replica set.

    Replica protocol (duck-typed; LocalReplica/HTTPReplica implement it):

    - ``replica_id``            stable string id
    - ``submit(images, timeout=...)``  -> logits (raises ServeError/...)
    - ``health()``              -> dict with ``status`` (raises when dead)
    - ``queue_depth()``         -> int (requests waiting at admission)
    - ``stats()``               -> dict (for /stats aggregation)

    ``submit`` is the one request entry point, thread-safe; the health
    prober runs on an internal daemon thread between :meth:`start` and
    :meth:`close` (tests may instead call :meth:`health_tick` directly
    for determinism).
    """

    def __init__(self, replicas, *, max_retries: int = 2,
                 backoff_ms: float = 25.0,
                 default_timeout_s: float = 30.0,
                 health_interval_s: float = 0.5,
                 eject_after: int = 2,
                 readmit_base_s: float = 0.5,
                 readmit_max_s: float = 30.0,
                 breaker_trip_after: int = 3,
                 breaker_cooldown_s: float = 1.0,
                 tracer=None, seed: int = 0, registry=None):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("a router needs at least one replica")
        ids = [r.replica_id for r in replicas]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids: {ids}")
        self.max_retries = max(int(max_retries), 0)
        self.backoff_s = max(float(backoff_ms), 0.0) / 1e3
        self.default_timeout_s = float(default_timeout_s)
        self.health_interval_s = float(health_interval_s)
        self.eject_after = max(int(eject_after), 1)
        self.readmit_base_s = float(readmit_base_s)
        self.readmit_max_s = float(readmit_max_s)
        self.tracer = tracer if tracer is not None else get_tracer()
        self._rng = random.Random(seed)   # analysis: shared-under(_lock)
        self._lock = threading.Lock()
        self._states: Dict[str, _ReplicaState] = {
            rid: _ReplicaState(r, CircuitBreaker(
                trip_after=breaker_trip_after,
                cooldown_s=breaker_cooldown_s))
            for rid, r in zip(ids, replicas)}
        self._order = ids                 # fixed rotation order
        self._rr = 0                      # analysis: shared-under(_lock)
        self._seq = 0                     # analysis: shared-under(_lock)
        self._req_seq = 0                 # analysis: shared-under(_lock)
        # Counters live in the metrics registry (internally locked); a
        # private registry by default keeps instances isolated — the
        # fleet passes its shared one so /metrics sees the router.
        self.registry = (registry if registry is not None
                         else MetricsRegistry())
        self._c_routed = self.registry.counter(
            "ddp_router_routed_total",
            "Routing decisions (one per pick round)").labels()
        self._c_retries = self.registry.counter(
            "ddp_router_retries_total",
            "Retry/re-route waits taken inside request budgets").labels()
        self._c_ejections = self.registry.counter(
            "ddp_router_ejections_total",
            "Replicas ejected from rotation by the health prober").labels()
        self._c_readmissions = self.registry.counter(
            "ddp_router_readmissions_total",
            "Ejected replicas re-admitted after a healthy probe").labels()
        shed = self.registry.counter(
            "ddp_router_shed_total",
            "Requests shed at the router, by RouterShed class",
            labelnames=("reason",))
        self._c_shed_no_replicas = shed.labels(reason="no_replicas")
        self._c_shed_overloaded = shed.labels(reason="overloaded")
        self._c_shed_draining = shed.labels(reason="draining")
        self._c_migrations = self.registry.counter(
            "ddp_router_session_migrations_total",
            "Sticky generative sessions re-pinned to a different replica "
            "(KV cache recomputed by full-history prefill)").labels()
        # session id -> replica id, insertion-ordered for LRU eviction.
        # analysis: shared-under(_lock)
        self._sessions: Dict[str, str] = {}
        self._max_sessions = 4096
        self.registry.gauge(
            "ddp_router_sessions",
            "Sticky generative sessions currently pinned").labels(
        ).set_function(lambda: float(len(self._sessions)))
        breaker_g = self.registry.gauge(
            "ddp_router_breaker_state",
            "Per-replica circuit state (0 closed, 1 half-open, 2 open)",
            labelnames=("replica",))
        served_c = self.registry.counter(
            "ddp_router_replica_served_total",
            "Requests served, per replica", labelnames=("replica",))
        failed_c = self.registry.counter(
            "ddp_router_replica_failed_total",
            "Requests failed, per replica", labelnames=("replica",))
        for rid in self._order:
            st = self._states[rid]
            breaker_g.labels(replica=rid).set_function(
                lambda b=st.breaker:
                _BREAKER_STATE_CODE[b.snapshot()["state"]])
            served_c.labels(replica=rid).set_function(
                lambda s=st: float(s.served))
            failed_c.labels(replica=rid).set_function(
                lambda s=st: float(s.failed))
        # Completion timestamps (monotonic) of recently served requests —
        # the live service-rate estimate Retry-After is derived from.
        # analysis: shared-under(_lock)
        self._served_t: List[float] = []
        self._stop = threading.Event()
        self._health_thread: Optional[threading.Thread] = None

    # Legacy counter names: read-only views of the registry children, so
    # stats() consumers and tests keep their field names while /metrics
    # and /stats can never disagree (one storage).
    @property
    def routed(self) -> int:
        return int(self._c_routed.value)

    @property
    def retries(self) -> int:
        return int(self._c_retries.value)

    @property
    def ejections(self) -> int:
        return int(self._c_ejections.value)

    @property
    def readmissions(self) -> int:
        return int(self._c_readmissions.value)

    @property
    def shed_no_replicas(self) -> int:
        return int(self._c_shed_no_replicas.value)

    @property
    def shed_overloaded(self) -> int:
        return int(self._c_shed_overloaded.value)

    @property
    def shed_draining(self) -> int:
        return int(self._c_shed_draining.value)

    # -- request path ------------------------------------------------------

    def submit(self, images, timeout: Optional[float] = None):
        """Route ``images`` to a healthy replica inside one deadline
        budget; bounded jittered retries on replica failure; immediate
        re-route (no budget charge) when a replica is draining mid-swap;
        shed with a derived ``Retry-After`` when nothing can take it.

        Mints the request id at admission; every span this request emits
        (here and downstream in the batcher) carries it."""
        out, _ = self._route(
            lambda st, remaining, req: st.replica.submit(
                images, timeout=remaining, req=req), timeout)
        return out

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = None,
                 session: Optional[str] = None):
        """Route one generative stream — same deadline/retry/shed
        machinery as :meth:`submit`, plus STICKY sessions: a ``session``
        id pins to the replica that served it last, so a multi-turn
        conversation keeps hitting the replica holding its KV-cache
        slots warm.  The pin is a PREFERENCE, not a guarantee: when the
        pinned replica is ejected, breaker-open, full, or fails
        mid-stream, the request re-routes like any other and the session
        re-pins to whichever replica served it — a MIGRATION (counted,
        logged).  Correctness never depends on the pin because every
        request carries its full token history and a migrated stream
        simply re-prefills on the new replica (recompute-on-migrate;
        the mid-stream replica-crash chaos drill pins zero failed
        streams on exactly this path)."""
        prefer = None
        if session is not None:
            with self._lock:
                prefer = self._sessions.get(session)

        def send(st, remaining, req):
            return st.replica.generate(
                prompt, max_new_tokens=max_new_tokens, timeout=remaining,
                req=req, session=session)

        out, rid = self._route(send, timeout, prefer=prefer)
        if session is not None:
            with self._lock:
                prev = self._sessions.pop(session, None)
                self._sessions[session] = rid  # re-insert: LRU order
                if len(self._sessions) > self._max_sessions:
                    self._sessions.pop(next(iter(self._sessions)))
            if prev is not None and prev != rid:
                self._c_migrations.inc()
                _log(f"router: session {session!r} migrated {prev} -> "
                     f"{rid} (KV cache recomputed by full-history "
                     "prefill)")
        return out

    def session_replica(self, session: str) -> Optional[str]:
        """The replica id ``session`` is currently pinned to (None when
        unknown) — the /stats sticky-routing assertion surface."""
        with self._lock:
            return self._sessions.get(session)

    def _route(self, send, timeout: Optional[float],
               prefer: Optional[str] = None):
        """The shared routing loop: returns ``(result, replica_id)``.
        ``send(state, remaining_s, req_id)`` performs one attempt on one
        replica; ``prefer`` (a replica id) is tried first when healthy
        and claimable — the sticky-session hint."""
        deadline = time.monotonic() + (self.default_timeout_s
                                       if timeout is None else
                                       max(float(timeout), 0.0))
        with self._lock:
            self._req_seq += 1
            req = f"q{self._req_seq}"
        failures = 0
        full: set = set()   # replicas that answered QueueFull this request
        failed_on: set = set()  # replicas that FAILED this request already
        drained: set = set()    # replicas that answered Draining TWICE
        drain_hits: Dict[str, int] = {}
        last_err: Optional[BaseException] = None
        tried_prefer = prefer is None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"deadline budget exhausted after {failures} "
                    f"failure(s); last error: {last_err!r}")
            st = seq = None
            if not tried_prefer:
                # Sticky hint: ONE shot at the pinned replica, claimed
                # through the same breaker gate as any pick; every
                # subsequent round falls through to normal routing.
                tried_prefer = True
                st, seq = self._pick_preferred(
                    prefer, exclude=full | failed_on | drained, req=req)
            if st is None:
                st, seq = self._pick(exclude=full | failed_on | drained,
                                     req=req)
            if st is None and failed_on:
                # Every untried replica is out; retrying the one that
                # already failed this request beats shedding it (a
                # crashed replica has an empty queue and would otherwise
                # keep winning least-loaded until its breaker trips).
                st, seq = self._pick(exclude=full | drained, req=req)
            if st is None:
                if full:
                    # Healthy replicas exist but every one of them is at
                    # admission capacity: shed NOW with the backlog-drain
                    # estimate, not a timeout 30 s from now.
                    self._c_shed_overloaded.inc()
                    raise RouterOverloaded(
                        f"all {len(full)} healthy replica(s) at admission "
                        "capacity; retry after backoff",
                        self._overload_retry_after())
                if drained:
                    # Every candidate answered Draining twice: the fleet
                    # is shutting down (a mid-swap replica serves again
                    # on its FIRST re-route).  Shed NOW like the
                    # single-engine 503 instead of busy-spinning retry
                    # spans until the deadline turns this into a 500.
                    self._c_shed_draining.inc()
                    raise RouterDraining(
                        f"all {len(drained)} candidate replica(s) "
                        "draining (fleet shutting down); retry shortly",
                        1.0)
                self._c_shed_no_replicas.inc()
                raise NoHealthyReplicas(
                    "no healthy replicas (all ejected or circuit-open); "
                    "retry after the next re-admission probe",
                    self._readmit_retry_after())
            try:
                out = send(st, remaining, req)
            except (ValueError, TypeError, RequestTooLarge):
                # The CLIENT's error: no retry, no breaker hit — but a
                # granted half-open probe slot must not stay latched.
                st.breaker.release_probe()
                raise
            except QueueFull:
                # Backpressure, not failure: try the other replicas with
                # no budget charge; all-full is handled above.
                st.breaker.release_probe()
                full.add(st.replica.replica_id)
                continue
            except Draining:
                # The replica is mid-hot-swap or shutting down — its old
                # batcher flushed this request un-served.  Not a fault of
                # the replica: re-route at once (a tiny jittered pause
                # keeps a swap transition from becoming a hot spin).  A
                # SECOND Draining from the same replica means it is
                # retiring, not swapping (a swap re-admits on the new
                # pair immediately): exclude it; all-excluded sheds
                # RouterDraining above.
                st.breaker.release_probe()
                rid = st.replica.replica_id
                drain_hits[rid] = drain_hits.get(rid, 0) + 1
                if drain_hits[rid] >= 2:
                    drained.add(rid)
                self._c_retries.inc()
                with self._lock:
                    pause = self._rng.uniform(0.0, 0.005)
                with self.tracer.span("retry", overlap=True, req=req):
                    time.sleep(min(pause, max(remaining, 0.0)))
                continue
            except TimeoutError as e:
                # The budget died inside the replica; record the failure
                # for the breaker but there is nothing left to retry with.
                st.breaker.record_failure()
                with self._lock:
                    st.failed += 1
                raise TimeoutError(
                    f"replica {st.replica.replica_id} exceeded the "
                    f"deadline budget: {e}") from e
            except Exception as e:
                # Replica-side failure (crash, wedged engine, transport):
                # breaker-countable, retryable within the budget.
                st.breaker.record_failure()
                last_err = e
                failures += 1
                failed_on.add(st.replica.replica_id)
                with self._lock:
                    st.failed += 1
                if failures > self.max_retries:
                    raise
                self._c_retries.inc()
                with self._lock:
                    # Jittered exponential backoff, never past deadline.
                    pause = (self.backoff_s * (2 ** (failures - 1))
                             * self._rng.uniform(0.5, 1.5))
                with self.tracer.span("retry", step=seq, overlap=True,
                                      req=req):
                    time.sleep(min(pause,
                                   max(deadline - time.monotonic(), 0.0)))
                # Queues drain during the backoff: re-admit replicas that
                # were merely full so the post-backoff pick can prefer a
                # momentarily-full replica over the one that just FAILED.
                full.clear()
                continue
            st.breaker.record_success()
            with self._lock:
                st.served += 1
                self._served_t.append(time.monotonic())
                if len(self._served_t) > 512:
                    del self._served_t[:256]
            return out, st.replica.replica_id

    def _pick_preferred(self, rid: str, exclude: set,
                        req: Optional[str] = None
                        ) -> Tuple[Optional["_ReplicaState"], Optional[int]]:
        """The sticky-session pick: the pinned replica or nothing.  Same
        gates as :meth:`_pick` — ejection, per-request exclusion, and the
        breaker's ``allow()`` claim — so a pin can never resurrect a
        replica routing would refuse."""
        with self.tracer.span("route", overlap=True, req=req):
            self._c_routed.inc()
            with self._lock:
                self._seq += 1
                seq = self._seq
                st = self._states.get(rid)
            if (st is not None and not st.ejected and rid not in exclude
                    and st.breaker.allow()):
                return st, seq
            return None, seq

    def _pick(self, exclude: set, req: Optional[str] = None
              ) -> Tuple[Optional[_ReplicaState], Optional[int]]:
        """Least-loaded healthy replica (round-robin tie-break), CLOSED
        breakers first; a replica whose breaker is OPEN-past-cooldown or
        HALF-OPEN is only picked when no CLOSED one exists, and claiming
        its single probe slot happens HERE (``allow()``), so probing N
        candidates never leaks N probes.  Recorded as a ``route`` span."""
        with self.tracer.span("route", overlap=True, req=req):
            self._c_routed.inc()
            with self._lock:
                self._seq += 1
                seq = self._seq
                rr = self._rr
                self._rr += 1
                live = [self._states[rid]
                        for rid in (self._order[rr % len(self._order):]
                                    + self._order[:rr % len(self._order)])
                        if not self._states[rid].ejected
                        and rid not in exclude]
            closed = [st for st in live
                      if st.breaker.snapshot()["state"] == "closed"]
            for st in sorted(closed, key=lambda s: s.replica.queue_depth()):
                if st.breaker.allow():
                    return st, seq
            for st in live:     # open/half-open: first claimable probe
                if st.breaker.allow():
                    return st, seq
            return None, seq

    # -- shed math ---------------------------------------------------------

    def _overload_retry_after(self) -> float:
        """Live backlog / measured service rate: how long until the
        queues now standing have drained, clamped to [1, 60] s."""
        depth = 0
        with self._lock:
            states = list(self._states.values())
            now = time.monotonic()
            recent = [t for t in self._served_t if now - t <= 5.0]
        for st in states:
            if not st.ejected:
                try:
                    depth += st.replica.queue_depth()
                except Exception:
                    pass
        rate = len(recent) / 5.0 if recent else 0.0
        if rate <= 0:
            return 1.0
        return min(max(depth / rate, 1.0), 60.0)

    def _readmit_retry_after(self) -> float:
        with self._lock:
            etas = [st.readmit_at for st in self._states.values()
                    if st.ejected]
        if not etas:
            return 1.0
        return min(max(min(etas) - time.monotonic(), 1.0), 60.0)

    # -- health prober -----------------------------------------------------

    def start(self) -> "Router":
        """Start the background health prober (idempotent)."""
        if self._health_thread is None:
            self._health_thread = threading.Thread(
                target=self._health_loop, daemon=True,
                name="router-health")
            self._health_thread.start()
        return self

    def _health_loop(self) -> None:
        while not self._stop.wait(self.health_interval_s):
            try:
                self.health_tick()
            except Exception as e:    # the prober must never die silently
                print(f"WARNING: router health tick failed "
                      f"({type(e).__name__}: {e}); next tick continues",
                      file=sys.stderr)

    def health_tick(self) -> None:
        """One probe round over every replica — the health loop's body,
        callable directly (tests, single-threaded embedders)."""
        now = time.monotonic()
        with self._lock:
            states = [self._states[rid] for rid in self._order]
        for st in states:
            with self._lock:
                if st.ejected and now < st.readmit_at:
                    continue
                ejected = st.ejected
            ok = self._probe(st)
            if ejected and ok:
                with self.tracer.span("readmit"):
                    with self._lock:
                        st.ejected = False
                        st.health_failures = 0
                        st.readmit_backoff_s = 0.0
                    self._c_readmissions.inc()
                st.breaker.record_success()   # give it requests again
                _log(f"router: replica {st.replica.replica_id} healthy "
                     "again; READMITTED to rotation")
            elif ejected and not ok:
                with self._lock:
                    st.readmit_backoff_s = min(
                        max(st.readmit_backoff_s * 2.0,
                            self.readmit_base_s),
                        self.readmit_max_s)
                    st.readmit_at = time.monotonic() + st.readmit_backoff_s
            elif not ejected and not ok:
                with self._lock:
                    st.health_failures += 1
                    trip = st.health_failures >= self.eject_after
                if trip:
                    with self.tracer.span("eject"):
                        with self._lock:
                            st.ejected = True
                            st.ejections += 1
                            st.readmit_backoff_s = self.readmit_base_s
                            st.readmit_at = (time.monotonic()
                                             + st.readmit_backoff_s)
                    self._c_ejections.inc()
                    _log(f"router: replica {st.replica.replica_id} failed "
                         f"{self.eject_after} consecutive health probes; "
                         "EJECTED from rotation (re-admission probes "
                         "backing off exponentially)")
            else:
                with self._lock:
                    st.health_failures = 0

    @staticmethod
    def _probe(st: _ReplicaState) -> bool:
        try:
            h = st.replica.health()
        except Exception:
            return False
        return isinstance(h, dict) and h.get("status") == "ok"

    # -- introspection / lifecycle ----------------------------------------

    def healthy_count(self) -> int:
        """Replicas currently routable (not ejected, breaker not open),
        from the router's own state — no probe round trips, so the
        fleet's rollup gauge can read it on every scrape."""
        with self._lock:
            states = [self._states[rid] for rid in self._order]
            ejected = {id(st) for st in states if st.ejected}
        return sum(1 for st in states
                   if id(st) not in ejected
                   and st.breaker.snapshot()["state"] != "open")

    def replica_health(self) -> List[dict]:
        """Best-effort health of every replica (dead ones reported, not
        raised) — the fleet /healthz body."""
        out = []
        with self._lock:
            states = [self._states[rid] for rid in self._order]
        for st in states:
            try:
                h = dict(st.replica.health())
            except Exception as e:
                h = {"status": "dead", "replica_id": st.replica.replica_id,
                     "error": f"{type(e).__name__}: {e}"}
            with self._lock:
                h["ejected"] = st.ejected
            h["breaker"] = st.breaker.snapshot()["state"]
            out.append(h)
        return out

    def stats(self) -> dict:
        with self._lock:
            base = {
                "replicas": len(self._order),
                "routed": self.routed,
                "retries": self.retries,
                "ejections": self.ejections,
                "readmissions": self.readmissions,
                "shed_no_replicas": self.shed_no_replicas,
                "shed_overloaded": self.shed_overloaded,
                "shed_draining": self.shed_draining,
                "sessions": dict(self._sessions),
                "session_migrations": int(self._c_migrations.value),
            }
            per = [(st, st.ejected, st.served, st.failed, st.ejections)
                   for st in (self._states[rid] for rid in self._order)]
        base["per_replica"] = [{
            "replica_id": st.replica.replica_id,
            "ejected": ejected,
            "served": served,
            "failed": failed,
            "ejections": ejections,
            "breaker": st.breaker.snapshot(),
            "queue_depth": _safe_depth(st.replica),
        } for st, ejected, served, failed, ejections in per]
        return base

    def close(self) -> None:
        """Stop the health prober (idempotent; replicas are owned and
        closed by the fleet, not the router)."""
        self._stop.set()
        t = self._health_thread
        if t is not None:
            t.join(timeout=10.0)
            self._health_thread = None


def _safe_depth(replica) -> Optional[int]:
    try:
        return int(replica.queue_depth())
    except Exception:
        return None


def _log(msg: str) -> None:
    print(msg, file=sys.stderr)
    sys.stderr.flush()
