"""Serving subsystem — the framework's first non-training workload.

The ROADMAP north star ("serve heavy traffic from millions of users")
needs a path from a trainer checkpoint to answered prediction requests.
This package is that path, built from the same parts training runs on:

- ``engine``   :class:`ServeEngine` — the newest *verifiable* checkpoint
               (``resilience.lineage.latest_verifiable``, the trainer's
               own resume walk), eval-mode jitted forwards for a bounded
               set of padded batch buckets over the ``parallel.mesh``
               data axis, AOT-warmed at startup so no request ever pays
               a compile.  The per-shard forward is
               ``train.step.make_eval_apply`` — the exact function
               ``evaluate()`` traces, so served logits cannot drift from
               the training-loop evaluation of the same checkpoint.
- ``batcher``  :class:`DynamicBatcher` — bounded admission queue,
               batches formed on ``max_batch``-or-``max_wait_ms``
               (whichever first), explicit backpressure
               (:class:`QueueFull`) instead of unbounded latency,
               graceful drain for shutdown.
- ``http``     stdlib-only threaded HTTP front end: ``/predict``,
               ``/healthz``, ``/stats``.
- ``__main__`` ``python -m ddp_tpu.serve`` — stand the stack up on a
               checkpoint; SIGTERM drains via the resilience preemption
               guard.

Every stage (queue_wait, batch_form, pad, h2d, forward, d2h) records
``obs.tracer`` spans, so ``python -m ddp_tpu.obs`` and the Perfetto
export explain a serve run exactly as they do a training run; the load
generator lives in ``bench.py --serve`` (open- and closed-loop, latency
percentiles vs offered load, saturation knee).
"""
from .batcher import Draining, DynamicBatcher, QueueFull, percentiles
from .engine import (RequestTooLarge, ServeEngine, ServeError,
                     resolve_buckets)
from .http import ServeHTTPServer

__all__ = [
    "Draining", "DynamicBatcher", "QueueFull", "RequestTooLarge",
    "ServeEngine", "ServeError", "ServeHTTPServer", "percentiles",
    "resolve_buckets",
]
