"""Serving subsystem — the framework's first non-training workload.

The ROADMAP north star ("serve heavy traffic from millions of users")
needs a path from a trainer checkpoint to answered prediction requests.
This package is that path, built from the same parts training runs on:

- ``engine``   :class:`ServeEngine` — the newest *verifiable* checkpoint
               (``resilience.lineage.latest_verifiable``, the trainer's
               own resume walk), eval-mode jitted forwards for a bounded
               set of padded batch buckets over the ``parallel.mesh``
               data axis, AOT-warmed at startup so no request ever pays
               a compile.  The per-shard forward is
               ``train.step.make_eval_apply`` — the exact function
               ``evaluate()`` traces, so served logits cannot drift from
               the training-loop evaluation of the same checkpoint.
- ``batcher``  :class:`DynamicBatcher` — bounded admission queue,
               batches formed on ``max_batch``-or-``max_wait_ms``
               (whichever first), explicit backpressure
               (:class:`QueueFull`) instead of unbounded latency,
               graceful drain for shutdown.
- ``router``   :class:`Router` — the fleet's fault-tolerance layer:
               health-driven ejection with exponential-backoff
               re-admission, per-request deadline budgets with bounded
               jittered retries, a per-replica circuit breaker
               (consecutive-failure trip, half-open single probe), and
               router-level shedding with ``Retry-After`` derived from
               live queue depth.
- ``fleet``    :class:`ServeFleet` — N warmed engine replicas
               (:class:`LocalReplica` in-process pairs and/or
               :class:`HTTPReplica` remote backends) behind the router,
               plus the zero-downtime checkpoint hot-swap watcher
               (``lineage.head_fingerprint`` poll → verified load →
               ``swap_warm`` AOT compile → atomic ``swap_commit``;
               torn publishes skipped with a named event).
- ``http``     stdlib-only threaded HTTP front end: ``/predict``,
               ``/healthz``, ``/stats`` — fronting one engine or a
               whole fleet; idempotent ``close()``.
- ``__main__`` ``python -m ddp_tpu.serve`` — stand the stack up on a
               checkpoint (``--fleet N`` for the router + hot-swap
               stack); SIGTERM drains via the resilience preemption
               guard.

Every stage (queue_wait, batch_form, pad, h2d, forward, d2h) records
``obs.tracer`` spans, so ``python -m ddp_tpu.obs`` and the Perfetto
export explain a serve run exactly as they do a training run; the load
generator lives in ``bench.py --serve`` (open- and closed-loop, latency
percentiles vs offered load, saturation knee).
"""
from .batcher import Draining, DynamicBatcher, QueueFull, percentiles
from .engine import (RequestTooLarge, ServeEngine, ServeError,
                     resolve_buckets)
from .fleet import HTTPReplica, LocalReplica, ServeFleet
from .http import ServeHTTPServer
from .router import (CircuitBreaker, NoHealthyReplicas, ReplicaCrashed,
                     Router, RouterDraining, RouterOverloaded, RouterShed)

__all__ = [
    "CircuitBreaker", "Draining", "DynamicBatcher", "HTTPReplica",
    "LocalReplica", "NoHealthyReplicas", "QueueFull", "ReplicaCrashed",
    "RequestTooLarge", "Router", "RouterDraining", "RouterOverloaded",
    "RouterShed", "ServeEngine", "ServeError", "ServeFleet",
    "ServeHTTPServer", "percentiles", "resolve_buckets",
]
