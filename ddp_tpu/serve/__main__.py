"""``python -m ddp_tpu.serve`` — stand up a model server on a checkpoint.

Loads the newest verifiable checkpoint (the trainer's own lineage walk),
AOT-compiles one eval forward per padded batch bucket, and serves
``/predict`` / ``/healthz`` / ``/stats`` / ``/metrics`` (Prometheus
text exposition) over a stdlib threaded HTTP
server fronted by the dynamic micro-batcher.  SIGTERM/SIGINT drain
gracefully through the resilience preemption guard: admission stops
(503 + draining healthz), accepted requests finish, the span spill is
flushed, exit 0.  A second signal kills immediately (the guard's
standard escape hatch).

Usage:
    python multigpu.py 5 1 --snapshot_path ck.pt        # train
    python -m ddp_tpu.serve --snapshot_path ck.pt --port 8100
    curl -s localhost:8100/healthz
    curl -s -X POST localhost:8100/predict -d '{"instances": [[[..]]]}'
    python -m ddp_tpu.obs serve_spill.jsonl             # telemetry
"""
from __future__ import annotations

import argparse
import json
import sys
import threading
import time
from typing import Optional

from ..utils.platform import pin_platform_from_env

pin_platform_from_env()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m ddp_tpu.serve",
        description=__doc__.splitlines()[0])
    p.add_argument("--snapshot_path", default="checkpoint.pt",
                   help="Checkpoint head path or directory (the trainer's "
                        "--snapshot_path); the newest VERIFIABLE snapshot "
                        "is loaded via resilience.lineage (default: "
                        "checkpoint.pt)")
    p.add_argument("--model", default="vgg",
                   choices=["vgg", "deepnn", "resnet18", "transformer",
                            "tinylm"],
                   help="Model architecture the checkpoint was trained "
                        "with (default: vgg — the reference's model); "
                        "tinylm + --generate serves token streams")
    p.add_argument("--host", default="127.0.0.1",
                   help="Bind address (default 127.0.0.1; 0.0.0.0 to "
                        "expose)")
    p.add_argument("--port", default=8100, type=int,
                   help="Listen port (default 8100; 0 picks a free port "
                        "and prints it)")
    p.add_argument("--buckets", default="1,8,32,128",
                   help="Padded batch buckets, comma-separated; each is "
                        "rounded up to a mesh-size multiple and compiled "
                        "ONCE at startup — the whole executable set, "
                        "bounded and known (default 1,8,32,128)")
    p.add_argument("--max_batch", default=None, type=int,
                   help="Batch-former row target (default: the largest "
                        "bucket)")
    p.add_argument("--max_wait_ms", default=5.0, type=float,
                   help="Batch-forming wait budget from the oldest queued "
                        "request (default 5 ms): a lone request never "
                        "waits longer; a busy queue never waits at all")
    p.add_argument("--queue_depth", default=256, type=int,
                   help="Admission queue bound; a full queue sheds with "
                        "503 instead of queueing into unbounded latency "
                        "(default 256 requests)")
    p.add_argument("--generate", action="store_true",
                   help="Generative decoding mode: front the tinylm "
                        "decoder (models/transformer.py) with a KV-cache "
                        "engine + token-level continuous batcher and "
                        "serve POST /generate; /predict routes stay on "
                        "classifier servers only")
    p.add_argument("--slots", default=8, type=int,
                   help="Generative only: concurrent KV-cache streams "
                        "per replica (rounded up to a data-mesh "
                        "multiple; default 8)")
    p.add_argument("--prefill_buckets", default="16,64",
                   help="Generative only: padded prompt-length buckets, "
                        "comma-separated; prefill + cache-write compile "
                        "once per bucket (default 16,64)")
    p.add_argument("--max_new_tokens", default=32, type=int,
                   help="Generative only: per-request generation cap "
                        "(requests may ask for fewer; default 32)")
    p.add_argument("--fleet", default=0, type=int, metavar="N",
                   help="Serve N in-process engine replicas behind the "
                        "fault-tolerant router (health-driven ejection, "
                        "retry budgets, circuit breakers) instead of one "
                        "bare engine; 0 = single-engine mode (default)")
    p.add_argument("--swap_poll_s", default=0.0, type=float,
                   help="Fleet only: poll the checkpoint lineage every "
                        "this many seconds and hot-swap newly published "
                        "verifiable snapshots into rotation with zero "
                        "downtime (0 disables the watcher; default 0)")
    p.add_argument("--bf16", action="store_true",
                   help="Serve in bfloat16 compute (match the flag the "
                        "checkpoint was trained with for parity)")
    p.add_argument("--num_devices", default=None, type=int,
                   help="Mesh size override (default: all visible "
                        "devices); formed batches shard across the same "
                        "data axis training uses")
    p.add_argument("--trace_spill", default=None,
                   metavar="PATH",
                   help="Span spill (queue_wait/batch_form/pad/h2d/"
                        "forward/d2h), analyzable with python -m "
                        "ddp_tpu.obs exactly like a training spill; '' "
                        "keeps tracing in-memory only (default: "
                        "serve_spill.jsonl next to --snapshot_path, the "
                        "run's output dir)")
    p.add_argument("--obs_off", action="store_true",
                   help="Telemetry kill-switch (the training CLI's "
                        "contract: no spans, no spill, zero overhead)")
    return p


def main(argv: Optional[list] = None) -> int:
    args = build_parser().parse_args(argv)

    from ..obs.registry import MetricsRegistry
    from ..obs.tracer import NullTracer, SpanTracer, set_tracer
    from ..parallel.mesh import make_mesh
    from ..resilience.faults import install_serve_faults
    from ..resilience.preemption import PreemptionGuard
    from .batcher import DynamicBatcher
    from .engine import ServeEngine
    from .fleet import ServeFleet
    from .http import ServeHTTPServer

    # Unset --trace_spill defaults next to the checkpoint head (the
    # run's output dir), not the CWD; '' stays the explicit kill value.
    from ..obs.tracer import default_spill_path
    trace_spill = args.trace_spill
    if trace_spill is None:
        trace_spill = default_spill_path(args.snapshot_path,
                                         "serve_spill.jsonl")
    if args.obs_off:
        tracer = NullTracer()
    else:
        tracer = SpanTracer(spill_path=trace_spill or None,
                            ring=65536, host=0)
    mesh = make_mesh(args.num_devices)
    registry = MetricsRegistry()  # one /metrics surface per process
    buckets = [int(b) for b in args.buckets.split(",") if b]
    compute_dtype = jnp.bfloat16 if args.bf16 else None
    try:
        set_tracer(tracer)
        print(f"loading newest verifiable checkpoint under "
              f"{args.snapshot_path!r} ...", file=sys.stderr)
        fleet = engine = batcher = None
        prefill_buckets = [int(b) for b in args.prefill_buckets.split(",")
                           if b]
        if args.fleet >= 1:
            t0 = time.monotonic()
            fleet = ServeFleet(
                args.snapshot_path, args.model, mesh=mesh,
                n_replicas=args.fleet, buckets=buckets,
                compute_dtype=compute_dtype, max_batch=args.max_batch,
                max_wait_ms=args.max_wait_ms,
                queue_depth=args.queue_depth, tracer=tracer,
                registry=registry, generate=args.generate,
                slots=args.slots, prompt_buckets=prefill_buckets,
                max_new_tokens=args.max_new_tokens)
            install_serve_faults(fleet)
            fleet.start(poll_s=args.swap_poll_s)
            print(f"warmed {args.fleet} replica(s) in "
                  f"{time.monotonic() - t0:.1f}s (checkpoint step "
                  f"{fleet.health()['checkpoint_step']}; hot-swap watcher "
                  f"{'every %.1fs' % args.swap_poll_s if args.swap_poll_s > 0 else 'off'})",
                  file=sys.stderr)
            httpd = ServeHTTPServer((args.host, args.port), fleet=fleet)
        elif args.generate:
            from .kvcache import KVCacheEngine
            from .token_batcher import TokenBatcher
            engine = KVCacheEngine.from_checkpoint(
                args.snapshot_path, args.model, mesh=mesh,
                slots=args.slots, prompt_buckets=prefill_buckets,
                compute_dtype=compute_dtype, tracer=tracer,
                registry=registry)
            t0 = time.monotonic()
            compiled = engine.warm()
            print(f"compiled {compiled} executable(s) (bound "
                  f"{engine.compile_bound}: prefill+write per prompt "
                  f"bucket {list(engine.prompt_buckets)} + 1 decode) in "
                  f"{time.monotonic() - t0:.1f}s (checkpoint "
                  f"{engine.checkpoint_file!r}, step "
                  f"{engine.checkpoint_step}); no stream pays a compile",
                  file=sys.stderr)
            batcher = TokenBatcher(engine,
                                   max_new_tokens=args.max_new_tokens,
                                   queue_depth=args.queue_depth,
                                   tracer=tracer,
                                   registry=registry).start()
            httpd = ServeHTTPServer((args.host, args.port), engine, batcher)
        else:
            engine = ServeEngine.from_checkpoint(
                args.snapshot_path, args.model, mesh=mesh, buckets=buckets,
                compute_dtype=compute_dtype, tracer=tracer,
                registry=registry)
            t0 = time.monotonic()
            compiled = engine.warm()
            print(f"compiled {compiled} bucket executable(s) "
                  f"{list(engine.buckets)} in {time.monotonic() - t0:.1f}s "
                  f"(checkpoint {engine.checkpoint_file!r}, epoch "
                  f"{engine.checkpoint_epoch}); no request pays a compile",
                  file=sys.stderr)
            batcher = DynamicBatcher(engine, max_batch=args.max_batch,
                                     max_wait_ms=args.max_wait_ms,
                                     queue_depth=args.queue_depth,
                                     tracer=tracer,
                                     registry=registry).start()
            httpd = ServeHTTPServer((args.host, args.port), engine, batcher)
        listener = threading.Thread(target=httpd.serve_forever,
                                    daemon=True, name="serve-http")
        listener.start()
        # Graceful drain on SIGTERM/SIGINT — the same resilience guard
        # the trainer uses for preemption (main-thread only; under a
        # non-main-thread embedder, stop via drain()/close()+close()).
        guard = (PreemptionGuard().install()
                 if threading.current_thread() is threading.main_thread()
                 else None)
        host, port = httpd.server_address[:2]
        what = (f"{args.model} fleet of {args.fleet}" if fleet is not None
                else args.model)
        routes = ("/generate" if args.generate else "/predict")
        print(f"serving {what} on http://{host}:{port} "
              f"({routes} /healthz /stats /metrics); SIGTERM drains "
              "gracefully", flush=True)
        try:
            while guard is None or not guard.noticed():
                time.sleep(0.2)
        except KeyboardInterrupt:
            pass  # second Ctrl-C during shutdown lands here; drain anyway
        print("draining: admission stopped, serving accepted requests ...",
              file=sys.stderr)
        if fleet is not None:
            drained = fleet.close(timeout=30.0)
        else:
            drained = batcher.drain(timeout=30.0)
        # Idempotent listener teardown: a second SIGTERM racing this
        # shutdown may have already closed it — close() absorbs that.
        httpd.close()
        if guard is not None:
            guard.uninstall()
        stats = (fleet.stats() if fleet is not None else
                 {"engine": engine.stats(), "batcher": batcher.stats()})
        print(json.dumps(stats), file=sys.stderr)
        print(f"drained={'clean' if drained else 'FORCED'}; bye",
              file=sys.stderr)
        return 0 if drained else 1
    finally:
        set_tracer(NullTracer())
        tracer.flush(fsync=True)
        tracer.close()


if __name__ == "__main__":
    raise SystemExit(main())
