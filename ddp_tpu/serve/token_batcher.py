"""Token-level continuous batching for generative decoding.

The classifier's :class:`~ddp_tpu.serve.batcher.DynamicBatcher` batches
WHOLE requests: one forward serves each request completely.  A
generative request is a stream of decode steps, so batching at request
granularity would convoy every stream behind the longest one.  This
batcher schedules at TOKEN granularity instead (the Orca-style
continuous batching): one engine thread runs the fixed-shape decode
program over ALL live streams each iteration, admitting new streams
into free KV-cache slots BETWEEN iterations — a stream joins the
decode batch the moment a slot frees, never at epoch boundaries.

Scheduling loop, each iteration:

1. admit: while a slot is free and a request is queued, prefill the
   request's prompt into a slot (its first token — the TTFT boundary —
   is produced here);
2. step: ONE decode advances every live stream by one token (inactive
   slots ride along computing garbage that is never read — the
   fixed-shape contract that keeps the compile count at one);
3. retire: streams that produced ``max_new_tokens`` (or whose caller
   abandoned them) release their slot and wake their caller.

The caller-facing contract mirrors the classifier batcher exactly —
bounded admission queue (:class:`QueueFull` at capacity), admission
refusal while draining (:class:`Draining`), oversize rejection at
admission (:class:`RequestTooLarge` — prompt past the largest bucket,
or prompt+max_new past the cache's T_MAX), blocking ``generate()`` with
timeout-abandonment reclaiming the stream's slot — so the router and
fleet treat both batcher kinds through one protocol (``start`` /
``queue_depth`` / ``draining`` / ``drain`` / ``stats``).

Metrics (shared registry when the fleet passes one): ``ddp_gen_*`` —
generated-token and completed-stream counters, TTFT and end-to-end
latency histograms, slot-occupancy gauge.
"""
from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..obs.registry import MetricsRegistry
from ..obs.tracer import get_tracer
from .batcher import Draining, QueueFull, percentiles
from .engine import RequestTooLarge
from .kvcache import KVCacheEngine


class _GenRequest:
    __slots__ = ("prompt", "max_new", "t_submit", "event", "tokens",
                 "ttft_ms", "error", "abandoned", "req_id", "session")

    def __init__(self, prompt: np.ndarray, max_new: int,
                 req_id: Optional[str] = None,
                 session: Optional[str] = None):
        self.prompt = prompt
        self.max_new = max_new
        self.req_id = req_id
        self.session = session
        self.t_submit = time.monotonic()
        self.event = threading.Event()
        self.tokens: Optional[List[int]] = None
        self.ttft_ms: Optional[float] = None
        self.error: Optional[BaseException] = None
        self.abandoned = False


class _Stream:
    __slots__ = ("req", "slot", "tokens", "cur")

    def __init__(self, req: _GenRequest, slot: int, first: int):
        self.req = req
        self.slot = slot
        self.tokens = [first]
        self.cur = first


class TokenBatcher:
    def __init__(self, engine: KVCacheEngine, *,
                 max_new_tokens: int = 32, queue_depth: int = 256,
                 tracer=None, registry=None, metric_labels=None):
        self.engine = engine
        self.max_new_tokens = int(max_new_tokens)
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new_tokens}")
        self._q: "queue.Queue[_GenRequest]" = queue.Queue(
            maxsize=max(int(queue_depth), 1))
        self.tracer = tracer if tracer is not None else get_tracer()
        self._draining = threading.Event()
        self._stopped = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._streams: Dict[int, _Stream] = {}  # slot -> live stream
        self._stats_lock = threading.Lock()
        # analysis: shared-under(_stats_lock)
        self._ttft_ms: collections.deque = collections.deque(maxlen=4096)
        # analysis: shared-under(_stats_lock)
        self._latency_ms: collections.deque = collections.deque(maxlen=4096)
        self.registry = (registry if registry is not None
                         else MetricsRegistry())
        labels = dict(metric_labels or {})
        labelnames = tuple(sorted(labels))
        reg = self.registry
        self._c_submitted = reg.counter(
            "ddp_gen_submitted_total",
            "Generative requests accepted for decoding",
            labelnames).labels(**labels)
        self._c_completed = reg.counter(
            "ddp_gen_completed_total",
            "Streams decoded to completion", labelnames).labels(**labels)
        self._c_tokens = reg.counter(
            "ddp_gen_tokens_total",
            "Tokens generated across all streams",
            labelnames).labels(**labels)
        self._c_shed_queue_full = reg.counter(
            "ddp_gen_shed_queue_full_total",
            "Generative requests shed at admission (queue at capacity)",
            labelnames).labels(**labels)
        self._c_rejected_oversize = reg.counter(
            "ddp_gen_rejected_oversize_total",
            "Requests rejected (prompt or prompt+max_new over budget)",
            labelnames).labels(**labels)
        self._c_timed_out = reg.counter(
            "ddp_gen_timed_out_total",
            "Generative requests whose caller gave up before completion",
            labelnames).labels(**labels)
        self._h_ttft = reg.histogram(
            "ddp_gen_ttft_ms",
            "Time to first token, submit to prefill logits (ms)",
            labelnames).labels(**labels)
        self._h_latency = reg.histogram(
            "ddp_gen_request_latency_ms",
            "Completed-stream latency, submit to last token (ms)",
            labelnames).labels(**labels)
        self._g_occupancy = reg.gauge(
            "ddp_gen_occupancy",
            "Live streams / KV-cache slots (the decode-batch fill rate)",
            labelnames).labels(**labels)
        self._g_occupancy.set_function(
            lambda: self.engine.active_slots() / max(self.engine.slots, 1))

    # -- caller side -------------------------------------------------------

    def generate(self, prompt: Sequence[int],
                 max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = None,
                 req_id: Optional[str] = None,
                 session: Optional[str] = None) -> dict:
        """Block until the stream completes; returns ``{"tokens":
        [generated ids], "prompt_len": n, "ttft_ms": float}``.
        Thread-safe (the one entry point HTTP handler threads call
        concurrently).  ``session`` is the router's sticky-routing key —
        it rides into the stream's spans and stats, the batcher itself
        treats every request as a fresh stream (a migrated session
        simply re-prefills its full history here)."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError(
                f"prompt must be a non-empty 1-D token list, got shape "
                f"{tuple(prompt.shape)}")
        max_new = (self.max_new_tokens if max_new_tokens is None
                   else min(int(max_new_tokens), self.max_new_tokens))
        if max_new < 1:
            raise ValueError(f"max_new_tokens must be >= 1, got "
                             f"{max_new_tokens}")
        n = int(prompt.size)
        if n > self.engine.max_prompt:
            self._c_rejected_oversize.inc()
            raise RequestTooLarge(
                f"{n} prompt tokens exceed the largest prompt bucket "
                f"{self.engine.max_prompt}; shorten the prompt")
        if n + max_new > self.engine.t_max:
            self._c_rejected_oversize.inc()
            raise RequestTooLarge(
                f"prompt ({n}) + max_new_tokens ({max_new}) exceeds the "
                f"KV-cache length T_MAX={self.engine.t_max}")
        if self._draining.is_set():
            raise Draining("server is draining; no new streams accepted")
        req = _GenRequest(prompt, max_new, req_id=req_id, session=session)
        self._c_submitted.inc()
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self._c_shed_queue_full.inc()
            raise QueueFull(
                f"admission queue at capacity ({self._q.maxsize} "
                "requests); retry after backoff") from None
        if self._stopped.is_set():
            self._flush_queue()  # loop already exited: fail stranded work
        if not req.event.wait(timeout):
            req.abandoned = True  # the loop retires it and frees the slot
            self._c_timed_out.inc()
            raise TimeoutError(
                f"stream not completed within {timeout}s (queue depth "
                f"{self._q.qsize()}, "
                f"{self.engine.active_slots()} live streams)")
        if req.error is not None:
            raise req.error
        lat_ms = (time.monotonic() - req.t_submit) * 1e3
        with self._stats_lock:
            self._latency_ms.append(lat_ms)
        self._h_latency.observe(lat_ms)
        return {"tokens": req.tokens, "prompt_len": n,
                "ttft_ms": req.ttft_ms}

    # -- engine thread -----------------------------------------------------

    def start(self) -> "TokenBatcher":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="serve-token-batcher")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while True:
            progressed = self._admit()
            progressed |= self._step()
            if not progressed:
                if self._draining.is_set() and not self._streams \
                        and self._q.empty():
                    self._stopped.set()
                    self._flush_queue()
                    return
                time.sleep(0.002)  # idle: don't spin the GIL

    def _admit(self) -> bool:
        """Prefill queued requests into free slots.  Returns True when
        any stream was admitted (or a request failed at prefill)."""
        progressed = False
        while self.engine.free_slots() > 0:
            try:
                req = self._q.get_nowait()
            except queue.Empty:
                break
            if req.abandoned:
                progressed = True
                continue
            try:
                seq_t0 = time.monotonic()
                slot, first = self.engine.start_stream(req.prompt)
            except BaseException as e:
                req.error = e
                req.event.set()
                progressed = True
                continue
            req.ttft_ms = (time.monotonic() - req.t_submit) * 1e3
            self.tracer.add_span("prefill_admit", seq_t0,
                                 time.monotonic() - seq_t0,
                                 req=req.req_id, overlap=True)
            with self._stats_lock:
                self._ttft_ms.append(req.ttft_ms)
            self._h_ttft.observe(req.ttft_ms)
            self._c_tokens.inc()
            self._streams[slot] = _Stream(req, slot, first)
            progressed = True
        return progressed

    def _step(self) -> bool:
        """One decode iteration over every live stream, then retire the
        finished/abandoned ones.  Returns True when any stream is live."""
        if not self._streams:
            return False
        # Retire abandoned streams BEFORE the step: no token burned on a
        # caller that already gave up.
        for slot in [s for s, st in self._streams.items()
                     if st.req.abandoned]:
            self._retire(slot, completed=False)
        if not self._streams:
            return True
        nxt = self.engine.decode(
            {slot: st.cur for slot, st in self._streams.items()})
        self._c_tokens.inc(len(nxt))
        for slot, tok in nxt.items():
            st = self._streams[slot]
            st.tokens.append(tok)
            st.cur = tok
            if len(st.tokens) >= st.req.max_new:
                self._retire(slot, completed=True)
        return True

    def _retire(self, slot: int, *, completed: bool) -> None:
        st = self._streams.pop(slot)
        self.engine.release(slot)
        if completed:
            st.req.tokens = st.tokens[:st.req.max_new]
            self._c_completed.inc()
            st.req.event.set()

    # -- lifecycle ---------------------------------------------------------

    def _flush_queue(self) -> int:
        leftovers = []
        while True:
            try:
                leftovers.append(self._q.get_nowait())
            except queue.Empty:
                break
        for r in leftovers:
            r.error = Draining("server drained before this stream ran")
            r.event.set()
        return len(leftovers)

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        """Refuse new streams, decode the live ones to completion, stop
        the engine thread.  Idempotent; same contract as the classifier
        batcher's drain."""
        self._draining.set()
        ok = True
        if self._thread is not None:
            self._thread.join(timeout)
            ok = not self._thread.is_alive()
            if ok:
                self._thread = None
        else:
            self._stopped.set()
        stranded = self._flush_queue()
        return ok and not stranded

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def queue_depth(self) -> int:
        """Streams accepted but not yet admitted to a slot — the router's
        least-loaded key, same semantic as the classifier batcher's."""
        return self._q.qsize()

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._stats_lock:
            ttft = list(self._ttft_ms)
            lat = list(self._latency_ms)
        out = {
            "submitted": int(self._c_submitted.value),
            "completed_streams": int(self._c_completed.value),
            "tokens_generated": int(self._c_tokens.value),
            "shed_queue_full": int(self._c_shed_queue_full.value),
            "rejected_oversize": int(self._c_rejected_oversize.value),
            "timed_out": int(self._c_timed_out.value),
            "live_streams": self.engine.active_slots(),
            "slots": self.engine.slots,
            "occupancy": round(
                self.engine.active_slots() / max(self.engine.slots, 1), 3),
            "queue_depth": self._q.qsize(),
            "queue_capacity": self._q.maxsize,
            "max_new_tokens": self.max_new_tokens,
            "draining": self._draining.is_set(),
        }
        out["ttft_ms"] = {k: (round(v, 3) if v is not None else None)
                          for k, v in percentiles(ttft).items()}
        out["latency_ms"] = {k: (round(v, 3) if v is not None else None)
                             for k, v in percentiles(lat).items()}
        return out
