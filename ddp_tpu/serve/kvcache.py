"""KV-cached generative decoding: the serve engine for the tiny LM.

The classifier engine (serve/engine.py) compiles one forward per padded
batch bucket; a generative model needs THREE program families, still a
small fixed set so no request ever waits on a compile (the same
Mesh-TensorFlow serving discipline, PAPERS.md arxiv 1811.02084):

- ``prefill`` — one executable per padded PROMPT-length bucket: full
  causal forward of one stream's prompt, returning the next-token logits
  and the prompt's K/V stack (models/transformer.py:lm_prefill).  The
  prompt batch is a single stream, so it is computed REPLICATED over the
  ``data`` axis (no collective, the auditor's forward invariant) —
  redundant work per prefill, bounded by the prompt bucket, in exchange
  for never re-sharding a batch-of-one;
- ``cache_write`` — one executable per prompt bucket: scatter the
  prefilled K/V into the stream's cache SLOT.  The slot axis is sharded
  over ``data``, so each shard writes iff it owns the slot (an
  axis_index ownership test, no collective at all — this program is
  registered and audited collective-free);
- ``decode`` — ONE executable, ever: all S slots advance one token
  (models/transformer.py:lm_decode_step — in-place
  dynamic_update_slice writes at each stream's position, masked
  attention over its valid prefix).  Inactive slots compute garbage that
  is never read (their positions are dead until a prefill overwrites
  from 0), which is what keeps the shape — and therefore the compile
  count — FIXED regardless of which streams are live.  The cache
  buffers are donated, so a decode step allocates no second cache.

Cache layout: ``[n_layers, slots, T_MAX, n_heads, head_dim]`` x2 (K and
V), slots sharded over ``data``, the heads dim sharded over ``model``
under a TP plan (each model shard holds its own heads' cache — the
attention stays zero-communication in decode exactly as in training;
the only model-axis collectives are the recipe's row psums, priced by
``expected_collectives`` and enforced by ``python -m ddp_tpu.analysis``).

Mesh portability: checkpoints are canonical (replicated per-leaf), so a
``--mesh_shape 2,4`` TP-trained LM snapshot loads onto a 1-D serving
mesh through the same ``latest_verifiable`` + ``load_for_mesh`` walk the
classifier engine uses — tests/test_kvcache.py pins the served logits
against the training-side full-sequence forward at every step.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..obs.registry import MetricsRegistry
from ..obs.tracer import get_tracer
from ..parallel.mesh import DATA_AXIS, MODEL_AXIS, replicated_sharding
from .engine import RequestTooLarge, ServeError  # noqa: F401 (re-export)


def _wiring(plan):
    """(param specs, tp_axis, tp_recipe, extra shard_map kwargs) — the
    serve twin of train/step.py:_eval_wiring."""
    from ..parallel.tp.plan import is_trivial, recipe_override
    if plan is None or is_trivial(plan):
        return P(), None, None, {}
    return (plan.param_specs, MODEL_AXIS, recipe_override(plan),
            {"check_vma": False})


def _cache_specs(mesh, plan) -> Tuple[P, P]:
    """(cache spec, fresh-K/V spec).  Cache ``[L, S, T, h, hd]``: slots on
    ``data``, heads on ``model`` under a plan; fresh prefill K/V
    ``[L, T, h, hd]`` is replicated over ``data`` (single stream), heads
    on ``model``."""
    tp = plan is not None and MODEL_AXIS in mesh.axis_names
    return (P(None, DATA_AXIS, None, MODEL_AXIS if tp else None, None),
            P(None, None, MODEL_AXIS if tp else None, None))


def make_lm_prefill(module, mesh, *, compute_dtype=None, plan=None,
                    on_trace=None):
    """Jitted prompt prefill: ``fn(params, tokens[T]) -> (logits[T, V],
    k[L, T, h, hd], v[L, T, h, hd])`` — one stream, computed replicated
    over ``data`` (heads sharded over ``model`` under ``plan``).  One
    executable per padded T bucket."""
    p_specs, tp_axis, tp_recipe, extra = _wiring(plan)
    _, kv_spec = _cache_specs(mesh, plan)

    def _shard_body(params, tokens):
        if on_trace is not None:
            on_trace()
        logits, k, v = module.lm_prefill(
            params, tokens[None, :], compute_dtype=compute_dtype,
            tp_axis=tp_axis, tp_recipe=tp_recipe)
        return logits[0], k[:, 0], v[:, 0]

    mapped = jax.shard_map(
        _shard_body, mesh=mesh,
        in_specs=(p_specs, P()),
        out_specs=(P(), kv_spec, kv_spec),
        **extra,
    )
    return jax.jit(mapped, out_shardings=(
        replicated_sharding(mesh), NamedSharding(mesh, kv_spec),
        NamedSharding(mesh, kv_spec)))


def make_cache_write(mesh, plan=None, *, on_trace=None):
    """Jitted slot scatter: ``fn(k_cache, v_cache, k_new[L, T_b, h, hd],
    v_new, slot) -> (k_cache, v_cache)`` — writes the prefilled K/V into
    ``slot`` at positions ``0..T_b-1``.  The slot axis is sharded over
    ``data``: each shard writes iff it owns the slot (pure ownership
    arithmetic — this program is collective-free and audited so).  Cache
    args are donated; one executable per prompt bucket."""
    cache_spec, kv_spec = _cache_specs(mesh, plan)
    extra = {} if plan is None else {"check_vma": False}

    def _shard_body(k_cache, v_cache, k_new, v_new, slot):
        if on_trace is not None:
            on_trace()
        s_local = k_cache.shape[1]
        li = slot - lax.axis_index(DATA_AXIS) * s_local
        owns = (li >= 0) & (li < s_local)
        li = jnp.clip(li, 0, s_local - 1)

        def write(cache, new):
            cur = lax.dynamic_index_in_dim(cache, li, axis=1,
                                           keepdims=False)
            upd = lax.dynamic_update_slice(
                cur, new.astype(cache.dtype), (0, 0, 0, 0))
            upd = jnp.where(owns, upd, cur)
            return lax.dynamic_update_index_in_dim(cache, upd, li, axis=1)

        return write(k_cache, k_new), write(v_cache, v_new)

    mapped = jax.shard_map(
        _shard_body, mesh=mesh,
        in_specs=(cache_spec, cache_spec, kv_spec, kv_spec, P()),
        out_specs=(cache_spec, cache_spec),
        **extra,
    )
    sh = NamedSharding(mesh, cache_spec)
    return jax.jit(mapped, donate_argnums=(0, 1),
                   out_shardings=(sh, sh))


def make_lm_decode(module, mesh, *, compute_dtype=None, plan=None,
                   on_trace=None):
    """Jitted decode step: ``fn(params, tokens[S], positions[S], k_cache,
    v_cache) -> (logits[S, V], k_cache, v_cache)`` — every slot advances
    one token (write at its position, attend over its valid prefix).
    Slots sharded over ``data``, heads over ``model``; cache donated.
    ONE executable for the whole serving run — the fixed [S] shape is
    the compile-bound contract."""
    p_specs, tp_axis, tp_recipe, extra = _wiring(plan)
    cache_spec, _ = _cache_specs(mesh, plan)

    def _shard_body(params, tokens, positions, k_cache, v_cache):
        if on_trace is not None:
            on_trace()
        return module.lm_decode_step(
            params, tokens, positions, k_cache, v_cache,
            compute_dtype=compute_dtype, tp_axis=tp_axis,
            tp_recipe=tp_recipe)

    mapped = jax.shard_map(
        _shard_body, mesh=mesh,
        in_specs=(p_specs, P(DATA_AXIS), P(DATA_AXIS), cache_spec,
                  cache_spec),
        out_specs=(P(DATA_AXIS), cache_spec, cache_spec),
        **extra,
    )
    sh = NamedSharding(mesh, cache_spec)
    return jax.jit(mapped, donate_argnums=(3, 4),
                   out_shardings=(NamedSharding(mesh, P(DATA_AXIS)),
                                  sh, sh))


def resolve_prompt_buckets(buckets: Sequence[int],
                           t_max: int) -> Tuple[int, ...]:
    """The padded prompt-length bucket set: deduplicated, ascending,
    clamped into ``[1, t_max]`` — unlike batch buckets there is no
    mesh-multiple rounding (the T axis is never sharded)."""
    if not buckets:
        raise ValueError("need at least one prompt bucket")
    if any(b < 1 for b in buckets):
        raise ValueError(f"prompt buckets must be >= 1, got {list(buckets)}")
    out = tuple(sorted({min(int(b), t_max) for b in buckets}))
    return out


class SlotsExhausted(ServeError):
    """Every KV-cache slot is occupied — admission-level backpressure;
    the token batcher queues behind this, never the engine."""


class KVCacheEngine:
    """Slot-managed generative decoding over a fixed compiled-program set.

    Single-caller by design (the token batcher's engine thread is the one
    caller); a lock still guards the pipeline so misuse degrades to
    serialization.  The compile-bound contract: ``2 * len(prompt
    buckets) + 1`` executables (prefill + cache-write per bucket, one
    decode), proved by ``trace_count`` exactly like the classifier
    engine.
    """

    def __init__(self, module, params, mesh, *, slots: int = 8,
                 prompt_buckets: Sequence[int] = (16, 64),
                 compute_dtype=None, plan=None, tracer=None,
                 registry=None, metric_labels=None):
        d = int(mesh.shape[DATA_AXIS])
        slots = -(-int(slots) // d) * d  # data-shardable slot count
        self.module = module
        self.mesh = mesh
        self.compute_dtype = compute_dtype
        self.slots = slots
        self.t_max = int(module.T_MAX)
        self.prompt_buckets = resolve_prompt_buckets(prompt_buckets,
                                                     self.t_max)
        self.max_prompt = self.prompt_buckets[-1]
        # Protocol alias: healthz/fleet surfaces that report a
        # classifier engine's batch buckets report prompt buckets here.
        self.buckets = self.prompt_buckets
        self.compile_bound = 2 * len(self.prompt_buckets) + 1
        self.trace_count = 0  # analysis: shared-under(_stats_lock)
        self.tracer = tracer if tracer is not None else get_tracer()
        self.registry = (registry if registry is not None
                         else MetricsRegistry())
        labels = dict(metric_labels or {})
        labelnames = tuple(sorted(labels))
        self._c_prefills = self.registry.counter(
            "ddp_kvcache_prefills_total",
            "Prompt prefills executed, by padded prompt bucket",
            labelnames + ("bucket",))
        self._prefill_children = {
            b: self._c_prefills.labels(bucket=str(b), **labels)
            for b in self.prompt_buckets}
        self._c_decode_steps = self.registry.counter(
            "ddp_kvcache_decode_steps_total",
            "Decode steps executed (all slots advance together)",
            labelnames).labels(**labels)
        self._g_active = self.registry.gauge(
            "ddp_kvcache_active_slots",
            "KV-cache slots currently bound to live streams",
            labelnames).labels(**labels)
        self._g_slots = self.registry.gauge(
            "ddp_kvcache_slots", "Total KV-cache slots",
            labelnames).labels(**labels)
        self._g_slots.set(self.slots)
        self._g_compiled = self.registry.gauge(
            "ddp_engine_compiled_executables",
            "Executables compiled so far (the compile-bound contract)",
            labelnames).labels(**labels)

        def _on_trace() -> None:
            with self._stats_lock:
                self.trace_count += 1
            self._g_compiled.inc()

        self._prefill = make_lm_prefill(module, mesh,
                                        compute_dtype=compute_dtype,
                                        plan=plan, on_trace=_on_trace)
        self._write = make_cache_write(mesh, plan, on_trace=_on_trace)
        self._decode = make_lm_decode(module, mesh,
                                      compute_dtype=compute_dtype,
                                      plan=plan, on_trace=_on_trace)

        rep = replicated_sharding(mesh)
        if plan is None:
            self._params = jax.device_put(
                jax.tree_util.tree_map(jnp.asarray, params), rep)
        else:
            # Per-leaf plan shardings (the checkpoint is canonical).
            self._params = jax.device_put(
                jax.tree_util.tree_map(jnp.asarray, params),
                jax.tree_util.tree_map(
                    lambda s: NamedSharding(mesh, s), plan.param_specs))

        cache_spec, _ = _cache_specs(mesh, plan)
        cd = compute_dtype or jnp.float32
        shape = (int(module.N_LAYERS), slots, self.t_max,
                 int(module.N_HEADS), int(module.HEAD_DIM))
        csh = NamedSharding(mesh, cache_spec)
        self._k = jax.device_put(jnp.zeros(shape, cd), csh)
        self._v = jax.device_put(jnp.zeros(shape, cd), csh)

        self._lock = threading.Lock()        # the pipeline
        self._stats_lock = threading.Lock()  # counters (probe-readable)
        self._free = list(range(slots))
        self._pos: Dict[int, int] = {}       # slot -> next write position
        self.prefills = 0       # analysis: shared-under(_stats_lock)
        self.decode_steps = 0   # analysis: shared-under(_stats_lock)
        self.tokens_out = 0     # analysis: shared-under(_stats_lock)
        self.warmed = False     # analysis: shared-under(_stats_lock)
        self.checkpoint_file: Optional[str] = None
        self.checkpoint_epoch: Optional[int] = None
        self.checkpoint_step: Optional[int] = None

    # -- construction ------------------------------------------------------

    @classmethod
    def from_checkpoint(cls, snapshot_path: str, model_name: str, *, mesh,
                        slots: int = 8, prompt_buckets=(16, 64),
                        compute_dtype=None, plan=None, tracer=None,
                        registry=None,
                        metric_labels=None) -> "KVCacheEngine":
        """Load the newest verifiable checkpoint — the SAME lineage walk
        as the classifier engine (any-mesh snapshot onto this serving
        mesh)."""
        import functools

        from ..models import transformer as tfm
        from ..resilience.lineage import latest_verifiable
        from ..train.checkpoint import CheckpointError
        from ..train.ckpt_shard import load_for_mesh
        if model_name != tfm.LM_NAME:
            raise ValueError(
                f"generative serving supports the {tfm.LM_NAME!r} decoder "
                f"(models/transformer.py), got {model_name!r}")
        loaded = latest_verifiable(
            snapshot_path,
            loader=functools.partial(load_for_mesh, mesh=mesh))
        if loaded is None:
            raise CheckpointError(
                f"no checkpoint found under {snapshot_path!r}; train the "
                "LM first (python -m ddp_tpu.train.lm --snapshot_path)")
        ckpt, used = loaded
        eng = cls(tfm, ckpt.params, mesh, slots=slots,
                  prompt_buckets=prompt_buckets,
                  compute_dtype=compute_dtype, plan=plan, tracer=tracer,
                  registry=registry, metric_labels=metric_labels)
        eng.checkpoint_file = used
        eng.checkpoint_epoch = int(ckpt.epoch)
        eng.checkpoint_step = int(ckpt.step)
        return eng

    def warm(self) -> int:
        """Compile every executable NOW: prefill + cache-write per prompt
        bucket, the one decode program.  Returns ``trace_count`` (==
        ``compile_bound`` when nothing was warm)."""
        with self._lock:
            for b in self.prompt_buckets:
                zeros = jnp.zeros((b,), jnp.int32)
                logits, k, v = self._prefill(self._params, zeros)
                jax.block_until_ready(logits)
                self._k, self._v = self._write(
                    self._k, self._v, k, v, jnp.asarray(0, jnp.int32))
            logits, self._k, self._v = self._decode(
                self._params, jnp.zeros((self.slots,), jnp.int32),
                jnp.zeros((self.slots,), jnp.int32), self._k, self._v)
            jax.block_until_ready(logits)
        with self._stats_lock:
            self.warmed = True
            return self.trace_count

    # -- slot lifecycle ----------------------------------------------------

    def free_slots(self) -> int:
        with self._stats_lock:
            return len(self._free)

    def active_slots(self) -> int:
        with self._stats_lock:
            return self.slots - len(self._free)

    def bucket_for(self, n_tokens: int) -> int:
        for b in self.prompt_buckets:
            if n_tokens <= b:
                return b
        raise RequestTooLarge(
            f"{n_tokens} prompt tokens exceed the largest prompt bucket "
            f"{self.max_prompt}; shorten the prompt or restart with a "
            "larger --prefill_buckets set")

    def start_stream(self, prompt: Sequence[int]) -> Tuple[int, int]:
        """Admit one stream: allocate a slot, prefill its prompt into the
        slot's cache, return ``(slot, first generated token)`` — the TTFT
        boundary.  :class:`SlotsExhausted` when no slot is free,
        :class:`RequestTooLarge` past the largest prompt bucket."""
        prompt = np.asarray(prompt, np.int32)
        if prompt.ndim != 1 or prompt.size == 0:
            raise ValueError(f"prompt must be a non-empty 1-D token list, "
                             f"got shape {prompt.shape}")
        if np.any(prompt < 0) or np.any(prompt >= int(self.module.VOCAB)):
            raise ValueError(
                f"prompt tokens must be in [0, {int(self.module.VOCAB)})")
        n = int(prompt.size)
        bucket = self.bucket_for(n)
        with self._stats_lock:
            if not self._free:
                raise SlotsExhausted(
                    f"all {self.slots} KV-cache slots are occupied")
            slot = self._free.pop(0)
        padded = np.zeros((bucket,), np.int32)
        padded[:n] = prompt
        with self._lock:
            logits, k, v = self._prefill(self._params, jnp.asarray(padded))
            self._k, self._v = self._write(
                self._k, self._v, k, v, jnp.asarray(slot, jnp.int32))
            first = int(np.argmax(np.asarray(
                jax.device_get(logits[n - 1]))))
        with self._stats_lock:
            self._pos[slot] = n
            self.prefills += 1
            self.tokens_out += 1
        self._prefill_children[bucket].inc()
        self._g_active.set(self.active_slots())
        return slot, first

    def release(self, slot: int) -> None:
        """Return a finished/abandoned stream's slot to the free pool.
        No cache scrub is needed: a future prefill overwrites from
        position 0 and nothing past a stream's position is ever read."""
        with self._stats_lock:
            if slot in self._pos:
                del self._pos[slot]
                self._free.append(slot)
        self._g_active.set(self.active_slots())

    def position(self, slot: int) -> int:
        with self._stats_lock:
            return self._pos[slot]

    # -- decoding ----------------------------------------------------------

    def decode(self, last_tokens: Dict[int, int]) -> Dict[int, int]:
        """One decode step for the given ``{slot: last token}`` streams;
        every OTHER slot rides along computing garbage that is never read
        (the fixed-shape contract).  Returns ``{slot: next token}`` and
        advances each stream's position."""
        if not last_tokens:
            return {}
        tokens = np.zeros((self.slots,), np.int32)
        positions = np.zeros((self.slots,), np.int32)
        with self._stats_lock:
            for slot, tok in last_tokens.items():
                pos = self._pos[slot]
                if pos >= self.t_max:
                    raise ServeError(
                        f"slot {slot} is at T_MAX={self.t_max}; the "
                        "batcher must finish streams before the cache "
                        "runs out of positions")
                tokens[slot] = tok
                positions[slot] = pos
        with self._lock:
            logits, self._k, self._v = self._decode(
                self._params, jnp.asarray(tokens), jnp.asarray(positions),
                self._k, self._v)
            out = np.asarray(jax.device_get(logits))
        nxt = {slot: int(np.argmax(out[slot])) for slot in last_tokens}
        with self._stats_lock:
            for slot in last_tokens:
                self._pos[slot] += 1
            self.decode_steps += 1
            self.tokens_out += len(nxt)
        self._c_decode_steps.inc()
        return nxt

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._stats_lock:
            return {
                "slots": self.slots,
                "active_slots": self.slots - len(self._free),
                "prompt_buckets": list(self.prompt_buckets),
                "compiled_executables": self.trace_count,
                "compile_bound": self.compile_bound,
                "prefills": self.prefills,
                "decode_steps": self.decode_steps,
                "tokens_out": self.tokens_out,
                "t_max": self.t_max,
                "mesh_devices": int(self.mesh.devices.size),
                "compute_dtype": (str(np.dtype(self.compute_dtype).name)
                                  if self.compute_dtype is not None
                                  else "float32"),
                "checkpoint": {
                    "file": self.checkpoint_file,
                    "epoch": self.checkpoint_epoch,
                    "step": self.checkpoint_step,
                },
            }
