"""The serving forward engine: bucketed, AOT-warmed, mesh-sharded.

Mesh-TensorFlow's discipline for production TPU inference (Shazeer et
al., PAPERS.md arxiv 1811.02084) is a SMALL, FIXED set of padded-shape
compiled programs — every request executes one of them, none ever waits
on a compile.  This engine is that discipline around the training
framework's own eval forward: the per-shard apply is
:func:`~ddp_tpu.train.step.make_eval_apply`, the exact function
``evaluate()``'s counters trace, so served logits cannot drift from the
training-loop evaluation of the same checkpoint (tests/test_serve.py
pins bit-identity at matched bucket shapes).

Mesh portability: checkpoints are canonical (replicated per-leaf) no
matter what mesh trained them — a tensor-parallel ``--mesh_shape`` run
GATHERS its model-sharded params at save time (train/trainer.py) — so
this engine serves a TP-trained snapshot on its own (typically 1-D)
serving mesh with no conversion step; tests/test_serve.py pins the
(2,4)-train -> 1-D-serve logits against the training-side eval forward.

Shape policy: requests are padded up to the smallest *bucket* (each
bucket rounded up to a mesh-size multiple so the ``data``-axis shard_map
sees equal shards), the bucket set is fixed at construction, and every
bucket's executable is compiled at startup (``warm()``).  A request
larger than the largest bucket is refused with :class:`RequestTooLarge`
— the caller-visible alternative to an unbounded-compile surprise.
The engine COUNTS traces (``trace_count`` — a Python side effect inside
the traced function, so it increments exactly once per compiled
executable and never on a cache hit): the compile-bound contract is an
assertable number, not a comment.

Telemetry: every forward records ``pad`` / ``h2d`` / ``forward`` /
``d2h`` spans (obs/tracer.py) keyed by a running batch sequence number,
so ``python -m ddp_tpu.obs`` and the Perfetto export explain serve runs
exactly as they do training runs.
"""
from __future__ import annotations

import threading
from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..obs.registry import MetricsRegistry
from ..obs.tracer import get_tracer
from ..parallel.mesh import batch_sharding, replicated_sharding
from ..train.step import make_eval_forward

# Batch sequence numbers are PROCESS-GLOBAL, not per-engine: a fleet
# runs several engines at once and a checkpoint hot-swap replaces an
# engine mid-run, so a per-engine counter would reuse step keys across
# replicas/generations and make the span-spill request->batch join
# (obs/export.py request_chains) ambiguous.  The batcher claims the seq
# at batch formation and passes it to forward(); a direct forward() call
# claims its own.
_SEQ_LOCK = threading.Lock()
_NEXT_SEQ = 0


def claim_batch_seq() -> int:
    """The next process-unique batch sequence number (span step key)."""
    global _NEXT_SEQ
    with _SEQ_LOCK:
        seq = _NEXT_SEQ
        _NEXT_SEQ += 1
        return seq


class ServeError(Exception):
    """Base class for request-visible serving failures."""


class RequestTooLarge(ServeError):
    """More rows than the largest padded batch bucket — the engine will
    never compile an ad-hoc shape for it; split the request instead."""


def resolve_buckets(buckets: Sequence[int], mesh_size: int) -> Tuple[int, ...]:
    """The effective padded-batch bucket set: each requested bucket
    rounded UP to a mesh-size multiple (the ``data``-axis shard_map needs
    equal per-device shards), deduplicated, ascending.  Rounding two
    requested buckets onto one shape (e.g. 1 and 8 on an 8-device mesh)
    is normal — the compile-bound contract is on the RESOLVED set."""
    if not buckets:
        raise ValueError("need at least one batch bucket")
    if any(b < 1 for b in buckets):
        raise ValueError(f"batch buckets must be >= 1, got {list(buckets)}")
    return tuple(sorted({-(-int(b) // mesh_size) * mesh_size
                         for b in buckets}))


class ServeEngine:
    """Eval-mode forwards over the training mesh, one executable per bucket.

    ``forward()`` is synchronous and single-caller by design (the dynamic
    batcher's engine thread is the one caller in the serving stack); it
    is still guarded by a lock so misuse degrades to serialization, not
    interleaved telemetry.
    """

    # CIFAR sample shape — the one input every model in the registry takes.
    input_shape = (32, 32, 3)

    def __init__(self, model, params, batch_stats, mesh, *,
                 buckets: Sequence[int] = (1, 8, 32, 128),
                 compute_dtype=None, tracer=None, registry=None,
                 metric_labels=None):
        self.model = model
        self.mesh = mesh
        self.compute_dtype = compute_dtype
        self.buckets = resolve_buckets(buckets, mesh.devices.size)
        self.max_rows = self.buckets[-1]
        self.trace_count = 0  # analysis: shared-under(_stats_lock)
        # Registry instruments: private registry by default (instance
        # isolation); the fleet passes its shared one with a replica
        # label so /metrics rolls every engine up side by side.  The
        # legacy stats() fields stay per-engine (a hot-swap starts a
        # fresh engine); the registry children are cumulative per label,
        # which is exactly Prometheus counter semantics across swaps.
        self.registry = (registry if registry is not None
                         else MetricsRegistry())
        labels = dict(metric_labels or {})
        labelnames = tuple(sorted(labels))
        self._c_rows = self.registry.counter(
            "ddp_engine_rows_served_total",
            "Valid rows returned by forward()", labelnames).labels(**labels)
        self._c_forwards = self.registry.counter(
            "ddp_engine_forwards_total",
            "Compiled forwards executed, by padded bucket",
            labelnames + ("bucket",))
        self._fwd_children = {
            b: self._c_forwards.labels(bucket=str(b), **labels)
            for b in self.buckets}
        self._g_compiled = self.registry.gauge(
            "ddp_engine_compiled_executables",
            "Executables compiled so far (the compile-bound contract)",
            labelnames).labels(**labels)

        def _on_trace() -> None:
            # Tracing happens inside warm()/forward() calls while /stats
            # and /healthz threads read the counter — same lock as every
            # other counter (never held around a device computation, so
            # no ordering risk with the pipeline _lock).
            with self._stats_lock:
                self.trace_count += 1
            self._g_compiled.inc()

        self._fwd = make_eval_forward(model, mesh, compute_dtype,
                                      on_trace=_on_trace)
        rep = replicated_sharding(mesh)
        as_dev = lambda t: jax.device_put(  # noqa: E731
            jax.tree_util.tree_map(jnp.asarray, t), rep)
        self._params = as_dev(params)
        self._stats = as_dev(batch_stats)
        self._sharding = batch_sharding(mesh)
        self.tracer = tracer if tracer is not None else get_tracer()
        self._lock = threading.Lock()  # the pipeline (one forward at a time)
        # Counters get their OWN lock: /healthz and /stats read them and
        # must not block behind an in-flight forward (hundreds of ms at
        # load — a health probe that flaps under load is worse than none).
        self._stats_lock = threading.Lock()
        # Batches this engine instance ran (the span step key is the
        # process-global claim_batch_seq(), not this).
        self._forward_batches = 0  # analysis: shared-under(_stats_lock)
        # analysis: shared-under(_stats_lock)
        self._per_bucket: Dict[int, int] = {b: 0 for b in self.buckets}
        self.rows_served = 0  # analysis: shared-under(_stats_lock)
        self.warmed = False   # analysis: shared-under(_stats_lock)
        # Provenance (set by from_checkpoint): which snapshot this engine
        # answers for — surfaced on /healthz so "what model is live" is
        # one curl, not an ops archaeology session.
        self.checkpoint_file: Optional[str] = None
        self.checkpoint_epoch: Optional[int] = None
        self.checkpoint_step: Optional[int] = None

    # -- construction ------------------------------------------------------

    @classmethod
    def from_checkpoint(cls, snapshot_path: str, model_name: str, *, mesh,
                        buckets: Sequence[int] = (1, 8, 32, 128),
                        compute_dtype=None, tracer=None,
                        registry=None) -> "ServeEngine":
        """Load the newest *verifiable* checkpoint under ``snapshot_path``
        (a head path or a directory) through the SAME lineage walk the
        trainer's ``--resume`` uses — ``resilience.lineage
        .latest_verifiable`` — so a torn head falls back to the newest
        retained snapshot instead of serving nothing.

        The loader is ``ckpt_shard.load_for_mesh`` bound to the SERVING
        mesh: a training run that wrote per-host SHARD files
        (``--ckpt_format sharded``, any (d, m) shape) serves on this
        engine's own — typically 1-D — mesh with no conversion step, the
        leaves assembled shard-by-shard straight onto their replicated
        serving placement (never a whole-pytree host copy); gathered v1
        files stream leaf-by-leaf the same way."""
        import functools

        from ..models import get_model
        from ..resilience.lineage import latest_verifiable
        from ..train.checkpoint import CheckpointError
        from ..train.ckpt_shard import load_for_mesh
        loaded = latest_verifiable(
            snapshot_path,
            loader=functools.partial(load_for_mesh, mesh=mesh))
        if loaded is None:
            raise CheckpointError(
                f"no checkpoint found under {snapshot_path!r}; the serve "
                "engine needs a trained snapshot (run training with "
                "--snapshot_path first)")
        ckpt, used = loaded
        engine = cls(get_model(model_name), ckpt.params, ckpt.batch_stats,
                     mesh, buckets=buckets, compute_dtype=compute_dtype,
                     tracer=tracer, registry=registry)
        engine.checkpoint_file = used
        engine.checkpoint_epoch = int(ckpt.epoch)
        engine.checkpoint_step = int(ckpt.step)
        return engine

    def warm(self) -> int:
        """Compile every bucket's executable NOW (startup), so no request
        ever pays a compile.  Returns the number of compiled executables
        (== the resolved bucket-set size; ``trace_count`` proves it)."""
        for b in self.buckets:
            zeros = np.zeros((b,) + self.input_shape, np.uint8)
            jax.block_until_ready(self._fwd(
                self._params, self._stats,
                jax.device_put(zeros, self._sharding)))
        with self._stats_lock:  # health probes read both concurrently
            self.warmed = True
            return self.trace_count

    # -- serving -----------------------------------------------------------

    def bucket_for(self, n_rows: int) -> int:
        """Smallest bucket holding ``n_rows``; :class:`RequestTooLarge`
        beyond the largest (shedding belongs at ADMISSION, not after the
        work is half done)."""
        for b in self.buckets:
            if n_rows <= b:
                return b
        raise RequestTooLarge(
            f"{n_rows} rows exceed the largest padded batch bucket "
            f"{self.max_rows}; split the request or restart the server "
            "with a larger --buckets set")

    def forward(self, images: np.ndarray,
                seq: Optional[int] = None) -> np.ndarray:
        """Logits for ``images`` (uint8 ``[n, 32, 32, 3]`` — the loaders'
        wire format; one dtype keeps the executable set at one program
        per bucket).  Pads to the bucket, runs the compiled forward,
        returns the valid ``[n, num_classes]`` float32 rows.

        ``seq`` is the batch sequence key for this forward's spans —
        the batcher claims it at batch formation (so its queue_wait/
        batch_form spans share it); a direct call claims its own."""
        images = np.asarray(images)
        if images.ndim != 4 or images.shape[1:] != self.input_shape:
            raise ValueError(
                f"expected images [n, {', '.join(map(str, self.input_shape))}"
                f"], got {images.shape}")
        if images.dtype != np.uint8:
            raise ValueError(
                f"expected uint8 images (the loaders' wire format), got "
                f"{images.dtype}; scale/quantize on the client")
        n = images.shape[0]
        if n == 0:
            return np.zeros((0, 0), np.float32)
        bucket = self.bucket_for(n)
        if seq is None:
            seq = claim_batch_seq()
        with self._lock:
            with self._stats_lock:
                self._forward_batches += 1
            tracer = self.tracer
            with tracer.span("pad", step=seq):
                if n < bucket:
                    padded = np.zeros((bucket,) + self.input_shape, np.uint8)
                    padded[:n] = images
                else:
                    padded = images
            with tracer.span("h2d", step=seq):
                dev = jax.device_put(padded, self._sharding)
            with tracer.span("forward", step=seq):
                out = self._fwd(self._params, self._stats, dev)
                out.block_until_ready()
            with tracer.span("d2h", step=seq):
                logits = np.asarray(jax.device_get(out))[:n]
            with self._stats_lock:
                self._per_bucket[bucket] += 1
                self.rows_served += n
            self._fwd_children[bucket].inc()
            self._c_rows.inc(n)
        return logits

    def predict(self, images: np.ndarray) -> np.ndarray:
        """Argmax class ids — the ``/predict`` convenience over
        :meth:`forward`."""
        return np.argmax(self.forward(images), axis=-1).astype(np.int64)

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._stats_lock:  # never the pipeline lock: see __init__
            return {
                "buckets": list(self.buckets),
                "compiled_executables": self.trace_count,
                "forward_batches": self._forward_batches,
                "forward_batches_per_bucket": {
                    str(b): c for b, c in self._per_bucket.items()},
                "rows_served": self.rows_served,
                "mesh_devices": int(self.mesh.devices.size),
                "compute_dtype": (str(np.dtype(self.compute_dtype).name)
                                  if self.compute_dtype is not None
                                  else "float32"),
                "checkpoint": {
                    "file": self.checkpoint_file,
                    "epoch": self.checkpoint_epoch,
                    "step": self.checkpoint_step,
                },
            }
