"""Replica fleet: N serving engines behind one router, hot-swappable.

The fleet owns what the :class:`~ddp_tpu.serve.router.Router` only
routes over — replica construction, the checkpoint they serve, and the
zero-downtime path that changes it:

- :class:`LocalReplica` — one in-process (engine, batcher) pair.  The
  pair reference is swapped ATOMICALLY under a lock: after a swap, new
  requests land on the new pair immediately while the OLD batcher
  drains — every request it already accepted is served by the engine
  that accepted it, so no response is ever computed from a batch
  spanning two checkpoints.  Admission never stops; "never drain" means
  the *fleet front door*, not the retiring batcher.

- :class:`HTTPReplica` — the same replica protocol over a remote
  ``python -m ddp_tpu.serve`` process (stdlib urllib; HTTP status codes
  mapped back onto the serve exception taxonomy so the router's
  retry/shed/breaker logic is transport-agnostic).

- :class:`ServeFleet` — loads the newest verifiable snapshot ONCE
  (``lineage.latest_verifiable`` + the resharding ``load_for_mesh``
  loader, exactly the single-engine path), builds N warmed replicas,
  starts the router, and runs the hot-swap watcher: a poll of
  ``lineage.head_fingerprint`` (a ~1 KB manifest read, no checkpoint
  bytes) detects a new publish; the full sha-verified lineage walk then
  loads it, ``swap_warm`` AOT-compiles every bucket on background
  engines (the ``warm()`` trace-count bound still asserted — a swap
  must not smuggle unbounded compiles into serving), and
  ``swap_commit`` rotates each replica to the new pair.  A torn or
  unverifiable publish is SKIPPED with a named ``swap_skipped`` event
  in the swap history (the lineage walk falls back to the snapshot
  already serving, which is never "newer") — serving is never degraded
  by a bad publish.

Each replica carries its own engine (own compiled functions, own
replicated param copy): replicas fail, swap, and serve independently,
which is the point of a fleet.  One :class:`~ddp_tpu.obs.registry.
MetricsRegistry` is shared fleet-wide — the router's counters plus each
replica's engine/batcher series under a ``replica`` label, with
fleet-rollup gauges (``ddp_fleet_healthy_replicas``,
``ddp_fleet_swap_commits_total``) — so one ``/metrics`` scrape reads
the whole fleet.  On one shared host this costs N param
copies — the price of blast-radius isolation, recorded honestly in
BENCH_r09 rather than hidden behind shared state.
"""
from __future__ import annotations

import functools
import json
import socket
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import List, Optional

import numpy as np

from ..obs.registry import MetricsRegistry
from ..obs.tracer import get_tracer
from .batcher import Draining, DynamicBatcher, QueueFull
from .engine import RequestTooLarge, ServeEngine
from .router import ReplicaCrashed, Router


def _log(msg: str) -> None:
    print(msg, file=sys.stderr)
    sys.stderr.flush()


class LocalReplica:
    """One in-process (engine, batcher) pair implementing the router's
    replica protocol; :meth:`swap` is the zero-downtime rotation point.

    ``crashed`` is a fault-injection latch (resilience/faults.py): once
    set, submits and health probes fail like a dead process would, and
    the router's prober ejects this replica.
    """

    def __init__(self, replica_id: str, engine: ServeEngine,
                 batcher: DynamicBatcher):
        self.replica_id = replica_id
        self._t0 = time.monotonic()
        # analysis: unlocked-ok(bool latch; set once by fault injection)
        self.crashed = False
        self._pair_lock = threading.Lock()
        self.engine = engine        # analysis: shared-under(_pair_lock)
        self.batcher = batcher      # analysis: shared-under(_pair_lock)
        self.swaps = 0              # analysis: shared-under(_pair_lock)

    def _pair(self):
        with self._pair_lock:
            return self.engine, self.batcher

    def submit(self, images, timeout: Optional[float] = None,
               req: Optional[str] = None):
        if self.crashed:
            raise ReplicaCrashed(
                f"replica {self.replica_id} is down (crash fault latched)")
        _, batcher = self._pair()
        # The batcher reference is pinned BEFORE submit: a swap landing
        # mid-call drains this (old) batcher, which still serves every
        # request it accepted — the consistent-snapshot guarantee.
        return batcher.submit(images, timeout=timeout, req_id=req)

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = None,
                 req: Optional[str] = None,
                 session: Optional[str] = None):
        """The generative leg of the replica protocol: one blocking
        stream on this replica's token batcher.  Same pin-before-call
        swap guarantee as :meth:`submit` — a mid-stream hot-swap lets
        the retiring batcher decode its accepted streams to completion
        on the engine (and KV cache) that prefilled them."""
        if self.crashed:
            raise ReplicaCrashed(
                f"replica {self.replica_id} is down (crash fault latched)")
        _, batcher = self._pair()
        if not hasattr(batcher, "generate"):
            # A classifier replica: the CLIENT asked the wrong fleet —
            # TypeError rides the router's no-retry ladder.
            raise TypeError(
                f"replica {self.replica_id} serves a classifier "
                "(DynamicBatcher); start the fleet with generate=True "
                "for token streams")
        return batcher.generate(prompt, max_new_tokens=max_new_tokens,
                                timeout=timeout, req_id=req,
                                session=session)

    def queue_depth(self) -> int:
        _, batcher = self._pair()
        return batcher.queue_depth()

    def health(self) -> dict:
        """The single-replica /healthz body; RAISES when the replica is
        dead (the router's probe treats any exception as a failed
        probe — like a refused TCP connect to a remote replica)."""
        if self.crashed:
            raise ReplicaCrashed(
                f"replica {self.replica_id} is down (crash fault latched)")
        engine, batcher = self._pair()
        draining = batcher.draining
        return {
            "status": "draining" if draining else "ok",
            "replica_id": self.replica_id,
            "checkpoint_step": engine.checkpoint_step,
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "queue_depth": batcher.queue_depth(),
        }

    def stats(self) -> dict:
        engine, batcher = self._pair()
        with self._pair_lock:
            swaps = self.swaps
        return {"replica_id": self.replica_id, "swaps": swaps,
                "engine": engine.stats(), "batcher": batcher.stats()}

    def swap(self, new_engine: ServeEngine, new_batcher: DynamicBatcher,
             drain_timeout: float = 30.0) -> bool:
        """Atomically rotate to the new (warmed) pair, then drain the
        retired batcher so its accepted requests finish on the engine
        that accepted them.  New requests are admitted by the new pair
        from the instant the lock releases — admission never pauses."""
        with self._pair_lock:
            old_batcher = self.batcher
            self.engine = new_engine
            self.batcher = new_batcher
            self.swaps += 1
        return old_batcher.drain(timeout=drain_timeout)

    def close(self, timeout: float = 30.0) -> bool:
        _, batcher = self._pair()
        return batcher.drain(timeout=timeout)


class HTTPReplica:
    """The replica protocol over a remote serve process (stdlib urllib).

    Status codes map back onto the serve exception taxonomy so the
    router treats remote and in-process replicas identically: 503 ->
    :class:`Draining`/:class:`QueueFull` (re-route, no breaker hit),
    400/413 -> the client's own error (no retry), transport failures
    (refused/reset/DNS) -> :class:`ReplicaCrashed` (retry elsewhere,
    breaker-counted), and timeouts — transport or replica-side 504 —
    -> :class:`TimeoutError`, the same no-retry deadline path a
    :class:`LocalReplica` batcher timeout takes.
    """

    def __init__(self, replica_id: str, base_url: str, *,
                 probe_timeout_s: float = 5.0):
        self.replica_id = replica_id
        self.base_url = base_url.rstrip("/")
        self.probe_timeout_s = float(probe_timeout_s)
        self._lock = threading.Lock()
        # Last queue depth seen on a health probe — queue_depth() must
        # not cost an HTTP round trip per routing decision.
        self._last_depth = 0    # analysis: shared-under(_lock)

    def submit(self, images, timeout: Optional[float] = None,
               req: Optional[str] = None):
        body = json.dumps(
            {"instances": np.asarray(images).tolist()}).encode()
        headers = {"Content-Type": "application/json"}
        if req is not None:
            headers["X-Request-Id"] = req
        http_req = urllib.request.Request(
            self.base_url + "/predict", data=body, headers=headers)
        try:
            with urllib.request.urlopen(
                    http_req, timeout=timeout if timeout is not None
                    else 30.0) as r:
                out = json.load(r)
        except urllib.error.HTTPError as e:
            raise self._map_http_error(e) from None
        except urllib.error.URLError as e:
            # A connect timeout arrives wrapped as the URLError reason;
            # the deadline budget died, so take the router's no-retry
            # TimeoutError path exactly like a LocalReplica would.
            if isinstance(e.reason, (socket.timeout, TimeoutError)):
                raise TimeoutError(
                    f"replica {self.replica_id} transport timeout: "
                    f"{e.reason}") from None
            raise ReplicaCrashed(
                f"replica {self.replica_id} transport failure: "
                f"{type(e).__name__}: {e}") from None
        except (socket.timeout, TimeoutError) as e:
            raise TimeoutError(
                f"replica {self.replica_id} transport timeout: "
                f"{e}") from None
        except (OSError, ConnectionError) as e:
            raise ReplicaCrashed(
                f"replica {self.replica_id} transport failure: "
                f"{type(e).__name__}: {e}") from None
        return np.asarray(out["logits"], np.float32)

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = None,
                 req: Optional[str] = None,
                 session: Optional[str] = None):
        """POST /generate on the remote replica; identical error
        taxonomy mapping to :meth:`submit`."""
        payload = {"prompt": np.asarray(prompt).tolist()}
        if max_new_tokens is not None:
            payload["max_new_tokens"] = int(max_new_tokens)
        if session is not None:
            payload["session"] = session
        headers = {"Content-Type": "application/json"}
        if req is not None:
            headers["X-Request-Id"] = req
        http_req = urllib.request.Request(
            self.base_url + "/generate", data=json.dumps(payload).encode(),
            headers=headers)
        try:
            with urllib.request.urlopen(
                    http_req, timeout=timeout if timeout is not None
                    else 30.0) as r:
                return json.load(r)
        except urllib.error.HTTPError as e:
            raise self._map_http_error(e) from None
        except urllib.error.URLError as e:
            if isinstance(e.reason, (socket.timeout, TimeoutError)):
                raise TimeoutError(
                    f"replica {self.replica_id} transport timeout: "
                    f"{e.reason}") from None
            raise ReplicaCrashed(
                f"replica {self.replica_id} transport failure: "
                f"{type(e).__name__}: {e}") from None
        except (socket.timeout, TimeoutError) as e:
            raise TimeoutError(
                f"replica {self.replica_id} transport timeout: "
                f"{e}") from None
        except (OSError, ConnectionError) as e:
            raise ReplicaCrashed(
                f"replica {self.replica_id} transport failure: "
                f"{type(e).__name__}: {e}") from None

    def _map_http_error(self, e: "urllib.error.HTTPError"):
        try:
            msg = json.load(e).get("error", "")
        except Exception:
            msg = ""
        msg = msg or f"HTTP {e.code} from {self.base_url}"
        if e.code == 413:
            return RequestTooLarge(msg)
        if e.code == 400:
            return ValueError(msg)
        if e.code == 503:
            return (Draining(msg) if "drain" in msg.lower()
                    else QueueFull(msg))
        if e.code == 504:
            # The remote batcher timed out THIS request's budget — the
            # LocalReplica equivalent raises TimeoutError (no retry).
            return TimeoutError(f"replica-side timeout: {msg}")
        return ReplicaCrashed(msg)

    def health(self) -> dict:
        try:
            with urllib.request.urlopen(self.base_url + "/healthz",
                                        timeout=self.probe_timeout_s) as r:
                h = json.load(r)
        except urllib.error.HTTPError as e:
            # 503-draining still carries a JSON body worth returning —
            # the router reads status != "ok" as unhealthy either way.
            try:
                h = json.load(e)
            except Exception:
                raise ReplicaCrashed(
                    f"health probe HTTP {e.code}") from None
        if isinstance(h, dict):
            with self._lock:
                self._last_depth = int(h.get("queue_depth", 0) or 0)
            h.setdefault("replica_id", self.replica_id)
        return h

    def queue_depth(self) -> int:
        with self._lock:
            return self._last_depth

    def stats(self) -> dict:
        try:
            with urllib.request.urlopen(self.base_url + "/stats",
                                        timeout=self.probe_timeout_s) as r:
                return json.load(r)
        except Exception as e:
            return {"replica_id": self.replica_id,
                    "error": f"{type(e).__name__}: {e}"}


class ServeFleet:
    """N warmed replicas + router + checkpoint hot-swap watcher."""

    def __init__(self, snapshot_path: str, model_name: str, *, mesh,
                 n_replicas: int = 2, buckets=(1, 8, 32, 128),
                 compute_dtype=None, max_batch: Optional[int] = None,
                 max_wait_ms: float = 5.0, queue_depth: int = 256,
                 drain_timeout_s: float = 30.0, tracer=None,
                 router_kwargs: Optional[dict] = None, registry=None,
                 generate: bool = False, slots: int = 8,
                 prompt_buckets=(16, 64), max_new_tokens: int = 32):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.generative = bool(generate)
        if self.generative:
            from ..models import transformer as tfm
            if model_name != tfm.LM_NAME:
                raise ValueError(
                    f"generative fleets serve the {tfm.LM_NAME!r} decoder "
                    f"(models/transformer.py), got {model_name!r}")
        self.snapshot_path = snapshot_path
        self.model_name = model_name
        self.mesh = mesh
        self.buckets = buckets
        self.slots = slots
        self.prompt_buckets = prompt_buckets
        self.max_new_tokens = max_new_tokens
        self.compute_dtype = compute_dtype
        self.max_batch = max_batch
        self.max_wait_ms = max_wait_ms
        self.queue_depth = queue_depth
        self.drain_timeout_s = float(drain_timeout_s)
        self.tracer = tracer if tracer is not None else get_tracer()
        # One registry fleet-wide: router counters + replica-labelled
        # engine/batcher series + the rollup gauges below, all behind
        # one /metrics scrape.
        self.registry = (registry if registry is not None
                         else MetricsRegistry())
        self._t0 = time.monotonic()
        self._draining = threading.Event()
        self._stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None
        self._swap_lock = threading.Lock()
        # analysis: shared-under(_swap_lock)
        self.swap_history: List[dict] = []
        self._current_file = None   # analysis: shared-under(_swap_lock)
        self._current_epoch = None  # analysis: shared-under(_swap_lock)
        self._current_step = None   # analysis: shared-under(_swap_lock)

        from ..resilience.lineage import head_fingerprint
        ckpt, used = self._load_snapshot()
        # analysis: unlocked-ok(watcher-thread only after init; tests
        # drive poll_once single-threaded instead of starting the watcher)
        self._last_fp = head_fingerprint(self.snapshot_path)
        engines = [self._make_engine(ckpt, used, f"r{i}")
                   for i in range(n_replicas)]
        self._warm_all(engines)
        self.replicas = [
            LocalReplica(f"r{i}", eng,
                         self._make_batcher(eng, f"r{i}").start())
            for i, eng in enumerate(engines)]
        self._current_file = used
        self._current_epoch = int(ckpt.epoch)
        self._current_step = int(ckpt.step)
        self.router = Router(self.replicas, tracer=self.tracer,
                             registry=self.registry,
                             **(router_kwargs or {}))
        self.registry.gauge(
            "ddp_fleet_healthy_replicas",
            "Replicas currently routable (not ejected, breaker not open)"
        ).set_function(lambda: float(self.router.healthy_count()))
        self.registry.counter(
            "ddp_fleet_swap_commits_total",
            "Checkpoint hot-swaps committed fleet-wide"
        ).set_function(self._swap_commit_count)

    # -- construction helpers ---------------------------------------------

    def _load_snapshot(self):
        """The full sha-verified lineage walk onto the serving mesh —
        the single choke point the ``torn_publish`` fault wraps."""
        from ..resilience.lineage import latest_verifiable
        from ..train.checkpoint import CheckpointError
        from ..train.ckpt_shard import load_for_mesh
        loaded = latest_verifiable(
            self.snapshot_path,
            loader=functools.partial(load_for_mesh, mesh=self.mesh))
        if loaded is None:
            raise CheckpointError(
                f"no checkpoint found under {self.snapshot_path!r}; the "
                "fleet needs a trained snapshot (run training with "
                "--snapshot_path first)")
        return loaded

    def _make_engine(self, ckpt, used: str, replica_id: str):
        if self.generative:
            from ..models import transformer as tfm
            from .kvcache import KVCacheEngine
            eng = KVCacheEngine(tfm, ckpt.params, self.mesh,
                                slots=self.slots,
                                prompt_buckets=self.prompt_buckets,
                                compute_dtype=self.compute_dtype,
                                plan=self._serving_plan(ckpt),
                                tracer=self.tracer,
                                registry=self.registry,
                                metric_labels={"replica": replica_id})
        else:
            from ..models import get_model
            eng = ServeEngine(get_model(self.model_name), ckpt.params,
                              ckpt.batch_stats, self.mesh,
                              buckets=self.buckets,
                              compute_dtype=self.compute_dtype,
                              tracer=self.tracer, registry=self.registry,
                              metric_labels={"replica": replica_id})
        eng.checkpoint_file = used
        eng.checkpoint_epoch = int(ckpt.epoch)
        eng.checkpoint_step = int(ckpt.step)
        return eng

    def _serving_plan(self, ckpt):
        """A TP layout plan when the SERVING mesh has a model axis; None
        on the common 1-D data mesh (a TP-trained checkpoint reshards
        onto it via ``load_for_mesh`` and serves replicated)."""
        from ..parallel.mesh import MODEL_AXIS
        if MODEL_AXIS not in self.mesh.axis_names:
            return None
        m = int(self.mesh.shape[MODEL_AXIS])
        if m <= 1:
            return None
        from ..parallel.tp.plan import plan_for_model
        return plan_for_model(self.model_name, ckpt.params, model_size=m)

    def _make_batcher(self, engine, replica_id: str):
        if self.generative:
            from .token_batcher import TokenBatcher
            return TokenBatcher(engine,
                                max_new_tokens=self.max_new_tokens,
                                queue_depth=self.queue_depth,
                                tracer=self.tracer,
                                registry=self.registry,
                                metric_labels={"replica": replica_id})
        return DynamicBatcher(engine, max_batch=self.max_batch,
                              max_wait_ms=self.max_wait_ms,
                              queue_depth=self.queue_depth,
                              tracer=self.tracer, registry=self.registry,
                              metric_labels={"replica": replica_id})

    def _swap_commit_count(self) -> float:
        with self._swap_lock:
            return float(sum(1 for e in self.swap_history
                             if e["event"] == "swap_commit"))

    def _warm_all(self, engines: List[ServeEngine]) -> int:
        """AOT-compile every bucket on every engine; the single-engine
        compile-bound contract holds per engine or the fleet refuses to
        (hot-)start — a swap must never smuggle unbounded compiles."""
        total = 0
        for eng in engines:
            compiled = eng.warm()
            # Classifier engines bound compiles at one-per-bucket; the
            # KV-cache engine publishes its own bound (prefill + cache
            # write per prompt bucket + one decode).
            bound = getattr(eng, "compile_bound", None)
            if bound is None:
                bound = len(eng.buckets)
            if compiled > bound:
                raise RuntimeError(
                    f"compile bound violated: {compiled} executables, "
                    f"bound {bound}")
            total += compiled
        return total

    # -- hot-swap watcher --------------------------------------------------

    def start(self, poll_s: float = 2.0) -> "ServeFleet":
        """Start the router's health prober and the checkpoint watcher
        (``poll_s <= 0`` starts the prober only; idempotent)."""
        self.router.start()
        if poll_s > 0 and self._watch_thread is None:
            self._watch_thread = threading.Thread(
                target=self._watch_loop, args=(float(poll_s),),
                daemon=True, name="fleet-ckpt-watch")
            self._watch_thread.start()
        return self

    def _watch_loop(self, poll_s: float) -> None:
        while not self._stop.wait(poll_s):
            try:
                self.poll_once()
            except Exception as e:  # the watcher must never die silently
                _log(f"WARNING: checkpoint watcher poll failed "
                     f"({type(e).__name__}: {e}); serving is unaffected, "
                     "next poll continues")

    def poll_once(self) -> Optional[str]:
        """One watcher iteration; returns the swap-history event name it
        recorded (``"swap_commit"`` / ``"swap_skipped"``) or None when
        nothing new was published.  Callable directly for deterministic
        tests and single-threaded embedders."""
        from ..resilience.lineage import head_fingerprint
        from ..train.checkpoint import CheckpointError
        fp = head_fingerprint(self.snapshot_path)
        if fp is None or fp == self._last_fp:
            return None
        # Consume the fingerprint BEFORE attempting the load: a bad
        # publish must not be re-tried every poll (the next PUBLISH
        # changes the fingerprint again and re-arms the watcher).
        self._last_fp = fp
        try:
            ckpt, used = self._load_snapshot()
        except CheckpointError as e:
            return self._record("swap_skipped",
                                reason=f"no verifiable snapshot: {e}")
        with self._swap_lock:
            cur_step = self._current_step
        if cur_step is not None and int(ckpt.step) <= cur_step:
            # The lineage walk fell back past a torn/unverifiable head
            # to a snapshot no newer than the one already serving.
            return self._record(
                "swap_skipped", file=used, step=int(ckpt.step),
                reason=f"head torn or stale: newest verifiable snapshot "
                       f"{used!r} (step {int(ckpt.step)}) is not newer "
                       f"than serving step {cur_step}")
        self._swap_to(ckpt, used)
        return "swap_commit"

    def _swap_to(self, ckpt, used: str) -> None:
        t0 = time.monotonic()
        with self.tracer.span("swap_warm"):
            engines = [self._make_engine(ckpt, used, r.replica_id)
                       for r in self.replicas]
            compiled = self._warm_all(engines)
        warm_s = time.monotonic() - t0
        with self.tracer.span("swap_commit"):
            clean = True
            for replica, eng in zip(self.replicas, engines):
                clean &= replica.swap(
                    eng,
                    self._make_batcher(eng, replica.replica_id).start(),
                    drain_timeout=self.drain_timeout_s)
            with self._swap_lock:
                from_step = self._current_step
                self._current_file = used
                self._current_epoch = int(ckpt.epoch)
                self._current_step = int(ckpt.step)
        self._record("swap_commit", file=used, epoch=int(ckpt.epoch),
                     step=int(ckpt.step), from_step=from_step,
                     warm_s=round(warm_s, 3), compiled_executables=compiled,
                     old_drained_clean=clean)

    def _record(self, event: str, **fields) -> str:
        entry = {"event": event, "t": round(time.time(), 3), **fields}
        with self._swap_lock:
            self.swap_history.append(entry)
        _log(f"fleet: {event} " + " ".join(
            f"{k}={v}" for k, v in fields.items()))
        return event

    # -- front-door API ----------------------------------------------------

    def submit(self, images, timeout: Optional[float] = None):
        return self.router.submit(images, timeout=timeout)

    def generate(self, prompt, max_new_tokens: Optional[int] = None,
                 timeout: Optional[float] = None,
                 session: Optional[str] = None):
        """Fleet front door for one generative stream; sticky-routed by
        ``session`` (see :meth:`Router.generate`)."""
        return self.router.generate(prompt,
                                    max_new_tokens=max_new_tokens,
                                    timeout=timeout, session=session)

    def health(self) -> dict:
        """The fleet /healthz body: ok while ANY replica can take
        traffic; per-replica detail for humans and probes."""
        reps = self.router.replica_health()
        healthy = sum(1 for r in reps
                      if r.get("status") == "ok" and not r.get("ejected")
                      and r.get("breaker") != "open")
        draining = self._draining.is_set()
        with self._swap_lock:
            ck = {"file": self._current_file, "epoch": self._current_epoch,
                  "step": self._current_step}
        return {
            "status": ("draining" if draining
                       else "ok" if healthy else "unavailable"),
            "replicas": reps,
            "healthy_replicas": healthy,
            "checkpoint": ck,
            "checkpoint_step": ck["step"],
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "queue_depth": sum(int(r.get("queue_depth", 0) or 0)
                               for r in reps),
        }

    def stats(self) -> dict:
        with self._swap_lock:
            swaps = list(self.swap_history)
        return {"router": self.router.stats(),
                "replicas": [r.stats() for r in self.replicas],
                "swaps": swaps}

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def close(self, timeout: float = 30.0) -> bool:
        """Stop watcher + prober, drain every replica.  Idempotent."""
        self._draining.set()
        self._stop.set()
        t = self._watch_thread
        if t is not None:
            t.join(timeout=10.0)
            self._watch_thread = None
        self.router.close()
        ok = True
        for replica in self.replicas:
            ok &= replica.close(timeout=timeout)
        return ok
