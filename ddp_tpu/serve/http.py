"""Stdlib-only threaded HTTP front end for the serving stack.

One ``ThreadingHTTPServer`` (a thread per connection — the blocking
``submit()`` call parks the handler thread while the engine thread does
the work, which is exactly the dynamic batcher's concurrency model):

- ``POST /predict``  body ``{"instances": [[...32x32x3 uint8...], ...]}``
  (one image's nested list is accepted bare) -> ``{"predictions": [...],
  "logits": [[...]]}``.  Admission failures map to transport-visible
  status codes: 400 malformed, 413 oversized (larger than the biggest
  bucket), 503 shed/draining with ``Retry-After`` — backpressure the
  client can act on, never an unbounded queue.
- ``POST /generate`` body ``{"prompt": [token ids], "max_new_tokens":
  N?, "session": "id"?}`` -> ``{"tokens": [generated ids],
  "prompt_len": n, "ttft_ms": float}`` — the generative front door
  (models/transformer.py decoder behind a KV-cache engine + token
  batcher).  Same status-code taxonomy as ``/predict``; ``session``
  is the fleet router's sticky-routing key.
- ``GET /healthz``   liveness + which checkpoint is live, plus the
  identity fields a fleet router keys on: ``replica_id``,
  ``checkpoint_step``, ``uptime_s``, ``queue_depth``; flips to
  ``"draining"`` (503) during graceful shutdown so load balancers stop
  routing before the listener closes.
- ``GET /stats``     engine + batcher counters (bucket usage, latency
  percentiles, shed counts, compiled-executable count) and the swap
  history (``swaps`` — every hot-swap commit/skip; empty list on a
  single-engine server, which has no swap machinery).
- ``GET /metrics``   the same counters in Prometheus text exposition
  (obs/registry.py) — per-replica labelled series on a fleet (the
  fleet's shared registry), the pair's private registry otherwise.

``POST /predict`` honors an ``X-Request-Id`` header on a single-pair
server (it rides into the request's spans); a fleet ignores it — the
router mints its own id at admission, the one the flow events use.

The same listener fronts either backend: a single (engine, batcher)
pair, or a :class:`~ddp_tpu.serve.fleet.ServeFleet` (pass ``fleet=``) —
the handler calls the server's ``submit``/``healthz_payload``/
``stats_payload`` indirection, so the router's shed errors (which carry
a derived ``retry_after_s``) map onto 503 + ``Retry-After`` exactly
like the batcher's.
"""
from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

import numpy as np

from ..obs.registry import CONTENT_TYPE as METRICS_CONTENT_TYPE
from .batcher import Draining, DynamicBatcher, QueueFull
from .engine import RequestTooLarge, ServeEngine

# Practical request-body bound: the largest sane request is
# max_rows * 32*32*3 bytes of pixels, JSON-inflated ~4x; 64 MiB covers a
# 1024-row bucket with headroom while refusing a memory-bomb POST early.
MAX_BODY_BYTES = 64 << 20

# Per-request completion bound: submit() must NOT wait forever (a lost
# completion would park the handler thread and the client indefinitely —
# the exact unbounded latency the 503/504 design exists to prevent).
# Generous: covers a full queue of max-bucket forwards on a slow box.
REQUEST_TIMEOUT_S = 60.0


class ServeHTTPServer(ThreadingHTTPServer):
    """The listener; carries the serving stack for handler access."""

    daemon_threads = True

    def __init__(self, addr, engine: Optional[ServeEngine] = None,
                 batcher: Optional[DynamicBatcher] = None,
                 quiet: bool = True, *, fleet=None,
                 replica_id: str = "r0"):
        if fleet is None and (engine is None or batcher is None):
            raise ValueError(
                "ServeHTTPServer needs either (engine, batcher) or fleet=")
        self.engine = engine
        self.batcher = batcher
        self.fleet = fleet
        self.replica_id = replica_id
        self.quiet = quiet
        self._t0 = time.monotonic()
        # close() latch: signal handlers and drain paths both call it;
        # the second (and every later) call must be a no-op, and calling
        # shutdown() on a listener whose serve_forever never ran would
        # block forever (stdlib wait-for-event), hence _started.
        self._closed = threading.Event()
        self._started = threading.Event()
        super().__init__(addr, _Handler)

    def serve_forever(self, poll_interval: float = 0.5) -> None:
        self._started.set()
        super().serve_forever(poll_interval)

    def close(self) -> None:
        """Idempotent listener teardown, safe to call twice and from a
        signal handler: first call stops ``serve_forever`` (if it ever
        ran) and closes the socket; every later call returns at once.
        Draining the batcher/fleet stays the caller's step — close()
        only guarantees the LISTENER can always be torn down exactly
        once, whatever order signals arrive in."""
        if self._closed.is_set():
            return
        self._closed.set()
        if self._started.is_set():
            try:
                self.shutdown()
            except Exception:
                pass  # already stopping; teardown must not raise
        try:
            self.server_close()
        except OSError:
            pass  # socket already closed

    # -- backend indirection (single pair vs fleet) ------------------------

    def submit(self, images: np.ndarray, timeout: float,
               req_id: Optional[str] = None) -> np.ndarray:
        if self.fleet is not None:
            # The router mints the canonical request id at admission.
            return self.fleet.submit(images, timeout=timeout)
        return self.batcher.submit(images, timeout=timeout, req_id=req_id)

    def generate(self, prompt, max_new_tokens: Optional[int],
                 timeout: float, session: Optional[str] = None,
                 req_id: Optional[str] = None) -> dict:
        if self.fleet is not None:
            return self.fleet.generate(prompt,
                                       max_new_tokens=max_new_tokens,
                                       timeout=timeout, session=session)
        if not hasattr(self.batcher, "generate"):
            raise TypeError(
                "this server fronts a classifier; start it with "
                "--generate (models/transformer.py decoder) for "
                "/generate")
        return self.batcher.generate(prompt,
                                     max_new_tokens=max_new_tokens,
                                     timeout=timeout, req_id=req_id,
                                     session=session)

    def metrics_exposition(self) -> Optional[str]:
        """Prometheus text for ``/metrics``: the fleet's shared registry
        when fronting a fleet, else the pair's; None when neither backend
        carries one (a hand-rolled stub in tests)."""
        backend = self.fleet if self.fleet is not None else self.batcher
        reg = getattr(backend, "registry", None)
        return reg.exposition() if reg is not None else None

    def healthz_payload(self) -> Tuple[int, dict]:
        if self.fleet is not None:
            h = self.fleet.health()
            return (200 if h["status"] == "ok" else 503), h
        draining = self.batcher.draining
        return 503 if draining else 200, {
            "status": "draining" if draining else "ok",
            "replica_id": self.replica_id,
            "checkpoint_step": getattr(self.engine, "checkpoint_step", None),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "queue_depth": self.batcher.queue_depth(),
            "buckets": list(self.engine.buckets),
            "compiled_executables": self.engine.trace_count,
            "checkpoint": self.engine.stats()["checkpoint"],
        }

    def stats_payload(self) -> dict:
        if self.fleet is not None:
            return self.fleet.stats()
        return {"engine": self.engine.stats(),
                "batcher": self.batcher.stats(),
                "swaps": []}


class _Handler(BaseHTTPRequestHandler):
    server: ServeHTTPServer

    # Socket timeout: a client that sends headers and then stalls the
    # body (slowloris) must not park a handler thread forever in
    # rfile.read() — the stdlib handler catches the resulting timeout
    # and closes the connection, reclaiming the thread.
    timeout = 60

    # -- plumbing ----------------------------------------------------------

    def log_message(self, fmt, *args):  # noqa: D102 — stdlib signature
        if not self.server.quiet:
            super().log_message(fmt, *args)

    def _reply(self, status: int, payload: dict,
               retry_after: Optional[int] = None) -> None:
        body = json.dumps(payload).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            self.send_header("Retry-After", str(retry_after))
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass  # client gave up; its latency bound, its call

    # -- GET ---------------------------------------------------------------

    def do_GET(self) -> None:  # noqa: N802 — stdlib naming
        if self.path == "/healthz":
            status, payload = self.server.healthz_payload()
            self._reply(status, payload)
        elif self.path == "/stats":
            self._reply(200, self.server.stats_payload())
        elif self.path == "/metrics":
            text = self.server.metrics_exposition()
            if text is None:
                self._reply(404, {"error": "no metrics registry on this "
                                           "server's backend"})
                return
            body = text.encode("utf-8")
            self.send_response(200)
            self.send_header("Content-Type", METRICS_CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass  # scraper gave up
        else:
            self._reply(404, {"error": f"no route {self.path!r}; try "
                                       "/predict, /generate, /healthz, "
                                       "/stats, /metrics"})

    # -- POST /predict, /generate ------------------------------------------

    def do_POST(self) -> None:  # noqa: N802 — stdlib naming
        if self.path not in ("/predict", "/generate"):
            self._reply(404, {"error": f"no route {self.path!r}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if length <= 0 or length > MAX_BODY_BYTES:
            self._reply(400, {"error": f"Content-Length must be in "
                                       f"(0, {MAX_BODY_BYTES}]"})
            return
        try:
            payload = json.loads(self.rfile.read(length))
        except (json.JSONDecodeError, UnicodeDecodeError) as e:
            self._reply(400, {"error": f"body is not valid JSON: {e}"})
            return
        try:
            if self.path == "/generate":
                out = self._run_generate(payload)
            else:
                out = self._run_predict(payload)
        except RequestTooLarge as e:
            self._reply(413, {"error": str(e)})
            return
        except (QueueFull, Draining) as e:
            # Router sheds carry a retry_after_s derived from live queue
            # depth / re-admission ETA; plain batcher backpressure keeps
            # the fixed 1 s hint.
            self._reply(503, {"error": str(e)},
                        retry_after=max(
                            1, round(getattr(e, "retry_after_s", 1))))
            return
        except (ValueError, TypeError) as e:
            self._reply(400, {"error": str(e)})
            return
        except TimeoutError as e:
            self._reply(504, {"error": str(e)})
            return
        except Exception as e:
            # An engine/runtime failure (XLA error mid-forward) reaches
            # every co-batched caller via req.error — answer it as a
            # 5xx the client can log and retry on, never a reset socket.
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
            return
        self._reply(200, out)

    def _run_predict(self, payload) -> dict:
        instances = (payload.get("instances")
                     if isinstance(payload, dict) else payload)
        images = np.asarray(instances)
        if images.ndim == 3:  # one bare image
            images = images[None]
        if not np.issubdtype(images.dtype, np.integer) or \
                images.min() < 0 or images.max() > 255:
            raise ValueError(
                "pixel values must be integers in [0, 255] (uint8 — "
                "the training loaders' wire format)")
        images = images.astype(np.uint8)
        logits = self.server.submit(
            images, timeout=REQUEST_TIMEOUT_S,
            req_id=self.headers.get("X-Request-Id") or None)
        return {
            "predictions": np.argmax(logits, axis=-1).astype(int).tolist(),
            "logits": [[float(v) for v in row] for row in logits],
        }

    def _run_generate(self, payload) -> dict:
        """Body ``{"prompt": [ids], "max_new_tokens": N?, "session":
        id?}`` -> ``{"tokens": [...], "prompt_len": n, "ttft_ms":
        float}``.  The session key is the ROUTER's sticky-routing
        handle; single-pair servers accept and record it unused."""
        if not isinstance(payload, dict) or "prompt" not in payload:
            raise ValueError(
                'body must be {"prompt": [token ids], "max_new_tokens"?, '
                '"session"?}')
        max_new = payload.get("max_new_tokens")
        if max_new is not None:
            max_new = int(max_new)
        session = payload.get("session")
        if session is not None and not isinstance(session, str):
            raise ValueError("session must be a string id")
        return self.server.generate(
            payload["prompt"], max_new, timeout=REQUEST_TIMEOUT_S,
            session=session,
            req_id=self.headers.get("X-Request-Id") or None)
