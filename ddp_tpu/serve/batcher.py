"""Dynamic micro-batching with SLO-aware admission control.

Request-facing half of the serving stack: callers block in
:meth:`DynamicBatcher.submit` while one engine thread forms batches and
runs the compiled forwards — the veScale-style split (PAPERS.md, arxiv
2509.07003) of eager host logic around one compiled SPMD program.

Batching policy (the classic dynamic-batcher contract):

- requests enqueue into a BOUNDED queue; a full queue sheds the request
  immediately with :class:`QueueFull` (an explicit backpressure error the
  HTTP layer maps to 503 + Retry-After) instead of letting latency grow
  without bound — admission control IS the SLO mechanism;
- the engine thread forms a batch when either ``max_batch`` rows are
  waiting or ``max_wait_ms`` has passed since the OLDEST queued request
  — whichever comes first, so a lone request never waits longer than the
  wait budget and a busy queue never waits at all;
- a request that does not fit the batch being formed is held over intact
  (requests are never split: one request = one contiguous row block of
  one forward batch);
- oversized requests (> the engine's largest bucket) are rejected at
  admission with :class:`RequestTooLarge`;
- :meth:`drain` stops admission (:class:`Draining` to new callers),
  serves everything already accepted, then stops the engine thread —
  the graceful-shutdown half of the SIGTERM story
  (``python -m ddp_tpu.serve`` wires it to the resilience preemption
  guard).

Telemetry: each request's ``queue_wait`` (enqueue -> batch formation) is
recorded as an ``overlap=True`` span (it runs concurrently with the
engine thread's serial pad/h2d/forward/d2h pipeline), and each formed
batch records a ``batch_form`` span keyed by the same batch sequence
number the engine's spans use — the batcher CLAIMS that number
(``engine.claim_batch_seq``, process-unique across replicas and
hot-swaps) at formation and passes it to ``forward(seq=...)``, and each
``queue_wait`` span carries the request's router-minted ``req`` id, so
the offline tooling joins request -> batch -> engine stages
unambiguously.  Counters live in the shared metrics registry
(``ddp_batcher_*``; legacy ``stats()`` names are read-only views), plus
one ``ddp_batcher_request_latency_ms`` histogram of served-request
end-to-end latency.
"""
from __future__ import annotations

import collections
import queue
import statistics
import threading
import time
from typing import List, Optional

import numpy as np

from ..obs.registry import MetricsRegistry
from ..obs.tracer import get_tracer
from .engine import RequestTooLarge, ServeError, claim_batch_seq


class QueueFull(ServeError):
    """Admission queue at capacity — shed NOW (explicit backpressure)
    rather than queue into unbounded latency."""


class Draining(ServeError):
    """The server is shutting down: in-flight work completes, new work
    must go elsewhere."""


class _Request:
    __slots__ = ("images", "n", "t_submit", "event", "logits", "error",
                 "abandoned", "req_id")

    def __init__(self, images: np.ndarray,
                 req_id: Optional[str] = None):
        self.images = images
        self.req_id = req_id  # router-minted request id (span flow key)
        self.n = images.shape[0]
        self.t_submit = time.monotonic()
        self.event = threading.Event()
        self.logits: Optional[np.ndarray] = None
        self.error: Optional[BaseException] = None
        # Caller gave up (submit timeout): batch formation skips it so
        # the engine never burns a forward on logits nobody will read —
        # at overload that wasted capacity would deepen the very
        # saturation that caused the timeout.
        self.abandoned = False


def percentiles(values: List[float], points=(50, 90, 99)) -> dict:
    """Nearest-rank percentiles of ``values`` (ms in, ms out) — shared by
    the batcher's stats and bench.py's ``--serve`` load records."""
    if not values:
        return {f"p{p}": None for p in points}
    ordered = sorted(values)
    return {f"p{p}": ordered[min(len(ordered) - 1,
                                 max(0, -(-len(ordered) * p // 100) - 1))]
            for p in points}


class DynamicBatcher:
    def __init__(self, engine, *, max_batch: Optional[int] = None,
                 max_wait_ms: float = 5.0, queue_depth: int = 256,
                 tracer=None, registry=None, metric_labels=None):
        self.engine = engine
        self.max_batch = engine.max_rows if max_batch is None \
            else min(int(max_batch), engine.max_rows)
        if self.max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.max_wait_s = max(float(max_wait_ms), 0.0) / 1e3
        self._q: "queue.Queue[_Request]" = queue.Queue(
            maxsize=max(int(queue_depth), 1))
        self.tracer = tracer if tracer is not None else get_tracer()
        # The request that didn't fit the last batch.  Engine-thread-only
        # between start() and the join in stop(); the post-join flush in
        # stop() is ordered by Thread.join, not a lock.
        # analysis: unlocked-ok(engine-thread only; stop reads after join)
        self._holdover: Optional[_Request] = None
        self._draining = threading.Event()
        self._stopped = threading.Event()  # engine loop has exited
        self._thread: Optional[threading.Thread] = None
        self._stats_lock = threading.Lock()
        # analysis: shared-under(_stats_lock)
        self._latency_ms: collections.deque = collections.deque(maxlen=4096)
        # analysis: shared-under(_stats_lock)
        self._batch_rows: collections.deque = collections.deque(maxlen=4096)
        # Counters live in the metrics registry (internally locked;
        # private registry by default — the fleet passes its shared one
        # with a replica label); the deques above stay under _stats_lock
        # for the stats() percentile snapshot.
        self.registry = (registry if registry is not None
                         else MetricsRegistry())
        labels = dict(metric_labels or {})
        labelnames = tuple(sorted(labels))
        reg = self.registry
        self._c_submitted = reg.counter(
            "ddp_batcher_submitted_total",
            "Requests accepted for batching", labelnames).labels(**labels)
        self._c_served = reg.counter(
            "ddp_batcher_served_total",
            "Requests served with logits", labelnames).labels(**labels)
        self._c_shed_queue_full = reg.counter(
            "ddp_batcher_shed_queue_full_total",
            "Requests shed at admission (queue at capacity)",
            labelnames).labels(**labels)
        self._c_rejected_oversize = reg.counter(
            "ddp_batcher_rejected_oversize_total",
            "Requests rejected as larger than the largest bucket",
            labelnames).labels(**labels)
        self._c_timed_out = reg.counter(
            "ddp_batcher_timed_out_total",
            "Requests whose caller gave up before service",
            labelnames).labels(**labels)
        self._c_batches = reg.counter(
            "ddp_batcher_batches_total",
            "Batches formed and forwarded", labelnames).labels(**labels)
        self._h_latency = reg.histogram(
            "ddp_batcher_request_latency_ms",
            "Served-request latency, submit to logits (ms)",
            labelnames).labels(**labels)

    # Legacy counter names: read-only views of the registry children.
    @property
    def submitted(self) -> int:
        return int(self._c_submitted.value)

    @property
    def served_requests(self) -> int:
        return int(self._c_served.value)

    @property
    def shed_queue_full(self) -> int:
        return int(self._c_shed_queue_full.value)

    @property
    def rejected_oversize(self) -> int:
        return int(self._c_rejected_oversize.value)

    @property
    def timed_out(self) -> int:
        return int(self._c_timed_out.value)

    @property
    def batches(self) -> int:
        return int(self._c_batches.value)

    # -- caller side -------------------------------------------------------

    def submit(self, images: np.ndarray,
               timeout: Optional[float] = None,
               req_id: Optional[str] = None) -> np.ndarray:
        """Block until ``images``' logits are ready (or raise).  Thread-safe
        — this is the one entry point every HTTP handler thread and load
        generator worker calls concurrently.  ``req_id`` (router-minted)
        rides into the request's spans for flow reconstruction."""
        images = np.asarray(images)
        # Validate at ADMISSION: a malformed request must fail alone, not
        # poison the innocent requests it would have been co-batched with.
        if images.ndim != 4 or images.shape[1:] != self.engine.input_shape:
            raise ValueError(
                f"expected images [n, "
                f"{', '.join(map(str, self.engine.input_shape))}], got "
                f"{images.shape}")
        if images.dtype != np.uint8:
            raise ValueError(
                f"expected uint8 images (the loaders' wire format), got "
                f"{images.dtype}; scale/quantize on the client")
        n = images.shape[0]
        if n == 0:
            raise ValueError("empty request (0 rows)")
        if n > self.engine.max_rows:
            self._c_rejected_oversize.inc()
            raise RequestTooLarge(
                f"{n} rows exceed the largest padded batch bucket "
                f"{self.engine.max_rows}; split the request")
        if self._draining.is_set():
            raise Draining("server is draining; no new requests accepted")
        req = _Request(images, req_id=req_id)
        self._c_submitted.inc()
        try:
            self._q.put_nowait(req)
        except queue.Full:
            self._c_shed_queue_full.inc()
            raise QueueFull(
                f"admission queue at capacity ({self._q.maxsize} "
                "requests); retry after backoff") from None
        if self._stopped.is_set():
            # Admission race closed: the engine loop exited between our
            # draining check and the put, so nothing will consume the
            # queue — fail the stranded request(s) NOW (the loop sets
            # _stopped BEFORE its own final flush, so a put that missed
            # that flush always lands in this branch).
            self._flush_queue()
        if not req.event.wait(timeout):
            req.abandoned = True  # reclaim the forward capacity
            self._c_timed_out.inc()
            raise TimeoutError(
                f"request not served within {timeout}s (queue depth "
                f"{self._q.qsize()})")
        if req.error is not None:
            raise req.error
        lat_ms = (time.monotonic() - req.t_submit) * 1e3
        with self._stats_lock:
            self._latency_ms.append(lat_ms)
        self._c_served.inc()
        self._h_latency.observe(lat_ms)
        return req.logits

    # -- engine thread -----------------------------------------------------

    def start(self) -> "DynamicBatcher":
        if self._thread is None:
            self._thread = threading.Thread(target=self._loop, daemon=True,
                                            name="serve-batcher")
            self._thread.start()
        return self

    def _loop(self) -> None:
        while True:
            batch = self._collect()
            if batch:
                self._run_batch(batch)
            elif self._draining.is_set() and self._holdover is None \
                    and self._q.empty():
                # Drained.  Order matters: mark stopped FIRST, then make
                # one final flush — a submit that slips a request in
                # after this flush must observe _stopped (set before it)
                # and flush its own request (see submit()).
                self._stopped.set()
                self._flush_queue()
                return

    def _collect(self) -> List[_Request]:
        """One formed batch: first request (held-over or queued), then
        accumulate until ``max_batch`` rows or the wait budget from the
        FIRST request's arrival runs out.  An empty queue is not an event
        — the engine thread just polls again (the empty-queue-timeout
        edge case tests/test_serve.py pins)."""
        first = self._holdover
        self._holdover = None
        if first is None:
            try:
                # Bounded get: the poll interval is what lets drain() make
                # progress when the queue is already empty.
                first = self._q.get(timeout=0.05)
            except queue.Empty:
                return []
        batch, rows = [first], first.n
        deadline = first.t_submit + self.max_wait_s
        while rows < self.max_batch:
            wait = deadline - time.monotonic()
            try:
                if wait <= 0 or self._draining.is_set():
                    # Budget spent (or draining): never WAIT for more work
                    # — but take everything already queued, up to
                    # max_batch.  Without this, a queue whose delay
                    # exceeds the wait budget (i.e. saturation, exactly
                    # when batching pays) would hand every request a
                    # pre-expired deadline and collapse to batch-of-1
                    # (measured: mean 1.03 rows/batch at 64 concurrent
                    # clients before this branch existed).
                    nxt = self._q.get_nowait()
                else:
                    nxt = self._q.get(timeout=wait)
            except queue.Empty:
                break
            if rows + nxt.n > self.max_batch:
                self._holdover = nxt  # never split a request
                break
            batch.append(nxt)
            rows += nxt.n
        return batch

    def _run_batch(self, batch: List[_Request]) -> None:
        batch = [r for r in batch if not r.abandoned]
        if not batch:
            return  # every caller gave up: don't burn the forward
        # Claim the process-unique batch sequence HERE so queue_wait/
        # batch_form and the engine's pad/h2d/forward/d2h spans share one
        # key even across a hot-swap replacing the engine mid-run.
        seq = claim_batch_seq()
        t_form = time.monotonic()
        for r in batch:
            # Per-request admission->formation wait; overlap=True — these
            # intervals run concurrently with the engine thread's serial
            # pipeline and would double-count a wall-time identity.
            self.tracer.add_span("queue_wait", r.t_submit,
                                 t_form - r.t_submit, step=seq, overlap=True,
                                 req=r.req_id)
        try:
            with self.tracer.span("batch_form", step=seq):
                images = (batch[0].images if len(batch) == 1
                          else np.concatenate([r.images for r in batch]))
            logits = self.engine.forward(images, seq=seq)
        except BaseException as e:
            for r in batch:
                r.error = e
                r.event.set()
            return
        off = 0
        for r in batch:
            r.logits = logits[off:off + r.n]
            off += r.n
            r.event.set()
        with self._stats_lock:
            self._batch_rows.append(off)
        self._c_batches.inc()

    # -- lifecycle ---------------------------------------------------------

    def _flush_queue(self) -> int:
        """Fail everything still queued (plus any holdover) with
        :class:`Draining`; returns the count.  Only called once nothing
        will consume the queue again (loop exit, post-join, or the
        submit-side race branch)."""
        leftovers = [self._holdover] if self._holdover is not None else []
        self._holdover = None
        while True:
            try:
                leftovers.append(self._q.get_nowait())
            except queue.Empty:
                break
        for r in leftovers:
            r.error = Draining("server drained before this request ran")
            r.event.set()
        return len(leftovers)

    def drain(self, timeout: Optional[float] = 30.0) -> bool:
        """Graceful shutdown: refuse new work, serve everything accepted,
        stop the engine thread.  Returns True when fully drained within
        ``timeout``.  Idempotent.  Any request that slipped past the
        admission check during the transition is failed with
        :class:`Draining` rather than left blocking forever (the
        loop-exit/_stopped ordering in ``_loop``/``submit`` closes the
        check-then-enqueue race)."""
        self._draining.set()
        ok = True
        if self._thread is not None:
            self._thread.join(timeout)
            ok = not self._thread.is_alive()
            if ok:
                self._thread = None
        else:
            self._stopped.set()  # never started: nothing consumes
        # Post-join flush: the normal path was already flushed by the
        # loop itself (usually 0 here); after a join TIMEOUT (engine
        # wedged mid-forward) it fails the still-queued requests so
        # their callers unblock instead of hanging with the engine.
        stranded = self._flush_queue()
        return ok and not stranded

    @property
    def draining(self) -> bool:
        return self._draining.is_set()

    def queue_depth(self) -> int:
        """Live admission-queue depth (requests accepted, not yet formed
        into a batch) — the router's least-loaded routing key and the
        ``Retry-After`` input; also on /healthz."""
        return self._q.qsize()

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        with self._stats_lock:
            lat = list(self._latency_ms)
            rows = list(self._batch_rows)
            out = {
                "submitted": self.submitted,
                "served_requests": self.served_requests,
                "shed_queue_full": self.shed_queue_full,
                "rejected_oversize": self.rejected_oversize,
                "timed_out": self.timed_out,
                "batches": self.batches,
                "queue_depth": self._q.qsize(),
                "queue_capacity": self._q.maxsize,
                "max_batch": self.max_batch,
                "max_wait_ms": self.max_wait_s * 1e3,
                "draining": self._draining.is_set(),
            }
        out["latency_ms"] = {k: (round(v, 3) if v is not None else None)
                             for k, v in percentiles(lat).items()}
        out["mean_batch_rows"] = (round(statistics.mean(rows), 2)
                                  if rows else None)
        return out
