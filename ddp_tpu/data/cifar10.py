"""CIFAR-10 dataset loading.

The reference uses ``torchvision.datasets.CIFAR10(root="data/cifar10",
download=True)`` (singlegpu.py:161-171).  We read the same on-disk layout
(the python-pickle batches ``cifar-10-batches-py/data_batch_{1..5}`` +
``test_batch``) directly with numpy — torchvision is not a given on TPU
hosts, and the unpickled arrays feed the vectorised augmentation pipeline
(``augment.py``) without a per-sample Python transform stage.

Like the reference (``download=True``), :func:`load` fetches the official
tarball when the data is absent — but failure is graceful: TPU pods are
usually egress-less, so a network error degrades to a FileNotFoundError
that says where to put the files.  ``synthetic()`` provides a deterministic
stand-in with the same shapes/dtypes for tests and benches.
"""
from __future__ import annotations

import hashlib
import os
import pickle
import tarfile
import tempfile
from typing import NamedTuple, Tuple

import numpy as np

DEFAULT_ROOT = "data/cifar10"
_BATCH_DIR = "cifar-10-batches-py"
# The official source torchvision uses (singlegpu.py:161-171 downloads
# through torchvision.datasets.CIFAR10, which fetches exactly this tarball).
_URL = "https://www.cs.toronto.edu/~kriz/cifar-10-python.tar.gz"
_MD5 = "c58f30108f718f92721af3b95e74349a"
NUM_CLASSES = 10


class Dataset(NamedTuple):
    images: np.ndarray  # uint8 [N,32,32,3] (NHWC — the TPU-native layout)
    labels: np.ndarray  # int32 [N]

    def __len__(self) -> int:
        return len(self.images)


def _load_batch(path: str) -> Tuple[np.ndarray, np.ndarray]:
    with open(path, "rb") as f:
        d = pickle.load(f, encoding="bytes")
    imgs = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
    labels = np.asarray(d.get(b"labels", d.get(b"fine_labels")), np.int32)
    return np.ascontiguousarray(imgs), labels


def _download(root: str, url: str = _URL, md5: str = _MD5) -> bool:
    """Fetch + verify + extract the official tarball; False on any failure.

    Process-race-safe the same way the reference's torchvision download is
    not required to be: the extraction happens in a temp dir and is moved
    into place atomically, so concurrent hosts can all call this.
    """
    import urllib.request
    try:
        os.makedirs(root, exist_ok=True)
        with tempfile.TemporaryDirectory(dir=root) as tmp:
            tar_path = os.path.join(tmp, "cifar10.tar.gz")
            with urllib.request.urlopen(url, timeout=60) as r, \
                    open(tar_path, "wb") as f:
                digest = hashlib.md5()
                while chunk := r.read(1 << 20):
                    digest.update(chunk)
                    f.write(chunk)
            if md5 and digest.hexdigest() != md5:
                return False
            with tarfile.open(tar_path) as tf:
                tf.extractall(tmp, filter="data")
            src = os.path.join(tmp, _BATCH_DIR)
            if not os.path.isdir(src):
                return False
            try:
                os.rename(src, os.path.join(root, _BATCH_DIR))
            except OSError:
                pass  # another process won the race — fine, data exists
        return os.path.isdir(os.path.join(root, _BATCH_DIR))
    except Exception:
        return False


def load(root: str = DEFAULT_ROOT,
         download: bool = True) -> Tuple[Dataset, Dataset]:
    """(train 50k, test 10k) from the standard pickle layout.

    ``download=True`` mirrors the reference (singlegpu.py:165): fetch the
    official tarball when absent — degrading to the explanatory error below
    when the host has no egress.
    """
    base = os.path.join(root, _BATCH_DIR)
    if not os.path.isdir(base) and download:
        _download(root)
    if not os.path.isdir(base):
        raise FileNotFoundError(
            f"CIFAR-10 not found under {base!r} and auto-download failed "
            "(egress-less host?). Place the extracted 'cifar-10-batches-py' "
            "directory there (the reference's torchvision download layout), "
            "or run with --synthetic.")
    train_parts = [_load_batch(os.path.join(base, f"data_batch_{i}"))
                   for i in range(1, 6)]
    train = Dataset(np.concatenate([p[0] for p in train_parts]),
                    np.concatenate([p[1] for p in train_parts]))
    test = Dataset(*_load_batch(os.path.join(base, "test_batch")))
    return train, test


def synthetic(n_train: int = 2048, n_test: int = 512,
              seed: int = 0,
              label_noise: float = 0.0) -> Tuple[Dataset, Dataset]:
    """Deterministic fake CIFAR with a learnable signal: the label is
    encoded in each image's mean brightness, so a real model trained on it
    shows a decreasing loss (needed for end-to-end tests, SURVEY.md §4).

    ``label_noise`` relabels that fraction of examples (train AND test)
    uniformly at random, so accuracy-parity recordings can target a
    NON-saturated regime — at 100% vs 100% a real framework difference
    would be invisible, while at an intermediate accuracy the comparison
    discriminates (analytic ceiling ``1 - 0.9*p``).
    """
    rng = np.random.default_rng(seed)
    # Flips come from an INDEPENDENT stream so the images and underlying
    # clean labels of BOTH splits are bit-identical across label_noise
    # settings — which makes the empirical ceiling of a noisy dataset
    # computable as agreement with the label_noise=0 counterpart.
    noise_rng = np.random.default_rng([seed, 0x5EED_10])

    def make(n: int) -> Dataset:
        labels = rng.integers(0, NUM_CLASSES, n).astype(np.int32)
        base = rng.integers(0, 64, (n, 32, 32, 3))
        imgs = np.clip(base + (labels * 18)[:, None, None, None],
                       0, 255).astype(np.uint8)
        if label_noise > 0.0:
            flip = noise_rng.random(n) < label_noise
            labels = np.where(
                flip,
                noise_rng.integers(0, NUM_CLASSES, n).astype(np.int32),
                labels)
        return Dataset(imgs, labels)

    return make(n_train), make(n_test)
