"""Build + bind the native (C++) host-side data kernels.

The reference leans on native code for its input pipeline without showing
any: torchvision transforms and the DataLoader worker pool are C++ under
the hood (singlegpu.py:154-180).  This module is the framework's explicit
equivalent: a small C++ OpenMP kernel (_native/crop_flip.cpp) compiled on
first use with the system toolchain and bound via ctypes — no pybind11 /
Python.h dependency.

The Python side draws all randomness (data/augment.py) and passes the
offsets in, so the native path is bit-identical to the numpy path and can
be swapped freely; ``DDP_TPU_NATIVE=0`` disables it, and any build failure
falls back to numpy silently (the .so is a throughput optimisation, not a
semantic dependency).
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "_native", "crop_flip.cpp")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_and_load() -> Optional[ctypes.CDLL]:
    with open(_SRC, "rb") as f:
        src = f.read()
    tag = hashlib.sha256(src).hexdigest()[:16]
    cache_dir = os.path.join(
        os.environ.get("XDG_CACHE_HOME",
                       os.path.join(os.path.expanduser("~"), ".cache")),
        "ddp_tpu")
    so_path = os.path.join(cache_dir, f"crop_flip_{tag}.so")
    if not os.path.exists(so_path):
        os.makedirs(cache_dir, exist_ok=True)
        fd, tmp = tempfile.mkstemp(suffix=".so", dir=cache_dir)
        os.close(fd)
        base = ["g++", "-O3", "-shared", "-fPIC", _SRC, "-o", tmp]
        for extra in (["-fopenmp"], []):  # OpenMP if available
            try:
                subprocess.run(base[:-2] + extra + base[-2:], check=True,
                               capture_output=True, timeout=120)
                break
            except (subprocess.SubprocessError, FileNotFoundError):
                continue
        else:
            os.unlink(tmp)
            return None
        os.replace(tmp, so_path)  # atomic: concurrent builders race safely
    lib = ctypes.CDLL(so_path)
    lib.crop_flip_u8.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_int64]
    lib.crop_flip_u8.restype = None
    return lib


def get_lib() -> Optional[ctypes.CDLL]:
    """The loaded kernel library, building it on first call; None if the
    toolchain is unavailable or ``DDP_TPU_NATIVE=0``."""
    global _lib, _tried
    if not _tried:
        _tried = True
        if os.environ.get("DDP_TPU_NATIVE", "1") != "0":
            try:
                _lib = _build_and_load()
            except OSError:
                _lib = None
    return _lib


def crop_flip(batch: np.ndarray, ys: np.ndarray, xs: np.ndarray,
              flip: np.ndarray) -> Optional[np.ndarray]:
    """Native RandomCrop+HFlip; None when the library is unavailable."""
    lib = get_lib()
    if lib is None or batch.dtype != np.uint8:
        # Non-uint8 batches (the numpy path handles any dtype) must not be
        # silently truncated by the u8 kernel — fall through to numpy.
        return None
    batch = np.ascontiguousarray(batch)
    ys = np.ascontiguousarray(ys, dtype=np.int64)
    xs = np.ascontiguousarray(xs, dtype=np.int64)
    flip_u8 = np.ascontiguousarray(flip, dtype=np.uint8)
    out = np.empty_like(batch)
    lib.crop_flip_u8(batch.ctypes.data, out.ctypes.data, ys.ctypes.data,
                     xs.ctypes.data, flip_u8.ctypes.data,
                     np.int64(batch.shape[0]))
    return out
