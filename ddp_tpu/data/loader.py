"""Batched loaders feeding the SPMD train/eval steps.

The reference feeds each rank from its own ``DataLoader`` over a
``DistributedSampler`` shard (multigpu.py:147-154); global batch k is then
implicitly {rank r's batch k}.  Our single-process SPMD program consumes
*global* batches sharded on the leading axis, so ``TrainLoader`` materialises
exactly that concatenation: row block r of global batch k == what rank r's
DataLoader would have yielded — device r therefore sees precisely rank r's
reference data stream, preserving per-shard BN statistics and the gradient
mean.

Ragged final batches are yielded at their true size (50000 isn't divisible by
512; every replica's shard is equally ragged thanks to sampler padding), which
costs one extra XLA compilation for the remainder shape instead of perturbing
the loss mean or BN stats with padding (SURVEY.md §7 hard-part #3).  Eval
batches are padded+masked instead — eval has masked counters, so padding is
free there.
"""
from __future__ import annotations

from typing import Dict, Iterator, Optional, Sequence

import numpy as np

from .augment import random_crop_flip
from .cifar10 import Dataset
from .sampler import DistributedShardSampler, ShuffleSampler


class TrainLoader:
    """Epoch-aware global-batch iterator with reference sampler semantics.

    ``per_replica_batch`` is the reference's ``--batch_size`` (512/rank,
    multigpu.py:259); the global batch is ``per_replica_batch *
    num_replicas``.  ``local_replicas`` restricts which replicas' rows this
    process materialises (multi-host feeding: host h passes its own chips'
    replica ids and hands the result to
    ``jax.make_array_from_process_local_data``).
    """

    def __init__(self, dataset: Dataset, per_replica_batch: int,
                 num_replicas: int = 1, *, shuffle: bool = True,
                 augment: bool = True, seed: int = 0,
                 local_replicas: Optional[Sequence[int]] = None):
        self.dataset = dataset
        self.per_replica_batch = per_replica_batch
        self.num_replicas = num_replicas
        self.augment = augment
        self.seed = seed
        self.epoch = 0
        self.local_replicas = (range(num_replicas) if local_replicas is None
                               else local_replicas)
        if num_replicas > 1:
            self.samplers = [
                DistributedShardSampler(len(dataset), num_replicas, r,
                                        shuffle=shuffle, seed=seed)
                for r in self.local_replicas]
        else:
            self.samplers = [ShuffleSampler(len(dataset), shuffle=shuffle,
                                            seed=seed)]
        self.steps_per_epoch = -(-len(self.samplers[0]) // per_replica_batch)
        import threading
        # The prefetch pool (data/prefetch.py) calls materialize() from
        # several workers at once; the lazy per-epoch shard build must
        # happen exactly once (it is idempotent — pure function of
        # (seed, epoch) — but N workers each permuting a 50k-index array
        # is N-1 wasted shuffles at every epoch boundary).
        self._shards_lock = threading.Lock()

    def set_epoch(self, epoch: int) -> None:
        """Reference ``sampler.set_epoch`` (multigpu.py:103)."""
        self.epoch = epoch
        for s in self.samplers:
            s.set_epoch(epoch)
        self._shards = None  # recomputed lazily for the new epoch

    def __len__(self) -> int:
        return self.steps_per_epoch

    def optimizer_steps_per_epoch(self, grad_accum: int = 1) -> int:
        """How many optimizer steps one epoch actually takes under
        ``--grad_accum``.  The accumulation grouping (``Trainer``'s
        ``_stack_groups`` and the resident splitter) flushes the current
        partial group when the ragged final batch arrives — the tail is
        always its own optimizer step — so the count is
        ``ceil(n_full / A) + (1 if ragged else 0)``, which exceeds
        ``ceil(len(loader) / A)`` whenever the number of FULL batches
        isn't divisible by A.  The LR schedule counts optimizer steps
        (torch's scheduler.step()-after-optimizer.step() convention,
        /root/reference/singlegpu.py:108), so it must be built from this
        number, not from the batch count."""
        a = max(grad_accum, 1)
        n_full, rem = divmod(len(self.samplers[0]), self.per_replica_batch)
        return -(-n_full // a) + (1 if rem else 0)

    def _epoch_shards(self):
        if getattr(self, "_shards", None) is None:
            with self._shards_lock:
                if getattr(self, "_shards", None) is None:
                    self._shards = [s.indices() for s in self.samplers]
        return self._shards

    def materialize(self, k: int) -> Dict[str, np.ndarray]:
        """Build global batch ``k`` of the current epoch.  Thread-safe,
        order-independent AND topology-invariant: the augmentation RNG is
        keyed (seed, epoch, k, GLOBAL replica id), so a prefetch pool can
        build batches concurrently, and a replica's rows get the same
        crops/flips no matter which process materialises them — a 2-host
        run augments identically to the single-process run of the same
        seed.  (The reference's torchvision transforms draw from one
        global torch RNG stream — per-replica keying preserves the
        distribution, which is what the loss curve depends on.)"""
        shards = self._epoch_shards()
        b = self.per_replica_batch
        idx = np.concatenate([sh[k * b:(k + 1) * b] for sh in shards])
        imgs = self.dataset.images[idx]
        if self.augment:
            per_rep = [random_crop_flip(
                part, np.random.default_rng(
                    (self.seed, self.epoch, k, int(r), 0x5EED)))
                for r, part in zip(self.local_replicas,
                                   np.split(imgs, len(self.samplers)))]
            imgs = np.concatenate(per_rep)
        # uint8 on the wire; ToTensor scaling happens on device
        # (train.step._as_input) at 1/4 the transfer bytes.
        return {"image": imgs, "label": self.dataset.labels[idx]}

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        return (self.materialize(k) for k in range(self.steps_per_epoch))

    def epoch_index_matrix(self):
        """The current epoch's batches as sample indices, for the
        device-resident path (data/resident.py + train/epoch.py).

        Returns ``(full, tail)``: ``full`` is int32 ``[steps_full,
        R_local * b]`` where row k holds exactly the indices
        ``materialize(k)`` would gather (replica row-blocks concatenated in
        the same order), and ``tail`` is the final ragged global batch's
        indices (``[R_local * b_tail]``) or ``None`` when the shard size
        divides the batch — the same true-shape ragged-batch semantics as
        the streaming path (singlegpu.py:179, drop_last=False).
        """
        shards = self._epoch_shards()
        b = self.per_replica_batch
        n_full = len(shards[0]) // b
        full = np.concatenate(
            [sh[:n_full * b].reshape(n_full, b) for sh in shards],
            axis=1).astype(np.int32)
        tails = [sh[n_full * b:] for sh in shards]
        tail = (np.concatenate(tails).astype(np.int32)
                if len(tails[0]) else None)
        return full, tail


class EvalLoader:
    """Sequential test-set batches, padded+masked to mesh divisibility.

    Reference: batch 512, shuffle=False, full set (multigpu.py:240-246) —
    but evaluated redundantly per rank; with masked ``psum`` counters we
    shard it instead (same result, SURVEY.md appendix).
    """

    def __init__(self, dataset: Dataset, per_replica_batch: int,
                 num_replicas: int = 1,
                 local_replicas: Optional[Sequence[int]] = None):
        self.dataset = dataset
        self.global_batch = per_replica_batch * num_replicas
        self.num_replicas = num_replicas
        self.local_replicas = (range(num_replicas) if local_replicas is None
                               else local_replicas)

    def __len__(self) -> int:
        return -(-len(self.dataset) // self.global_batch)

    def epoch_index_matrix(self):
        """Test-set indices as ``(idx, mask)`` of shape ``[steps,
        global_batch]`` for the device-resident eval scan
        (train/epoch.py:make_eval_epoch).  Sequential order
        (shuffle=False, multigpu.py:244), padded with masked index-0 rows to
        keep shapes static; multi-host keeps only this process's replicas'
        column blocks."""
        n = len(self.dataset)
        steps = -(-n // self.global_batch)
        total = steps * self.global_batch
        idx = np.zeros(total, np.int32)
        idx[:n] = np.arange(n, dtype=np.int32)
        mask = np.zeros(total, np.float32)
        mask[:n] = 1.0
        idx = idx.reshape(steps, self.global_batch)
        mask = mask.reshape(steps, self.global_batch)
        if len(self.local_replicas) != self.num_replicas:
            per = self.global_batch // self.num_replicas
            cols = np.concatenate([np.arange(r * per, (r + 1) * per)
                                   for r in self.local_replicas])
            idx, mask = idx[:, cols], mask[:, cols]
        return idx, mask

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        n = len(self.dataset)
        for start in range(0, n, self.global_batch):
            imgs = self.dataset.images[start:start + self.global_batch]
            labels = self.dataset.labels[start:start + self.global_batch]
            size = len(imgs)
            pad = -size % self.num_replicas
            mask = np.ones(size, np.float32)
            if pad:
                imgs = np.concatenate([imgs, np.zeros_like(imgs[:pad])])
                labels = np.concatenate([labels, np.zeros(pad, labels.dtype)])
                mask = np.concatenate([mask, np.zeros(pad, np.float32)])
            if len(self.local_replicas) != self.num_replicas:
                # Multi-host: keep only this host's replicas' row blocks.
                per = len(imgs) // self.num_replicas
                rows = np.concatenate([np.arange(r * per, (r + 1) * per)
                                       for r in self.local_replicas])
                imgs, labels, mask = imgs[rows], labels[rows], mask[rows]
            yield {"image": imgs, "label": labels, "mask": mask}
