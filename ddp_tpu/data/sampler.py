"""Per-rank data sharding with torch ``DistributedSampler`` semantics
(reference multigpu.py:7, 152-153; set_epoch at multigpu.py:103).

Semantics reproduced exactly (verified against
torch.utils.data.DistributedSampler in tests/test_data.py — structural
properties under shuffle, index-exact without shuffle):
- ``num_samples = ceil(len / world)`` and ``total = num_samples * world``
  (drop_last=False default): the index list is padded to divisibility by
  repeating its head.
- shuffle=True (default): epoch-seeded permutation, re-seeded via
  ``set_epoch`` (seed + epoch) so every epoch reshuffles identically across
  ranks.
- rank r takes the strided slice ``indices[r::world]`` — disjoint (up to the
  padding) and equal-sized, which is what makes DDP's mean-of-rank-means equal
  the global mean.

The permutation itself uses numpy's PCG64 rather than torch's Philox — the
*distributional* semantics (which the loss curve depends on) are identical;
the concrete order is RNG-specific in the reference too.
"""
from __future__ import annotations

import numpy as np


class DistributedShardSampler:
    def __init__(self, dataset_size: int, world_size: int = 1, rank: int = 0,
                 shuffle: bool = True, seed: int = 0,
                 drop_last: bool = False):
        if not 0 <= rank < world_size:
            raise ValueError(f"rank {rank} out of range for world {world_size}")
        self.dataset_size = dataset_size
        self.world_size = world_size
        self.rank = rank
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last and dataset_size % world_size != 0:
            self.num_samples = dataset_size // world_size
        else:
            self.num_samples = -(-dataset_size // world_size)  # ceil
        self.total_size = self.num_samples * world_size

    def set_epoch(self, epoch: int) -> None:
        """Reference multigpu.py:103 — re-seeds the shuffle each epoch."""
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        """This rank's index shard for the current epoch."""
        if self.shuffle:
            rng = np.random.default_rng(self.seed + self.epoch)
            idx = rng.permutation(self.dataset_size)
        else:
            idx = np.arange(self.dataset_size)
        if not self.drop_last and self.total_size > len(idx):
            pad = self.total_size - len(idx)
            reps = -(-pad // len(idx))
            idx = np.concatenate([idx] + [idx] * reps)[: self.total_size]
        else:
            idx = idx[: self.total_size]
        return idx[self.rank:self.total_size:self.world_size]

    def __len__(self) -> int:
        return self.num_samples


class ShuffleSampler:
    """Single-process shuffle=True DataLoader semantics (singlegpu.py:179):
    fresh permutation every epoch, no padding (final batch may be ragged)."""

    def __init__(self, dataset_size: int, shuffle: bool = True, seed: int = 0):
        self.dataset_size = dataset_size
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def indices(self) -> np.ndarray:
        if not self.shuffle:
            return np.arange(self.dataset_size)
        rng = np.random.default_rng(self.seed + self.epoch)
        return rng.permutation(self.dataset_size)

    def __len__(self) -> int:
        return self.dataset_size
