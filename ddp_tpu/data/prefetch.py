"""Background host->device prefetch.

The reference hides input-pipeline latency with ``pin_memory=True`` +
DataLoader worker processes (singlegpu.py:177); the TPU analogue here is a
thread pool that materialises (gather + augment) upcoming batches
concurrently, plus a device_put one step ahead of consumption.  Loaders
exposing ``materialize(k)`` (order-independent, per-batch-seeded —
``TrainLoader``) get true parallel workers; any other batch iterable falls
back to a single pipelining thread.
"""
from __future__ import annotations

import queue
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterable, Iterator

import numpy as np

from ..train.step import shard_batch

_DONE = object()


def prefetch_to_device(batches: Iterable[Dict[str, np.ndarray]], mesh,
                       depth: int = 2, workers: int = 4) -> Iterator[dict]:
    """Yield device-resident, data-sharded batches ahead of consumption."""
    if hasattr(batches, "materialize") and hasattr(batches, "__len__"):
        yield from _pooled(batches, mesh, depth, workers)
    else:
        yield from _threaded(iter(batches), mesh, depth)


def _pooled(loader, mesh, depth: int, workers: int) -> Iterator[dict]:
    n = len(loader)
    with ThreadPoolExecutor(max_workers=workers) as pool:
        futures = deque(pool.submit(loader.materialize, k)
                        for k in range(min(workers + depth, n)))
        next_k = len(futures)
        while futures:
            batch = futures.popleft().result()
            if next_k < n:
                futures.append(pool.submit(loader.materialize, next_k))
                next_k += 1
            yield shard_batch(batch, mesh)


def _threaded(batches: Iterator[Dict[str, np.ndarray]], mesh,
              depth: int) -> Iterator[dict]:
    q: queue.Queue = queue.Queue(maxsize=depth)

    def worker() -> None:
        try:
            for batch in batches:
                q.put(shard_batch(batch, mesh))
        except BaseException as e:  # surfaced in the consumer thread
            q.put(("__error__", e))
            return
        q.put(_DONE)

    threading.Thread(target=worker, daemon=True).start()
    while True:
        item = q.get()
        if item is _DONE:
            return
        if isinstance(item, tuple) and len(item) == 2 \
                and item[0] == "__error__":
            raise item[1]
        yield item
