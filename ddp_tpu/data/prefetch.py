"""Background host->device prefetch — the streaming overlap engine.

The reference hides input-pipeline latency with ``pin_memory=True`` +
DataLoader worker processes (singlegpu.py:177); the TPU analogue here is a
thread pool that materialises (gather + augment) upcoming batches
concurrently, plus a device_put up to ``depth`` steps ahead of consumption,
so host augment, H2D transfer, and device compute pipeline instead of
serializing.  Loaders exposing ``materialize(k)`` (order-independent,
per-batch-seeded — ``TrainLoader``) get true parallel workers; any other
batch iterable falls back to a single pipelining thread.

Contracts the tests pin (tests/test_prefetch.py):

- **Order/equality**: the yielded stream is the loader's batches, in order,
  bit-for-bit — prefetch is a scheduling change, never a data change, at
  every depth/worker setting (including ``depth=0`` = no overlap, the
  plain-loop shape).
- **Clean shutdown**: abandoning the iterator (consumer exception, early
  ``break``, preemption unwinding the epoch loop) stops and joins the
  producer machinery — no thread left blocked on a queue, no pending
  future still materialising.  This is what lets the engine compose with
  the resilience paths (SIGTERM/watchdog) without leaking threads.
- **Error transparency**: a producer-side exception re-raises in the
  consumer, after shutdown.

``PrefetchStats`` (opt-in) attributes where streaming time goes — producer
host busy time (materialise + augment), H2D enqueue time, and consumer
wait time (the dispatch gap: how long the device-feeding loop sat waiting
for a batch that was not ready).  ``bench.py --stream_attr`` builds the
BASELINE.md streaming-gap table from these plus the tracer's span record
(utils/profiling.py:attribute_streaming).

Telemetry (round 7): every stage also reports into the run's span tracer
(obs/tracer.py) — ``host_augment`` and ``h2d`` spans from wherever they
actually run (marked ``overlap=True`` on producer threads, whose time
hides behind the consumer loop), ``data_wait`` from the consumer's side
of the queue.  ``step0`` anchors span step numbers at the trainer's
global step so "where did step 4817 go" is answerable from the spill.
With the default NullTracer the spans are shared no-op context managers
— the ``--obs_off`` zero-overhead contract.
"""
from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterable, Iterator, Optional

import numpy as np

from ..obs.tracer import get_tracer
from ..train.step import shard_batch

_DONE = object()
_ERROR = "__error__"


class PrefetchStats:
    """Thread-safe wall-time attribution counters for one streaming run.

    ``host_s``  — producer time materialising/augmenting batches (sums
    across pool workers, so it can exceed wall time when workers overlap);
    ``h2d_s``   — time in ``shard_batch`` (device_put enqueue; on CPU and
    through remote-device tunnels this is where the copy cost lands);
    ``wait_s``  — consumer time blocked waiting for a batch that was not
    ready: the measured pipeline bubble.  ``wait_s`` ~ 0 with the engine
    keeping up means the input pipeline is fully hidden behind compute —
    occupancy as a number, not an argument (VERDICT r5 next #4).

    ``registry`` (a :class:`~ddp_tpu.obs.registry.MetricsRegistry`)
    mirrors the four fields as function-backed ``ddp_prefetch_*``
    instruments — this object stays the source of truth; the registry
    reads it at scrape time.
    """

    def __init__(self, registry=None, metric_labels=None) -> None:
        self._lock = threading.Lock()
        self.host_s = 0.0   # analysis: shared-under(_lock)
        self.h2d_s = 0.0    # analysis: shared-under(_lock)
        self.wait_s = 0.0   # analysis: shared-under(_lock)
        self.batches = 0    # analysis: shared-under(_lock)
        if registry is not None:
            labels = dict(metric_labels or {})
            names = tuple(sorted(labels))
            for metric, help_, fn in (
                    ("ddp_prefetch_host_seconds_total",
                     "Producer time materialising/augmenting batches",
                     lambda: self.host_s),
                    ("ddp_prefetch_h2d_seconds_total",
                     "Host-to-device enqueue time",
                     lambda: self.h2d_s),
                    ("ddp_prefetch_wait_seconds_total",
                     "Consumer time blocked on an unready batch (the "
                     "pipeline bubble)",
                     lambda: self.wait_s),
                    ("ddp_prefetch_batches_total",
                     "Batches yielded to the consumer loop",
                     lambda: float(self.batches))):
                registry.counter(metric, help_,
                                 names).labels(**labels).set_function(fn)

    def _add(self, field: str, dt: float) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + dt)

    def count_batch(self) -> None:
        with self._lock:
            self.batches += 1

    def per_step_ms(self) -> Dict[str, float]:
        # Under the lock: pool workers are still adding while the epoch
        # summary reads, and the numbers must be one consistent snapshot
        # (a torn host_s/batches pair misattributes the bubble).
        with self._lock:
            n = max(self.batches, 1)
            return {"host_ms_per_step": round(self.host_s / n * 1e3, 3),
                    "h2d_enqueue_ms_per_step":
                        round(self.h2d_s / n * 1e3, 3),
                    "consumer_wait_ms_per_step":
                        round(self.wait_s / n * 1e3, 3),
                    "batches": self.batches}


def prefetch_to_device(batches: Iterable[Dict[str, np.ndarray]], mesh,
                       depth: int = 2, workers: int = 4,
                       stats: Optional[PrefetchStats] = None,
                       shard_fn=None, tracer=None,
                       step0: int = 0, start: int = 0) -> Iterator[dict]:
    """Yield device-resident, data-sharded batches ahead of consumption.

    ``depth`` is how many batches may be in flight beyond the workers'
    own hands (the bounded-queue size); ``depth=0`` disables overlap
    entirely — materialise + device_put inline, the unprefetched loop
    (bit-identical stream, pinned by tests).  ``workers`` only applies to
    loaders with ``materialize(k)`` random access.  ``shard_fn(batch,
    mesh)`` overrides the host->device placement (default
    :func:`~ddp_tpu.train.step.shard_batch`; the accumulation path passes
    ``shard_batch_stacked`` for its ``[A, B, ...]`` group stacks).
    ``tracer`` (default: the process tracer) receives host_augment/h2d/
    data_wait spans, step-numbered from ``step0``.

    ``start`` fast-forwards the epoch to batch index ``start`` — the
    mid-epoch resume path (resilience/preemption): batches ``[0, start)``
    are never materialised for ``materialize(k)`` loaders (random access
    jumps straight to ``start``) and are materialised-but-dropped for
    plain iterators (no random access to skip with).  The yielded stream
    is bit-identical to the tail of the unoffset stream because batch
    content is a function of ``(seed, epoch, k)`` alone, never of which
    batches were consumed before it.
    """
    shard = shard_batch if shard_fn is None else shard_fn
    tracer = tracer if tracer is not None else get_tracer()
    start = max(int(start), 0)
    if depth <= 0:
        if start and hasattr(batches, "materialize") \
                and hasattr(batches, "__len__"):
            loader = batches  # bind NOW: the genexpr must not see itself
            batches = (loader.materialize(k)
                       for k in range(start, len(loader)))
            start = 0
        yield from _passthrough(iter(batches), mesh, stats, shard, tracer,
                                step0, start)
    elif hasattr(batches, "materialize") and hasattr(batches, "__len__"):
        yield from _pooled(batches, mesh, depth, max(workers, 1), stats,
                           shard, tracer, step0, start)
    else:
        yield from _threaded(iter(batches), mesh, depth, stats, shard,
                             tracer, step0, start)


def _timed(stats: Optional[PrefetchStats], field: str, fn, *args):
    if stats is None:
        return fn(*args)
    t0 = time.perf_counter()
    out = fn(*args)
    stats._add(field, time.perf_counter() - t0)
    return out


def _skip(batches: Iterator, start: int) -> None:
    """Advance a plain iterator past its first ``start`` items — the
    no-random-access fast-forward (materialise cost is paid, device_put
    is not).  Exhaustion before ``start`` just leaves an empty stream."""
    for _ in range(start):
        try:
            next(batches)
        except StopIteration:
            return


def _passthrough(batches: Iterator[Dict[str, np.ndarray]], mesh,
                 stats: Optional[PrefetchStats], shard, tracer,
                 step0: int, start: int = 0) -> Iterator[dict]:
    """The unpipelined reference shape: one batch materialised, shipped,
    then consumed, strictly in sequence (singlegpu.py:104-107's loop).
    Everything runs on the consumer thread, so the spans are serial
    (overlap=False) — exactly the attribution the depth-0 mode exists
    to expose.  A span whose body raises StopIteration is not recorded
    (tracer contract), so the exhaustion probe leaves no bogus span."""
    _skip(batches, start)
    k = step0
    while True:
        try:
            with tracer.span("host_augment", step=k):
                batch = _timed(stats, "host_s", lambda: next(batches))
        except StopIteration:
            return
        with tracer.span("h2d", step=k):
            out = _timed(stats, "h2d_s", shard, batch, mesh)
        if stats is not None:
            stats.count_batch()
        k += 1
        yield out


def _materialize_traced(tracer, stats, loader, k: int, step0: int):
    """Worker-side materialise: host_augment span marked overlap=True —
    pool workers run concurrently with the consumer loop, so their wall
    time must not be summed against it."""
    with tracer.span("host_augment", step=step0 + k, overlap=True):
        return _timed(stats, "host_s", loader.materialize, k)


def _pooled(loader, mesh, depth: int, workers: int,
            stats: Optional[PrefetchStats], shard, tracer,
            step0: int, start: int = 0) -> Iterator[dict]:
    n = len(loader)
    pool = ThreadPoolExecutor(max_workers=workers,
                              thread_name_prefix="ddp_tpu_prefetch")
    futures: deque = deque()
    try:
        # ``start`` is the mid-epoch resume offset: random access means
        # the skipped prefix is simply never submitted.
        futures.extend(pool.submit(_materialize_traced, tracer, stats,
                                   loader, k, step0)
                       for k in range(start,
                                      min(start + workers + depth, n)))
        next_k = start + len(futures)
        i = 0
        while futures:
            with tracer.span("data_wait", step=step0 + i):
                batch = _timed(stats, "wait_s", futures.popleft().result)
            if next_k < n:
                futures.append(pool.submit(_materialize_traced, tracer,
                                           stats, loader, next_k, step0))
                next_k += 1
            with tracer.span("h2d", step=step0 + i):
                out = _timed(stats, "h2d_s", shard, batch, mesh)
            if stats is not None:
                stats.count_batch()
            i += 1
            yield out
    finally:
        # Abandoned mid-epoch (consumer exception/break/preemption): drop
        # the queued work and JOIN the workers — an in-flight materialize
        # finishes (bounded: one batch per worker) and nothing else runs.
        pool.shutdown(wait=True, cancel_futures=True)


def _threaded(batches: Iterator[Dict[str, np.ndarray]], mesh, depth: int,
              stats: Optional[PrefetchStats], shard, tracer,
              step0: int, start: int = 0) -> Iterator[dict]:
    _skip(batches, start)
    q: queue.Queue = queue.Queue(maxsize=depth)
    stop = threading.Event()

    def _put(item) -> bool:
        """Bounded put that gives up when the consumer is gone — the
        producer must never block forever on a full queue (the dangling-
        thread leak the pre-round-6 engine had)."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def worker() -> None:
        # Producer thread: host_augment + h2d both run here, hidden
        # behind the consumer's dispatch — overlap=True spans.
        k = step0
        try:
            while not stop.is_set():
                try:
                    with tracer.span("host_augment", step=k, overlap=True):
                        batch = _timed(stats, "host_s",
                                       lambda: next(batches))
                except StopIteration:
                    break
                with tracer.span("h2d", step=k, overlap=True):
                    item = _timed(stats, "h2d_s", shard, batch, mesh)
                if not _put(item):
                    return
                k += 1
        except BaseException as e:  # surfaced in the consumer thread
            _put((_ERROR, e))
            return
        _put(_DONE)

    t = threading.Thread(target=worker, daemon=True,
                         name="ddp_tpu_prefetch")
    t.start()
    i = 0
    try:
        while True:
            # Timed by hand, recorded only for REAL batches: the get that
            # returns the end-of-stream/error sentinel is not a step's
            # data wait, and spanning it would invent a phantom step
            # numbered as the next epoch's first (add_span's reason).
            t0 = time.monotonic() if tracer.enabled else 0.0
            item = _timed(stats, "wait_s", q.get)
            if item is _DONE:
                return
            if isinstance(item, tuple) and len(item) == 2 \
                    and item[0] == _ERROR:
                raise item[1]
            if tracer.enabled:
                tracer.add_span("data_wait", t0, time.monotonic() - t0,
                                step=step0 + i)
            i += 1
            if stats is not None:
                stats.count_batch()
            yield item
    finally:
        stop.set()
        try:  # unblock a producer mid-put immediately
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        t.join(timeout=10.0)
