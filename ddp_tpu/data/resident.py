"""Device-resident dataset: the whole training set lives in HBM.

The reference streams every batch host->device (`.to(gpu_id)` per batch,
multigpu.py:105-106).  For CIFAR-10 that traffic is pointless on TPU: the
full uint8 training set is ~150 MB — under 1% of a chip's HBM — so we
upload it once, replicated over the mesh, and each step *gathers* its batch
by index on device (train/epoch.py).  Per-epoch host->device traffic drops
from ~150 MB of images to a ~200 KB int32 index matrix, and the input
pipeline stops existing as a bottleneck (SURVEY.md §7 hard-part #4).

Augmentation correspondingly moves on device (data/device_augment.py) —
the same RandomCrop+HFlip distribution as the host path (torchvision
transforms, singlegpu.py:154-160).

Sampler semantics are unchanged: the index matrix is produced by the same
``DistributedSampler``-exact host samplers (data/sampler.py), so device r
sees exactly rank r's reference data stream.
"""
from __future__ import annotations

import jax
import numpy as np

from jax.sharding import Mesh

from ..parallel.mesh import replicated_sharding
from .cifar10 import Dataset


class ResidentData:
    """``dataset.images``/``labels`` as replicated device arrays.

    uint8 images on device; the ToTensor u8/255 scaling happens inside the
    train step (train/step.py ``_as_input``), so HBM holds the dataset at
    1/4 fp32 size.  Multi-host: every process passes its (identical) host
    copy and the replicated global array is assembled process-locally.
    """

    def __init__(self, dataset: Dataset, mesh: Mesh):
        rep = replicated_sharding(mesh)
        images = np.ascontiguousarray(dataset.images)
        labels = np.ascontiguousarray(dataset.labels, dtype=np.int32)
        if jax.process_count() == 1:
            self.images = jax.device_put(images, rep)
            self.labels = jax.device_put(labels, rep)
        else:
            self.images = jax.make_array_from_process_local_data(rep, images)
            self.labels = jax.make_array_from_process_local_data(rep, labels)
