"""Device-resident dataset: the whole training set lives in HBM.

The reference streams every batch host->device (`.to(gpu_id)` per batch,
multigpu.py:105-106).  For CIFAR-10 that traffic is pointless on TPU: the
full uint8 training set is ~150 MB — under 1% of a chip's HBM — so we
upload it once, replicated over the mesh, and each step *gathers* its batch
by index on device (train/epoch.py).  Per-epoch host->device traffic drops
from ~150 MB of images to a ~200 KB int32 index matrix, and the input
pipeline stops existing as a bottleneck (SURVEY.md §7 hard-part #4).

Augmentation correspondingly moves on device (data/device_augment.py) —
the same RandomCrop+HFlip distribution as the host path (torchvision
transforms, singlegpu.py:154-160).

Sampler semantics are unchanged: the index matrix is produced by the same
``DistributedSampler``-exact host samplers (data/sampler.py), so device r
sees exactly rank r's reference data stream.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np

from jax.sharding import Mesh

from ..parallel.mesh import replicated_sharding
from .cifar10 import Dataset

# Fraction of a device's HBM the replicated dataset may occupy.  The rest
# is headroom for params/momentum/activations and XLA scratch — CIFAR-scale
# data (~150 MB vs ~16 GB HBM) never comes near it; the guard exists so a
# too-large dataset fails with instructions instead of a raw XLA OOM
# mid-upload (the reference's streaming loop, multigpu.py:104-107, has no
# such cliff and the superset must not add one).
HBM_BUDGET_FRACTION = 0.8


def _device_bytes_limit(device) -> Optional[int]:
    """Per-device memory capacity in bytes, or None when the backend does
    not report one (the CPU backend; tests monkeypatch this seam)."""
    try:
        stats = device.memory_stats()
    except Exception:  # backend without memory_stats support
        return None
    return (stats or {}).get("bytes_limit")


class ResidentData:
    """``dataset.images``/``labels`` as replicated device arrays.

    uint8 images on device; the ToTensor u8/255 scaling happens inside the
    train step (train/step.py ``_as_input``), so HBM holds the dataset at
    1/4 fp32 size.  Multi-host: every process passes its (identical) host
    copy and the replicated global array is assembled process-locally.

    Raises :class:`ValueError` before any upload when the dataset would not
    fit the per-device HBM budget — resident mode replicates the FULL
    dataset on every device, so capacity does not grow with the mesh; the
    streaming loader is the mode for datasets beyond HBM.
    """

    def __init__(self, dataset: Dataset, mesh: Mesh):
        rep = replicated_sharding(mesh)
        images = np.ascontiguousarray(dataset.images)
        labels = np.ascontiguousarray(dataset.labels, dtype=np.int32)
        # Probe an ADDRESSABLE device: under multi-host, mesh device 0
        # belongs to process 0 only, and a non-addressable device's
        # memory_stats raises.  The guard must make the SAME decision on
        # every process (a rank that raises while others proceed leaves
        # the others hanging in the assembly collective), so multi-host
        # runs agree on the global minimum limit — with "no limit
        # reported anywhere" disabling the guard everywhere.  (A process
        # owning NO mesh devices is unsupported throughout — it gets
        # assemble_from_local's explicit error below.)
        from ..parallel.mesh import local_replica_ids
        local = [mesh.devices.flat[i] for i in local_replica_ids(mesh)]
        limit = _device_bytes_limit(local[0]) if local else None
        if jax.process_count() > 1:
            # Mesh-based global min (NOT multihost_utils.process_allgather,
            # which assumes equal per-host device counts and breaks on
            # asymmetric topologies); "no limit reported" anywhere
            # disables the guard everywhere.
            from ..parallel.mesh import process_min_mib
            limit = process_min_mib(mesh, limit)
        needed = images.nbytes + labels.nbytes
        if limit is not None and needed > HBM_BUDGET_FRACTION * limit:
            raise ValueError(
                f"resident mode replicates the whole dataset into every "
                f"device's HBM, but this dataset is "
                f"{needed / 2**20:,.0f} MiB and the per-device budget is "
                f"{HBM_BUDGET_FRACTION * limit / 2**20:,.0f} MiB "
                f"({HBM_BUDGET_FRACTION:.0%} of {limit / 2**20:,.0f} MiB "
                f"HBM, the rest reserved for params/activations). "
                f"Drop --resident to stream batches from the host "
                f"(optionally with --device_augment), or shrink the "
                f"dataset.")
        if jax.process_count() == 1:
            self.images = jax.device_put(images, rep)
            self.labels = jax.device_put(labels, rep)
        else:
            # Explicit global shapes (= local: fully replicated), so the
            # upload works on asymmetric host->device topologies too.
            self.images = jax.make_array_from_process_local_data(
                rep, images, images.shape)
            self.labels = jax.make_array_from_process_local_data(
                rep, labels, labels.shape)
