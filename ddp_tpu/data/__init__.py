from .augment import random_crop_flip, to_float
from .cifar10 import Dataset, load, synthetic
from .loader import EvalLoader, TrainLoader
from .prefetch import PrefetchStats, prefetch_to_device
from .resident import ResidentData
from .sampler import DistributedShardSampler, ShuffleSampler

__all__ = [
    "Dataset", "DistributedShardSampler", "EvalLoader", "PrefetchStats",
    "ResidentData", "ShuffleSampler", "TrainLoader", "load",
    "prefetch_to_device", "random_crop_flip", "synthetic", "to_float",
]
