from .augment import random_crop_flip, to_float
from .cifar10 import Dataset, load, synthetic
from .loader import EvalLoader, TrainLoader
from .resident import ResidentData
from .sampler import DistributedShardSampler, ShuffleSampler

__all__ = [
    "Dataset", "DistributedShardSampler", "EvalLoader", "ResidentData",
    "ShuffleSampler", "TrainLoader", "load", "random_crop_flip", "synthetic",
    "to_float",
]
