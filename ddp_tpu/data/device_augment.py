"""On-device train-time augmentation (RandomCrop(32, pad 4) + HFlip).

TPU-first alternative to the host-side ``augment.py`` path: raw uint8
batches go over the host->device link and the crop/flip happens inside the
jitted train step.  At pod scale the host augmentation thread pool is the
classic input bottleneck (SURVEY.md §7 hard-part #4); on device the cost is
noise next to the convolutions.

The crop+flip is expressed as two one-hot MATMULS (row-select, then
col-select with the flip folded in) rather than a gather: XLA:TPU lowers
per-sample advanced-indexing gathers to a slow generic gather (~6 ms per
512 images on v5e), while the equivalent one-hot einsum rides the MXU at
~1 ms.  Out-of-range one-hot rows are all-zero, which supplies the
reference's zero padding (torchvision RandomCrop fill=0) for free.  The
selection is numerically exact (each output pixel is 1*value + 0*rest with
fp32 accumulation), so the result is cast back to the input dtype
losslessly.

Distributional parity with torchvision's transforms (singlegpu.py:154-160):
offsets uniform over [0, 8], flip probability 0.5, zero padding.  The
concrete RNG stream differs (JAX threefry vs torch Philox vs numpy PCG64) —
as with the samplers, only the distribution is load-bearing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..ops.gather import gather_rows

PAD = 4
SIZE = 32


def random_crop_flip(rng: jax.Array, imgs: jax.Array) -> jax.Array:
    """[N,32,32,3] (any dtype) -> same shape/dtype, cropped+flipped.

    Same RNG draws as :func:`gather_crop_flip` (which is exactly this after
    a batch gather), so the per-step and resident paths augment
    bit-identically on the same key."""
    return _crop_flip_onehot(rng, imgs)


def gather_crop_flip(rng: jax.Array, table: jax.Array,
                     idx_row: jax.Array) -> jax.Array:
    """Dataset-gather + RandomCrop(32, pad 4) + HFlip for the
    device-resident path (train/epoch.py).

    ``table`` is the whole resident dataset ``[M,32,32,3]``; the batch is
    pulled by the Pallas DMA row gather (ops/gather.py) and augmented by
    the one-hot matmuls below — together ~2 ms per 512 images on v5e
    against ~7.6 ms for the fused clamped-gather formulation this
    replaces."""
    return _crop_flip_onehot(rng, gather_rows(table, idx_row))


def _crop_flip_onehot(rng: jax.Array, imgs: jax.Array) -> jax.Array:
    """Crop+flip as two one-hot contractions; zero-fill via OOB one-hots."""
    n = imgs.shape[0]
    k_off, k_flip = jax.random.split(rng)
    ys, xs = jax.random.randint(k_off, (2, n), 0, 2 * PAD + 1)
    flip = jax.random.bernoulli(k_flip, 0.5, (n,))
    row = jnp.arange(SIZE)
    y_src = ys[:, None] + row[None, :] - PAD                 # [N, 32]
    x_cols = jnp.where(flip[:, None], SIZE - 1 - row[None, :],
                       row[None, :])
    x_src = xs[:, None] + x_cols - PAD                       # [N, 32]
    # one_hot yields an all-zero row for out-of-range sources == zero fill.
    ysel = jax.nn.one_hot(y_src, SIZE, dtype=jnp.float32)    # [N, 32, 32]
    xsel = jax.nn.one_hot(x_src, SIZE, dtype=jnp.float32)
    x = imgs.astype(jnp.float32)
    # uint8-origin values (<= 255) are exact in the MXU's bf16 multiplies;
    # arbitrary float images need full-precision passes to stay lossless.
    prec = ("highest" if jnp.issubdtype(imgs.dtype, jnp.floating) else None)
    y1 = jnp.einsum("nio,nohc->nihc", ysel, x, precision=prec)
    out = jnp.einsum("njw,niwc->nijc", xsel, y1, precision=prec)
    return out.astype(imgs.dtype)
