"""On-device train-time augmentation (RandomCrop(32, pad 4) + HFlip).

TPU-first alternative to the host-side ``augment.py`` path: raw uint8
batches go over the host->device link and the crop/flip happens inside the
jitted train step — per-image dynamic slices and a reversed ``where``, both
trivially fused by XLA.  At pod scale the host augmentation thread pool is
the classic input bottleneck (SURVEY.md §7 hard-part #4); on device the cost
is noise next to the convolutions.

Distributional parity with torchvision's transforms (singlegpu.py:154-160):
offsets uniform over [0, 8], flip probability 0.5, zero padding.  The
concrete RNG stream differs (JAX threefry vs torch Philox vs numpy PCG64) —
as with the samplers, only the distribution is load-bearing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

PAD = 4
SIZE = 32


def random_crop_flip(rng: jax.Array, imgs: jax.Array) -> jax.Array:
    """[N,32,32,3] (any dtype) -> same shape/dtype, cropped+flipped."""
    n = imgs.shape[0]
    k_off, k_flip = jax.random.split(rng)
    ys, xs = jax.random.randint(k_off, (2, n), 0, 2 * PAD + 1)
    flip = jax.random.bernoulli(k_flip, 0.5, (n,))
    padded = jnp.pad(imgs, ((0, 0), (PAD, PAD), (PAD, PAD), (0, 0)))

    def crop_one(img, y, x):
        return lax.dynamic_slice(img, (y, x, 0), (SIZE, SIZE, img.shape[-1]))

    out = jax.vmap(crop_one)(padded, ys, xs)
    return jnp.where(flip[:, None, None, None], out[:, :, ::-1, :], out)
