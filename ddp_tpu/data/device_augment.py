"""On-device train-time augmentation (RandomCrop(32, pad 4) + HFlip).

TPU-first alternative to the host-side ``augment.py`` path: raw uint8
batches go over the host->device link and the crop/flip happens inside the
jitted train step — per-image dynamic slices and a reversed ``where``, both
trivially fused by XLA.  At pod scale the host augmentation thread pool is
the classic input bottleneck (SURVEY.md §7 hard-part #4); on device the cost
is noise next to the convolutions.

Distributional parity with torchvision's transforms (singlegpu.py:154-160):
offsets uniform over [0, 8], flip probability 0.5, zero padding.  The
concrete RNG stream differs (JAX threefry vs torch Philox vs numpy PCG64) —
as with the samplers, only the distribution is load-bearing.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

PAD = 4
SIZE = 32


def random_crop_flip(rng: jax.Array, imgs: jax.Array) -> jax.Array:
    """[N,32,32,3] (any dtype) -> same shape/dtype, cropped+flipped.

    Exactly :func:`gather_crop_flip` with the identity index row — the
    delegation makes the per-step and resident paths bit-identical *by
    construction* (same RNG draws, same gather), not merely by test.
    """
    return gather_crop_flip(rng, imgs, jnp.arange(imgs.shape[0]))


def gather_crop_flip(rng: jax.Array, table: jax.Array,
                     idx_row: jax.Array) -> jax.Array:
    """Fused dataset-gather + RandomCrop(32, pad 4) + HFlip for the
    device-resident path (train/epoch.py).

    ``table`` is the whole resident dataset ``[M,32,32,3]``; the batch
    ``table[idx_row]``, its zero-padding, the crop, and the flip collapse
    into ONE gather with clamped source indices plus a validity mask (the
    mask multiply zeroes what the reference's zero-padding would have
    supplied).  No padded or pre-gathered intermediate ever materialises —
    a single batched gather is ~5x faster on TPU than the
    vmap-of-``dynamic_slice`` formulation (~10 ms per 512 images, enough
    to dominate the resident train step).
    """
    n = idx_row.shape[0]
    k_off, k_flip = jax.random.split(rng)
    ys, xs = jax.random.randint(k_off, (2, n), 0, 2 * PAD + 1)
    flip = jax.random.bernoulli(k_flip, 0.5, (n,))
    row = jnp.arange(SIZE)
    y_src = ys[:, None] + row[None, :] - PAD                 # [N, 32]
    x_cols = jnp.where(flip[:, None], SIZE - 1 - row[None, :],
                       row[None, :])
    x_src = xs[:, None] + x_cols - PAD                       # [N, 32]
    valid = (((y_src >= 0) & (y_src < SIZE))[:, :, None]
             & ((x_src >= 0) & (x_src < SIZE))[:, None, :])  # [N, 32, 32]
    yc = jnp.clip(y_src, 0, SIZE - 1)
    xc = jnp.clip(x_src, 0, SIZE - 1)
    out = table[idx_row[:, None, None], yc[:, :, None], xc[:, None, :], :]
    return out * valid[..., None].astype(out.dtype)
