"""Vectorised CIFAR train-time augmentation.

Reference transforms (singlegpu.py:154-160): RandomCrop(32, padding=4) +
RandomHorizontalFlip + ToTensor.  torchvision applies them per-sample in
Python; at batch 512 x N chips that becomes the input bottleneck the GPU
reference never noticed (SURVEY.md section 7 hard-part #4), so here the whole
batch is augmented with single vectorised numpy gathers on the host.
"""
from __future__ import annotations

import numpy as np

PAD = 4
SIZE = 32


def random_crop_flip(batch: np.ndarray, rng: np.random.Generator
                     ) -> np.ndarray:
    """[N,32,32,3] uint8 -> augmented [N,32,32,3] uint8.

    Zero-padding and uniform offsets match torchvision RandomCrop defaults
    (fill=0); flip probability 0.5.  All randomness is drawn here; the
    memory movement dispatches to the native C++ kernel (data/native.py)
    when available, else the vectorised numpy gather — both bit-identical
    on the same draws (tests/test_native.py).
    """
    n = batch.shape[0]
    ys = rng.integers(0, 2 * PAD + 1, n)
    xs = rng.integers(0, 2 * PAD + 1, n)
    flip = rng.random(n) < 0.5
    from . import native
    out = native.crop_flip(batch, ys, xs, flip)
    if out is not None:
        return out
    return _numpy_crop_flip(batch, ys, xs, flip)


def _numpy_crop_flip(batch: np.ndarray, ys: np.ndarray, xs: np.ndarray,
                     flip: np.ndarray) -> np.ndarray:
    """Pure-numpy reference implementation (one batched gather)."""
    n = batch.shape[0]
    padded = np.pad(batch, ((0, 0), (PAD, PAD), (PAD, PAD), (0, 0)))
    row = np.arange(SIZE)
    out = padded[np.arange(n)[:, None, None],
                 (ys[:, None] + row)[:, :, None],
                 (xs[:, None] + row)[:, None, :], :]
    out[flip] = out[flip, :, ::-1]
    return out


def to_float(batch_u8: np.ndarray) -> np.ndarray:
    """ToTensor scaling: uint8 [0,255] -> float32 [0,1].  The reference
    applies no mean/std normalisation (singlegpu.py:154-160)."""
    return batch_u8.astype(np.float32) / 255.0
