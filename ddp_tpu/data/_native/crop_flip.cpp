// Native host-side CIFAR augmentation: RandomCrop(32, pad 4) + HFlip.
//
// The reference's augmentation runs inside torchvision/PIL and the
// DataLoader's C++ worker pool (singlegpu.py:154-160, 174-180); this is the
// framework's native analogue for the host-fed streaming path.  Pure memory
// movement: Python (data/augment.py) draws the offsets/flips from its RNG
// and hands them over, so the native and numpy implementations are
// bit-identical on the same draws (tests/test_native.py).
//
// Layout: images are [N, 32, 32, 3] uint8, C-contiguous.  Crop offsets
// (ys[i], xs[i]) are in [0, 8] and index the zero-padded 40x40 frame; the
// output pixel (y, x) reads padded (ys+y, xs+x), i.e. source
// (ys+y-4, xs+x-4) with zero fill outside, then a horizontal flip reverses
// x order when flips[i] is set.
//
// Built on first use by data/native.py (g++ -O3 -shared -fopenmp); no
// Python.h dependency — plain C ABI via ctypes.
#include <cstdint>
#include <cstring>

namespace {
constexpr int kSize = 32;
constexpr int kPad = 4;
constexpr int kCh = 3;
constexpr int kRow = kSize * kCh;      // bytes per image row
constexpr int kImg = kSize * kRow;     // bytes per image
}  // namespace

extern "C" void crop_flip_u8(const uint8_t* in, uint8_t* out,
                             const int64_t* ys, const int64_t* xs,
                             const uint8_t* flips, int64_t n) {
#pragma omp parallel for schedule(static)
  for (int64_t i = 0; i < n; ++i) {
    const uint8_t* img = in + i * kImg;
    uint8_t* dst = out + i * kImg;
    const int y0 = static_cast<int>(ys[i]) - kPad;
    const int x0 = static_cast<int>(xs[i]) - kPad;
    const bool flip = flips[i] != 0;
    for (int y = 0; y < kSize; ++y) {
      uint8_t* drow = dst + y * kRow;
      const int sy = y + y0;
      if (sy < 0 || sy >= kSize) {
        std::memset(drow, 0, kRow);
        continue;
      }
      const uint8_t* srow = img + sy * kRow;
      // Valid source x range for this row: clip [x0, x0+32) to [0, 32).
      const int xlo = x0 < 0 ? -x0 : 0;            // first valid out-x
      const int xhi = x0 + kSize > kSize ? kSize - x0 : kSize;  // one past
      if (!flip) {
        if (xlo > 0) std::memset(drow, 0, xlo * kCh);
        if (xhi < kSize)
          std::memset(drow + xhi * kCh, 0, (kSize - xhi) * kCh);
        std::memcpy(drow + xlo * kCh, srow + (x0 + xlo) * kCh,
                    (xhi - xlo) * kCh);
      } else {
        // out x -> source (x0 + (31 - x)); write zero where out of range.
        for (int x = 0; x < kSize; ++x) {
          const int sx = x0 + (kSize - 1 - x);
          uint8_t* d = drow + x * kCh;
          if (sx < 0 || sx >= kSize) {
            d[0] = 0; d[1] = 0; d[2] = 0;
          } else {
            const uint8_t* s = srow + sx * kCh;
            d[0] = s[0]; d[1] = s[1]; d[2] = s[2];
          }
        }
      }
    }
  }
}
